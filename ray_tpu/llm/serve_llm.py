"""LLMServer — the serve deployment wrapping InferenceEngine.

Role-equivalent to the reference's LLMDeployment + OpenAI surface
(reference: llm/_internal/serve/deployments/llm/vllm/vllm_deployment.py;
configs/openai_api_models.py request/response schemas): requests arriving
on any of the replica's handler threads enqueue into the engine and block
on a per-request event; a single engine thread runs the continuous-
batching loop, so concurrent requests share decode batches.

Token streaming: ``stream()`` is a generator — under serve it runs as a
streaming actor method, every yielded token batch becomes consumable
before the request finishes, and the HTTP proxy turns it into SSE
(``/v1/completions`` with ``"stream": true``, the reference's OpenAI
contract).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.llm.engine import InferenceEngine
from ray_tpu.llm.tokenizer import ByteTokenizer
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util import log_plane, trace_context


def _ambient_trace_id() -> str:
    """The trace_id the serve router stamped on this request's wire
    frame (restored as ambient context by the worker runtime) — linked
    into the engine's flight-recorder record so `ray_tpu trace
    --request <rid>` can merge span tree + request timeline."""
    amb = trace_context.current()
    return amb[0] if amb else ""


class LLMServer:
    """Use via serve:  serve.deployment(max_ongoing_requests=16)(LLMServer)
    then .bind(cfg_kwargs...). Accepts {"prompt_ids": [...],
    "max_tokens": N} and returns {"token_ids": [...]}."""

    def __init__(self, model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None,
                 tokenizer=None, model_name: str = "rtpu-llm",
                 chat_template=None):
        cfg = LlamaConfig.tiny(**(model_config or {}))
        self.engine = InferenceEngine(cfg, **(engine_config or {}))
        self.engine.track_progress = True  # the serve loop drains it
        self.tokenizer = tokenizer or ByteTokenizer()
        self.model_name = model_name
        self.chat_template = chat_template or apply_chat_template
        self._results: Dict[str, List[int]] = {}
        self._events: Dict[str, threading.Event] = {}
        self._abandoned: set = set()
        # rid -> queue of incremental token lists (None = stream end);
        # fed by the engine thread, drained by stream() generators
        self._token_qs: Dict[str, "queue_mod.Queue"] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            if not self.engine.has_work():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            finished = self.engine.step()
            progress = self.engine.drain_progress()
            with self._lock:
                for rid, new_toks in progress.items():
                    q = self._token_qs.get(rid)
                    if q is not None and new_toks:
                        q.put(list(new_toks))
                for rid, toks in finished.items():
                    q = self._token_qs.get(rid)
                    if q is not None:
                        q.put(None)  # end of stream
                        continue
                    if rid in self._abandoned:
                        self._abandoned.discard(rid)
                        continue
                    self._results[rid] = toks
                    ev = self._events.get(rid)
                    if ev is not None:
                        ev.set()

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        prompt = self._prompt_ids(request)
        max_tokens = int(request.get("max_tokens", 32))
        ev = threading.Event()
        rid = self.engine.add_request(prompt, max_tokens,
                                      trace_id=_ambient_trace_id())
        # ambient request id: every log record emitted while this
        # request is in flight on this thread carries request_id=rid,
        # so `ray_tpu logs --request RID` finds it
        with log_plane.request_context(rid):
            log_plane.get_logger().info(
                f"llm request start ({len(prompt)} prompt tok, "
                f"max_new {max_tokens})")
            with self._lock:
                self._events[rid] = ev
                if rid in self._results:  # engine already finished it
                    ev.set()
            self._wake.set()
            if not ev.wait(timeout=300):
                # the engine will still finish the request eventually;
                # mark it abandoned so _loop drops the late result
                # instead of leaking it (and the event) forever
                with self._lock:
                    self._events.pop(rid, None)
                    self._abandoned.add(rid)
                log_plane.get_logger().warning("llm request timed out")
                raise TimeoutError(f"LLM request {rid} timed out")
            with self._lock:
                toks = self._results.pop(rid)
                self._events.pop(rid, None)
            log_plane.get_logger().info(
                f"llm request finished ({len(toks)} tok)")
        return {"token_ids": toks, "request_id": rid}

    # ------------------------------------------------------------ streaming

    def stream(self, request: Dict[str, Any]) -> Iterator[Dict[str, Any]]:
        """Generator: yields {"token_ids": [...]} batches as the engine
        produces them, then {"done": True, "token_ids": <all>}."""
        prompt = self._prompt_ids(request)
        max_tokens = int(request.get("max_tokens", 32))
        q: "queue_mod.Queue" = queue_mod.Queue()
        with self._lock:
            rid = self.engine.add_request(prompt, max_tokens,
                                          trace_id=_ambient_trace_id())
            self._token_qs[rid] = q
        # a generator can't hold the ambient contextvar across yields
        # without leaking it into the consumer, so stamp the lifecycle
        # records explicitly instead
        with log_plane.request_context(rid):
            log_plane.get_logger().info(
                f"llm stream start ({len(prompt)} prompt tok, "
                f"max_new {max_tokens})")
        self._wake.set()
        produced: List[int] = []
        completed = False
        try:
            while True:
                item = q.get(timeout=300)
                if item is None:
                    completed = True
                    break
                produced.extend(item)
                yield {"token_ids": item, "request_id": rid}
            with log_plane.request_context(rid):
                log_plane.get_logger().info(
                    f"llm stream finished ({len(produced)} tok)")
            yield {"done": True, "request_id": rid,
                   "token_ids": list(produced),
                   "finish_reason": self.engine.finish_reason(rid),
                   "cached_tokens": self.engine.cached_tokens(rid)}
        finally:
            with self._lock:
                self._token_qs.pop(rid, None)
                if not completed:
                    # consumer went away mid-stream (disconnect/close):
                    # the engine will still finish rid — mark abandoned so
                    # _loop drops the late result instead of parking it in
                    # _results forever, and drop any already-parked result
                    self._results.pop(rid, None)
                    self._abandoned.add(rid)

    def _prompt_ids(self, request: Dict[str, Any]) -> List[int]:
        if "prompt_ids" in request:
            return list(request["prompt_ids"])
        prompt = request.get("prompt")
        if isinstance(prompt, str):
            return self.tokenizer.encode(prompt)
        if isinstance(prompt, list):
            return list(prompt)
        raise ValueError("request needs 'prompt' (str) or 'prompt_ids'")

    # --------------------------------------------------------- OpenAI API

    def _completion_body(self, rid: str, token_ids: List[int],
                         n_prompt: int, finish_reason: str,
                         cached: int = 0) -> Dict[str, Any]:
        return {
            "id": f"cmpl-{rid}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": [{"index": 0,
                         "text": self.tokenizer.decode(token_ids),
                         "token_ids": list(token_ids),
                         "logprobs": None,
                         "finish_reason": finish_reason}],
            # prompt_tokens_details.cached_tokens: prompt tokens served
            # from the engine's prefix cache (OpenAI cached-tokens field)
            "usage": {"prompt_tokens": n_prompt,
                      "completion_tokens": len(token_ids),
                      "total_tokens": n_prompt + len(token_ids),
                      "prompt_tokens_details": {"cached_tokens": cached}},
        }

    def completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI-style /v1/completions, non-streaming (reference:
        llm/_internal/serve/configs/openai_api_models.py
        CompletionResponse)."""
        prompt = self._prompt_ids(request)
        out = self.__call__({"prompt_ids": prompt,
                             "max_tokens": request.get("max_tokens", 32)})
        return self._completion_body(
            out["request_id"], out["token_ids"], len(prompt),
            self.engine.finish_reason(out["request_id"]),
            self.engine.cached_tokens(out["request_id"]))

    def completions_stream(self, request: Dict[str, Any]
                           ) -> Iterator[Dict[str, Any]]:
        """OpenAI-style streaming chunks (SSE framing happens in the
        proxy); each chunk carries the newly-decoded text delta."""
        prompt = self._prompt_ids(request)
        rid = None
        for item in self.stream({"prompt_ids": prompt,
                                 "max_tokens":
                                     request.get("max_tokens", 32)}):
            rid = item["request_id"]
            if item.get("done"):
                chunk = self._completion_body(
                    rid, [], len(prompt),
                    item.get("finish_reason", "length"),
                    item.get("cached_tokens", 0))
                chunk["object"] = "text_completion.chunk"
                # the terminal chunk is where OpenAI clients read usage:
                # report the real completion count, not the empty delta
                n_out = len(item.get("token_ids", ()))
                chunk["usage"]["completion_tokens"] = n_out
                chunk["usage"]["total_tokens"] = len(prompt) + n_out
                yield chunk
                return
            chunk = self._completion_body(rid, item["token_ids"],
                                          len(prompt), None)
            chunk["object"] = "text_completion.chunk"
            chunk.pop("usage")
            yield chunk

    # ----------------------------------------------------- chat completions

    def _chat_prompt_ids(self, request: Dict[str, Any]) -> List[int]:
        messages = request.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ValueError("chat request needs a non-empty 'messages' "
                             "list")
        return self.tokenizer.encode(self.chat_template(messages))

    def _chat_body(self, rid: str, content: str, n_prompt: int,
                   n_out: int, finish_reason,
                   cached: int = 0) -> Dict[str, Any]:
        return {
            "id": f"chatcmpl-{rid}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": [{"index": 0,
                         "message": {"role": "assistant",
                                     "content": content},
                         "finish_reason": finish_reason}],
            "usage": {"prompt_tokens": n_prompt,
                      "completion_tokens": n_out,
                      "total_tokens": n_prompt + n_out,
                      "prompt_tokens_details": {"cached_tokens": cached}},
        }

    def chat_completions(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """OpenAI-style /v1/chat/completions, non-streaming: role-templated
        messages -> prompt, assistant message back (reference:
        llm/_internal/serve/configs/openai_api_models.py
        ChatCompletionRequest/Response)."""
        prompt = self._chat_prompt_ids(request)
        out = self.__call__({"prompt_ids": prompt,
                             "max_tokens": request.get("max_tokens", 32)})
        toks = out["token_ids"]
        return self._chat_body(
            out["request_id"], self.tokenizer.decode(toks), len(prompt),
            len(toks), self.engine.finish_reason(out["request_id"]),
            self.engine.cached_tokens(out["request_id"]))

    def chat_completions_stream(self, request: Dict[str, Any]
                                ) -> Iterator[Dict[str, Any]]:
        """OpenAI chat streaming chunks: first delta carries the role,
        then content deltas, then the terminal chunk with finish_reason +
        usage (SSE framing happens in the proxy)."""
        prompt = self._chat_prompt_ids(request)
        first = True
        for item in self.stream({"prompt_ids": prompt,
                                 "max_tokens":
                                     request.get("max_tokens", 32)}):
            rid = item["request_id"]
            if item.get("done"):
                chunk = self._chat_body(
                    rid, "", len(prompt), len(item.get("token_ids", ())),
                    item.get("finish_reason", "length"),
                    item.get("cached_tokens", 0))
                chunk["object"] = "chat.completion.chunk"
                chunk["choices"][0]["delta"] = {}
                del chunk["choices"][0]["message"]
                yield chunk
                return
            delta: Dict[str, Any] = {
                "content": self.tokenizer.decode(item["token_ids"])}
            if first:
                delta = {"role": "assistant", **delta}
                first = False
            chunk = self._chat_body(rid, "", len(prompt), 0, None)
            chunk["object"] = "chat.completion.chunk"
            chunk["choices"][0]["delta"] = delta
            del chunk["choices"][0]["message"]
            chunk.pop("usage")
            yield chunk

    def stats(self) -> Dict[str, Any]:
        out = dict(self.engine.stats)
        prefix = self.engine.prefix
        if prefix is not None:
            out["prefix_cache"] = {
                "lookups": prefix.lookups, "hits": prefix.hits,
                "hit_tokens": prefix.hit_tokens,
                "evictions": prefix.evictions,
                "cached_pages": prefix.num_cached,
                "evictable_pages": prefix.num_evictable,
            }
        return out

    def request_records(self) -> List[Dict[str, Any]]:
        """Flight-recorder snapshot of this replica's engine (wire
        dicts; [] when the recorder is disabled). The same records ship
        to the head over telemetry_push — this is the direct,
        replica-local view for tests and debugging."""
        if self.engine.request_log is None:
            return []
        return self.engine.request_log.snapshot()

    def set_overload_level(self, level: int,
                           budget_factor: float = 0.5) -> int:
        """Degradation ladder hook, invoked by the serve controller's SLO
        policy: level n runs the engine at step_token_budget *
        budget_factor**n — tighter prefill admission keeps decode TPOT
        alive for already-admitted requests at the cost of new-request
        TTFT. Level 0 restores the configured budget. Returns the
        effective budget (an unbounded base budget of 0 degrades from
        the config default so level>0 always tightens something)."""
        if not hasattr(self, "_base_token_budget"):
            self._base_token_budget = self.engine.step_token_budget
        level = max(0, int(level))
        if level == 0:
            self.engine.step_token_budget = self._base_token_budget
        else:
            from ray_tpu.core.config import GlobalConfig
            base = self._base_token_budget or \
                GlobalConfig.llm_step_token_budget or 2048
            self.engine.step_token_budget = max(
                64, int(base * (budget_factor ** level)))
        return self.engine.step_token_budget

    def check_health(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("engine thread died")


def apply_chat_template(messages: List[Dict[str, Any]]) -> str:
    """Default role templating (reference: the router templates chat
    messages through the model's tokenizer chat template; this framework's
    byte-level tokenizer uses an explicit llama-chat-style marker form —
    swap per model via LLMServer(chat_template=...))."""
    parts = []
    for m in messages:
        role = str(m.get("role", "user"))
        content = str(m.get("content", ""))
        parts.append(f"<|{role}|>\n{content}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)


# ---------------------------------------------------------------------------
# Placement derivation: parallel degrees -> gang bundles
# ---------------------------------------------------------------------------

def placement_for_engine(tp: int = 1, pp: int = 1,
                         chips_per_host: int = 8):
    """(bundles, strategy) derived from the engine's parallel degrees —
    the reference computes the same from TP×PP engine_kwargs (reference:
    llm/_internal/serve/deployments/llm/vllm/vllm_models.py:128-153).

    TPU mapping: a tp-group must sit on ICI, so a group that fits one
    host is ONE bundle of tp chips (STRICT_PACK — same host, adjacent
    chips); a group spanning hosts becomes one whole-host bundle per
    host, PACKed so the slice stays ICI-contiguous.
    """
    world = max(1, int(tp)) * max(1, int(pp))
    if world <= chips_per_host:
        return [{"TPU": float(world)}], "STRICT_PACK"
    if world % chips_per_host:
        raise ValueError(
            f"tp*pp={world} spans hosts but is not a multiple of "
            f"chips_per_host={chips_per_host}")
    n_hosts = world // chips_per_host
    return ([{"TPU": float(chips_per_host)}] * n_hosts), "PACK"


def build_llm_app(model_config: Optional[Dict[str, Any]] = None,
                  engine_config: Optional[Dict[str, Any]] = None, *,
                  name: str = "llm", num_replicas: int = 1,
                  max_ongoing_requests: int = 16,
                  runtime_env: Optional[Dict[str, Any]] = None,
                  use_tpu_resources: Optional[bool] = None,
                  model_name: str = "rtpu-llm"):
    """Bind an LLMServer deployment whose replica resources are DERIVED
    from the engine's tensor-parallel degree (reference: the LLM
    deployment's placement-group shorthand, vllm_models.py:128-153).

    tp > 1 replicas reserve a {"TPU": tp} gang on one host — the engine
    process drives all tp chips through one jax Mesh, so the gang and
    the mesh are the same object. ``use_tpu_resources=False`` (or
    leaving it None on a TPU-less test cluster... pass False) skips the
    chip reservation so CPU-mesh tests can deploy the sharded engine.

    A tp-group larger than one host's chips needs one engine process
    per host under ``jax.distributed`` — not served by this builder;
    ``placement_for_engine`` already computes the multi-host bundles
    for when the serve controller grows PG-backed replicas.
    """
    from ray_tpu import serve as serve_mod
    engine_config = dict(engine_config or {})
    tp = int(engine_config.get("tp", 1))
    ray_actor_options: Dict[str, Any] = {}
    if use_tpu_resources is None:
        use_tpu_resources = tp > 1
    if tp > 1 and use_tpu_resources:
        bundles, strategy = placement_for_engine(tp)
        if len(bundles) > 1:
            raise NotImplementedError(
                "tp groups spanning hosts need one engine process per "
                "host (jax.distributed); shard within one host's chips "
                "or raise chips_per_host")
        ray_actor_options["resources"] = bundles[0]
    if runtime_env:
        ray_actor_options["runtime_env"] = runtime_env
    dep = serve_mod.deployment(
        name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests,
        ray_actor_options=ray_actor_options or None)(LLMServer)
    return dep.bind(model_config, engine_config, None, model_name)
