"""LLMServer — the serve deployment wrapping InferenceEngine.

Role-equivalent to the reference's LLMDeployment (reference:
llm/_internal/serve/deployments/llm/vllm/vllm_deployment.py): requests
arriving on any of the replica's handler threads enqueue into the engine
and block on a per-request event; a single engine thread runs the
continuous-batching loop, so concurrent requests share decode batches.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.llm.engine import InferenceEngine
from ray_tpu.models.llama import LlamaConfig


class LLMServer:
    """Use via serve:  serve.deployment(max_ongoing_requests=16)(LLMServer)
    then .bind(cfg_kwargs...). Accepts {"prompt_ids": [...],
    "max_tokens": N} and returns {"token_ids": [...]}."""

    def __init__(self, model_config: Optional[Dict[str, Any]] = None,
                 engine_config: Optional[Dict[str, Any]] = None):
        cfg = LlamaConfig.tiny(**(model_config or {}))
        self.engine = InferenceEngine(cfg, **(engine_config or {}))
        self._results: Dict[str, List[int]] = {}
        self._events: Dict[str, threading.Event] = {}
        self._abandoned: set = set()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            if not self.engine.has_work():
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            finished = self.engine.step()
            if finished:
                with self._lock:
                    for rid, toks in finished.items():
                        if rid in self._abandoned:
                            self._abandoned.discard(rid)
                            continue
                        self._results[rid] = toks
                        ev = self._events.get(rid)
                        if ev is not None:
                            ev.set()

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        prompt = request["prompt_ids"]
        max_tokens = int(request.get("max_tokens", 32))
        ev = threading.Event()
        rid = self.engine.add_request(prompt, max_tokens)
        with self._lock:
            self._events[rid] = ev
            if rid in self._results:  # engine already finished it
                ev.set()
        self._wake.set()
        if not ev.wait(timeout=300):
            # the engine will still finish the request eventually; mark it
            # abandoned so _loop drops the late result instead of leaking
            # it (and the event) forever
            with self._lock:
                self._events.pop(rid, None)
                self._abandoned.add(rid)
            raise TimeoutError(f"LLM request {rid} timed out")
        with self._lock:
            toks = self._results.pop(rid)
            self._events.pop(rid, None)
        return {"token_ids": toks, "request_id": rid}

    def stats(self) -> Dict[str, Any]:
        return dict(self.engine.stats)

    def check_health(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("engine thread died")
