"""Build the native C++ runtime library (libray_tpu_native.so).

Invoked lazily on first import of ray_tpu.core._native (and by `make native`).
Rebuilds when any source is newer than the built .so.
"""

from __future__ import annotations

import os
import subprocess
import sys

_THIS_DIR = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(_THIS_DIR, "src")
LIB_PATH = os.path.join(_THIS_DIR, "libray_tpu_native.so")

SOURCES = [
    "shm_store.cc",
    "scheduler.cc",
    "transport.cc",
]

CXXFLAGS = [
    "-O2",
    "-g",
    "-std=c++17",
    "-fPIC",
    "-shared",
    "-Wall",
    "-pthread",
]


def needs_build() -> bool:
    if not os.path.exists(LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(SRC_DIR, s)) > lib_mtime for s in SOURCES
    )


def build(verbose: bool = False) -> str:
    if not needs_build():
        return LIB_PATH
    base_cmd = ["g++"] + CXXFLAGS + [os.path.join(SRC_DIR, s) for s in SOURCES]
    if verbose:
        sys.stderr.write(
            " ".join(base_cmd + ["-o", LIB_PATH, "-lrt"]) + "\n")
    # Serialize concurrent builds (several workers may import simultaneously).
    lockfile = LIB_PATH + ".lock"
    import fcntl

    with open(lockfile, "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            if needs_build():
                tmp = LIB_PATH + f".tmp.{os.getpid()}"
                subprocess.run(base_cmd + ["-o", tmp, "-lrt"], check=True)
                os.replace(tmp, LIB_PATH)
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)
    return LIB_PATH


if __name__ == "__main__":
    build(verbose=True)
    sys.stdout.write(LIB_PATH + "\n")
