// Shared-memory object store — the per-node data plane.
//
// Role-equivalent to the reference's plasma store (reference:
// src/ray/object_manager/plasma/store.h:55, object_lifecycle_manager.h,
// eviction_policy.h) but redesigned for the rebuild: instead of a store
// *server* process speaking a socket protocol with fd-passing
// (reference: plasma/fling.cc), every worker maps one named POSIX shm
// arena and operates on it directly through this library. Synchronization
// is a robust process-shared mutex in the arena header. This removes the
// socket round-trip from create/get entirely (the reference's hot path,
// store.h client protocol) while keeping the same semantics:
//   create -> seal -> get (zero-copy, pinned) -> release -> delete
//   LRU eviction of unpinned sealed objects when the arena is full
//   (reference: plasma/eviction_policy.h LRU policy).
//
// Layout:
//   [StoreHeader | ObjectEntry[slots] | data arena]
// Allocator: first-fit free list with block headers and coalescing
// (stand-in for the reference's dlmalloc-over-mmap, plasma/dlmalloc.cc).
// All intra-arena references are offsets, so mappings need not share a base
// address across processes.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5254505553544f52ULL;  // "RTPUSTOR"
constexpr uint32_t kIdSize = 28;
constexpr uint64_t kAlign = 64;

enum ObjState : uint32_t {
  kEmpty = 0,
  kCreating = 1,
  kSealed = 2,
};

// Error codes (mirrored in the Python binding).
enum {
  RTPU_OK = 0,
  RTPU_ERR_EXISTS = -1,
  RTPU_ERR_FULL = -2,
  RTPU_ERR_NOT_FOUND = -3,
  RTPU_ERR_NOT_SEALED = -4,
  RTPU_ERR_TABLE_FULL = -5,
  RTPU_ERR_SYS = -6,
  RTPU_ERR_PINNED = -7,
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint32_t pin_count;
  uint64_t data_offset;  // from arena base
  uint64_t data_size;
  int64_t lru_prev;  // slot index, -1 = none; only valid when sealed+unpinned
  int64_t lru_next;
  uint64_t seq;       // monotonically bumped on (re)use for ABA safety
  uint32_t creator_pid;
  uint32_t flags;     // bit0: delete_pending
};

struct FreeBlock {
  uint64_t size;       // payload size including this header
  uint64_t next;       // offset of next free block from data base, 0 = none
};

struct StoreHeader {
  uint64_t magic;
  uint64_t total_size;
  uint64_t slots;
  uint64_t data_capacity;
  uint64_t data_base;   // offset of arena from segment start
  uint64_t free_head;   // offset into data region, kNoBlock = none
  uint64_t bytes_used;
  uint64_t num_objects;
  int64_t lru_head;     // eviction candidates, head = oldest
  int64_t lru_tail;
  uint64_t lru_clock;
  // stats
  uint64_t total_created;
  uint64_t total_evicted;
  uint64_t total_deleted;
  uint64_t eviction_bytes;
  pthread_mutex_t mutex;
};

constexpr uint64_t kNoBlock = ~0ULL;

struct Store {
  void* base;
  uint64_t mapped_size;
  StoreHeader* hdr;
  ObjectEntry* table;
  uint8_t* data;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 28-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; state may be a torn create. Mark the
    // mutex consistent; torn kCreating entries are reaped lazily by delete.
    pthread_mutex_consistent(&s->hdr->mutex);
  }
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// ---- free-list allocator (first fit, coalescing on free) ----

uint64_t arena_alloc(Store* s, uint64_t size) {
  size = align_up(size);
  StoreHeader* h = s->hdr;
  uint64_t prev = kNoBlock;
  uint64_t cur = h->free_head;
  while (cur != kNoBlock) {
    FreeBlock* blk = reinterpret_cast<FreeBlock*>(s->data + cur);
    if (blk->size >= size) {
      uint64_t remainder = blk->size - size;
      if (remainder >= sizeof(FreeBlock) + kAlign) {
        // split: tail remains free
        uint64_t tail_off = cur + size;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(s->data + tail_off);
        tail->size = remainder;
        tail->next = blk->next;
        if (prev == kNoBlock) h->free_head = tail_off;
        else reinterpret_cast<FreeBlock*>(s->data + prev)->next = tail_off;
        h->bytes_used += size;
        return cur;
      } else {
        if (prev == kNoBlock) h->free_head = blk->next;
        else reinterpret_cast<FreeBlock*>(s->data + prev)->next = blk->next;
        h->bytes_used += blk->size;
        return cur;
      }
    }
    prev = cur;
    cur = blk->next;
  }
  return kNoBlock;
}

void arena_free(Store* s, uint64_t offset, uint64_t size) {
  size = align_up(size);
  StoreHeader* h = s->hdr;
  // insert sorted by offset, coalesce with neighbors
  uint64_t prev = kNoBlock;
  uint64_t cur = h->free_head;
  while (cur != kNoBlock && cur < offset) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(s->data + cur)->next;
  }
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(s->data + offset);
  blk->size = size;
  blk->next = cur;
  if (prev == kNoBlock) h->free_head = offset;
  else reinterpret_cast<FreeBlock*>(s->data + prev)->next = offset;
  h->bytes_used -= size;
  // coalesce forward
  if (cur != kNoBlock && offset + blk->size == cur) {
    FreeBlock* nxt = reinterpret_cast<FreeBlock*>(s->data + cur);
    blk->size += nxt->size;
    blk->next = nxt->next;
  }
  // coalesce backward
  if (prev != kNoBlock) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(s->data + prev);
    if (prev + pb->size == offset) {
      pb->size += blk->size;
      pb->next = blk->next;
    }
  }
}

// ---- object table: open addressing, linear probe ----

int64_t table_find(Store* s, const uint8_t* id) {
  uint64_t slots = s->hdr->slots;
  uint64_t idx = hash_id(id) % slots;
  for (uint64_t i = 0; i < slots; i++) {
    ObjectEntry* e = &s->table[(idx + i) % slots];
    if (e->state == kEmpty) {
      // Deleted entries keep a tombstone flag so probes continue.
      if (!(e->flags & 2)) return -1;
      continue;
    }
    if (memcmp(e->id, id, kIdSize) == 0) return (int64_t)((idx + i) % slots);
  }
  return -1;
}

int64_t table_insert_slot(Store* s, const uint8_t* id) {
  uint64_t slots = s->hdr->slots;
  uint64_t idx = hash_id(id) % slots;
  for (uint64_t i = 0; i < slots; i++) {
    ObjectEntry* e = &s->table[(idx + i) % slots];
    if (e->state == kEmpty) return (int64_t)((idx + i) % slots);
  }
  return -1;
}

// ---- LRU list of evictable (sealed, unpinned) objects ----

void lru_push_back(Store* s, int64_t slot) {
  StoreHeader* h = s->hdr;
  ObjectEntry* e = &s->table[slot];
  e->lru_prev = h->lru_tail;
  e->lru_next = -1;
  if (h->lru_tail >= 0) s->table[h->lru_tail].lru_next = slot;
  h->lru_tail = slot;
  if (h->lru_head < 0) h->lru_head = slot;
}

void lru_remove(Store* s, int64_t slot) {
  StoreHeader* h = s->hdr;
  ObjectEntry* e = &s->table[slot];
  if (e->lru_prev >= 0) s->table[e->lru_prev].lru_next = e->lru_next;
  else if (h->lru_head == slot) h->lru_head = e->lru_next;
  if (e->lru_next >= 0) s->table[e->lru_next].lru_prev = e->lru_prev;
  else if (h->lru_tail == slot) h->lru_tail = e->lru_prev;
  e->lru_prev = e->lru_next = -1;
}

void delete_entry_locked(Store* s, int64_t slot) {
  ObjectEntry* e = &s->table[slot];
  if (e->state == kSealed && e->pin_count == 0) lru_remove(s, slot);
  if (e->data_size > 0) arena_free(s, e->data_offset, e->data_size);
  e->state = kEmpty;
  e->flags = 2;  // tombstone
  e->pin_count = 0;
  s->hdr->num_objects--;
  s->hdr->total_deleted++;
}

// Evict LRU objects until `needed` bytes could plausibly be allocated.
// Returns true if anything was evicted.
bool evict_for(Store* s, uint64_t needed) {
  StoreHeader* h = s->hdr;
  bool any = false;
  while (h->lru_head >= 0) {
    // free list may already satisfy after coalescing; try cheap check
    uint64_t off = arena_alloc(s, needed);
    if (off != kNoBlock) {
      arena_free(s, off, needed);
      return any;
    }
    int64_t victim = h->lru_head;
    ObjectEntry* e = &s->table[victim];
    h->total_evicted++;
    h->eviction_bytes += e->data_size;
    delete_entry_locked(s, victim);
    any = true;
  }
  return any;
}

}  // namespace

extern "C" {

// Returns mapped handle or nullptr. total size derived from capacity+slots.
void* rtpu_store_create(const char* name, uint64_t capacity, uint64_t slots) {
  uint64_t table_bytes = align_up(slots * sizeof(ObjectEntry));
  uint64_t header_bytes = align_up(sizeof(StoreHeader));
  uint64_t total = header_bytes + table_bytes + capacity;
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  memset(hdr, 0, sizeof(StoreHeader));
  hdr->total_size = total;
  hdr->slots = slots;
  hdr->data_capacity = capacity;
  hdr->data_base = header_bytes + table_bytes;
  hdr->lru_head = hdr->lru_tail = -1;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  auto* store = new Store();
  store->base = base;
  store->mapped_size = total;
  store->hdr = hdr;
  store->table = reinterpret_cast<ObjectEntry*>(
      reinterpret_cast<uint8_t*>(base) + header_bytes);
  memset(store->table, 0, slots * sizeof(ObjectEntry));
  store->data = reinterpret_cast<uint8_t*>(base) + hdr->data_base;

  // one big free block
  FreeBlock* blk = reinterpret_cast<FreeBlock*>(store->data);
  blk->size = capacity;
  blk->next = kNoBlock;
  hdr->free_head = 0;
  hdr->magic = kMagic;  // publish last
  return store;
}

void* rtpu_store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  auto* hdr = reinterpret_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  auto* store = new Store();
  store->base = base;
  store->mapped_size = (uint64_t)st.st_size;
  store->hdr = hdr;
  store->table = reinterpret_cast<ObjectEntry*>(
      reinterpret_cast<uint8_t*>(base) + align_up(sizeof(StoreHeader)));
  store->data = reinterpret_cast<uint8_t*>(base) + hdr->data_base;
  return store;
}

void rtpu_store_close(void* handle) {
  auto* s = reinterpret_cast<Store*>(handle);
  munmap(s->base, s->mapped_size);
  delete s;
}

int rtpu_store_unlink(const char* name) { return shm_unlink(name); }

// Create an object buffer for zero-copy writing. On success *out_ptr points
// at `size` writable bytes. Object is invisible to get() until sealed.
int rtpu_store_create_object(void* handle, const uint8_t* id, uint64_t size,
                             void** out_ptr) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  if (table_find(s, id) >= 0) {
    unlock(s);
    return RTPU_ERR_EXISTS;
  }
  uint64_t alloc_size = size ? size : kAlign;
  uint64_t off = arena_alloc(s, alloc_size);
  if (off == kNoBlock) {
    evict_for(s, alloc_size);
    off = arena_alloc(s, alloc_size);
  }
  if (off == kNoBlock) {
    unlock(s);
    return RTPU_ERR_FULL;
  }
  int64_t slot = table_insert_slot(s, id);
  if (slot < 0) {
    arena_free(s, off, alloc_size);
    unlock(s);
    return RTPU_ERR_TABLE_FULL;
  }
  ObjectEntry* e = &s->table[slot];
  memcpy(e->id, id, kIdSize);
  e->state = kCreating;
  e->pin_count = 1;  // creator holds a pin until seal+release
  e->data_offset = off;
  e->data_size = alloc_size;
  e->lru_prev = e->lru_next = -1;
  e->seq++;
  e->creator_pid = (uint32_t)getpid();
  e->flags = 0;
  s->hdr->num_objects++;
  s->hdr->total_created++;
  *out_ptr = s->data + off;
  unlock(s);
  return RTPU_OK;
}

// Seal: object becomes immutable and visible. Keeps the creator pin.
int rtpu_store_seal(void* handle, const uint8_t* id) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int64_t slot = table_find(s, id);
  if (slot < 0) {
    unlock(s);
    return RTPU_ERR_NOT_FOUND;
  }
  ObjectEntry* e = &s->table[slot];
  if (e->state == kSealed) {
    unlock(s);
    return RTPU_OK;
  }
  e->state = kSealed;
  unlock(s);
  return RTPU_OK;
}

// Get a sealed object: pins it and returns a pointer + size. Zero-copy.
int rtpu_store_get(void* handle, const uint8_t* id, void** out_ptr,
                   uint64_t* out_size) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int64_t slot = table_find(s, id);
  if (slot < 0) {
    unlock(s);
    return RTPU_ERR_NOT_FOUND;
  }
  ObjectEntry* e = &s->table[slot];
  if (e->state != kSealed) {
    unlock(s);
    return RTPU_ERR_NOT_SEALED;
  }
  if (e->pin_count == 0) lru_remove(s, slot);
  e->pin_count++;
  *out_ptr = s->data + e->data_offset;
  *out_size = e->data_size;
  unlock(s);
  return RTPU_OK;
}

// Release one pin. When the last pin drops the object becomes evictable
// (joins LRU) — or is deleted immediately if delete_pending.
int rtpu_store_release(void* handle, const uint8_t* id) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int64_t slot = table_find(s, id);
  if (slot < 0) {
    unlock(s);
    return RTPU_ERR_NOT_FOUND;
  }
  ObjectEntry* e = &s->table[slot];
  if (e->pin_count > 0) e->pin_count--;
  if (e->pin_count == 0) {
    if (e->flags & 1) {
      delete_entry_locked(s, slot);
    } else if (e->state == kSealed) {
      s->hdr->lru_clock++;
      lru_push_back(s, slot);
    } else {
      // creator died mid-create; reclaim
      delete_entry_locked(s, slot);
    }
  }
  unlock(s);
  return RTPU_OK;
}

int rtpu_store_contains(void* handle, const uint8_t* id) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int64_t slot = table_find(s, id);
  int sealed = slot >= 0 && s->table[slot].state == kSealed;
  unlock(s);
  return sealed;
}

// Delete (or mark delete-pending if pinned).
int rtpu_store_delete(void* handle, const uint8_t* id) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  int64_t slot = table_find(s, id);
  if (slot < 0) {
    unlock(s);
    return RTPU_ERR_NOT_FOUND;
  }
  ObjectEntry* e = &s->table[slot];
  if (e->pin_count > 0) {
    e->flags |= 1;  // delete_pending
    unlock(s);
    return RTPU_ERR_PINNED;
  }
  delete_entry_locked(s, slot);
  unlock(s);
  return RTPU_OK;
}

struct StoreStats {
  uint64_t capacity;
  uint64_t bytes_used;
  uint64_t num_objects;
  uint64_t total_created;
  uint64_t total_evicted;
  uint64_t total_deleted;
  uint64_t eviction_bytes;
};

int rtpu_store_stats(void* handle, StoreStats* out) {
  auto* s = reinterpret_cast<Store*>(handle);
  lock(s);
  out->capacity = s->hdr->data_capacity;
  out->bytes_used = s->hdr->bytes_used;
  out->num_objects = s->hdr->num_objects;
  out->total_created = s->hdr->total_created;
  out->total_evicted = s->hdr->total_evicted;
  out->total_deleted = s->hdr->total_deleted;
  out->eviction_bytes = s->hdr->eviction_bytes;
  unlock(s);
  return RTPU_OK;
}

}  // extern "C"
