// transport.cc — native RPC transport: epoll loop driven by the caller.
//
// Role-equivalent to the reference's C++ rpc transport (reference:
// src/ray/rpc/grpc_server.h, grpc_client.h — event-loop IO off the Python
// interpreter). Design differences are deliberate: the framework keeps its
// 16-byte frame header (<QQ>: request id, payload length — identical to
// the pure-Python protocol.py framing, so native and Python transports
// interoperate on one cluster), and the event loop has NO internal thread:
// rt_poll() runs epoll_wait + socket reads + frame parsing inline on the
// calling (dispatcher) thread with the GIL released, returning a BATCH of
// parsed messages per call. A message therefore takes the same number of
// thread hops as a dedicated reader thread would — none extra — while all
// connections share one dispatcher and framing costs no interpreter time.
// (A first cut used an internal C++ loop thread + event queue; the extra
// wakeup per message measurably LOST to the threaded-Python transport on
// small hosts. This caller-driven design beats both.)
//
// Threading model:
//  - rt_send: caller threads append to a per-conn write queue under that
//    conn's mutex and attempt the writev inline (latency fast-path);
//    leftovers are flushed by the poller on EPOLLOUT. epoll_ctl is
//    thread-safe and takes effect during a concurrent epoll_wait, so
//    senders arm EPOLLOUT directly — no wakeup pipe needed for data.
//  - rt_poll: single consumer. Owns accepts, connect completion, reads,
//    queued-write flushes, and conn destruction (fd close happens only
//    here or under the conn mutex, so a send can never hit a reused fd).
//  - ops queue + eventfd: connect/close/stop requests from other threads
//    that must run on the poller.
//
// Flow control: per-conn write queues block the sender above
// RT_WQ_HIGH_BYTES (callers bind this GIL-released); inbound parsing is
// bounded per poll call by the caller's max_events window — unconsumed
// frames stay queued and reads pause above RT_INQ_HIGH_BYTES.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <pthread.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <memory>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t RT_MAX_FRAME = 1ull << 40;   // corruption guard (1 TiB)
constexpr size_t RT_WQ_HIGH_BYTES = 256ull << 20;  // sender blocks above
constexpr size_t RT_WQ_LOW_BYTES = 128ull << 20;   // ...until below this
constexpr size_t RT_INQ_HIGH_BYTES = 512ull << 20; // pause reads above
constexpr size_t RT_INQ_LOW_BYTES = 256ull << 20;  // resume below
constexpr int RT_IOV_BATCH = 64;

enum EvType : uint8_t { EV_MSG = 1, EV_ACCEPT = 2, EV_DISCONNECT = 3 };

// Fast-path frames: request id carries RT_FAST_BIT and the payload is the
// binary KV protocol below — handled entirely inside the loop (no Python,
// no pickle, no GIL). This is the head's native kv/ping service (role of
// the reference's GcsInternalKVManager, src/ray/gcs/gcs_server/
// gcs_kv_manager.h — a C++ KV the Python layer also reads directly).
//   request:  u8 op | u8 flags | u32 klen | u64 vlen | key | val
//   reply:    u8 status | u64 vlen | val
constexpr uint64_t RT_FAST_BIT = 1ull << 62;
constexpr uint64_t RT_REPLY_BIT = 1ull << 63;

enum FastOp : uint8_t {
  FOP_PUT = 1,        // flags bit0 = overwrite; status = 1 if newly created
  FOP_GET = 2,        // status = 1 hit (val follows), 0 miss
  FOP_DEL = 3,        // status = 1 if the key existed
  FOP_PING = 4,       // status = 1, val = u64 incarnation
  FOP_LEASE_ACQ = 5,  // key = u64 shape sig; status 1 + grant blob, 0 miss
  FOP_LEASE_REL = 6,  // key = u64 lease key; status 1 re-pooled, 0 unknown
};

// Native lease grant pool (role of the reference raylet's worker-lease
// grant loop, src/ray/raylet/node_manager.cc:1908 HandleRequestWorkerLease
// — redesigned: Python placement policy pre-stocks fully-formed grants per
// resource-shape signature; acquire/release in the steady state are served
// entirely inside this event loop, no Python, no pickle, no GIL).
struct FastLease {
  struct Held {
    uint64_t conn_id;
    uint64_t sig;
    std::string grant;
  };
  // sig -> FIFO of (lease_key, grant blob) ready to hand out
  std::unordered_map<uint64_t,
                     std::deque<std::pair<uint64_t, std::string>>> pools;
  // lease_key -> holder (reclaimed by Python on conn disconnect)
  std::unordered_map<uint64_t, Held> held;
  uint64_t hits = 0, misses = 0, releases = 0;
};

struct FastKV {
  std::mutex mu;  // guards kv AND lease (one lock: ops touch one or other)
  std::unordered_map<std::string, std::string> kv;
  FastLease lease;
  uint64_t incarnation = 0;
  std::atomic<uint64_t> version{0};  // bumped on mutation (persist-dirty)
};

struct rt_event {
  uint8_t type;
  uint64_t conn_id;
  uint64_t req_id;   // MSG: request id; ACCEPT: listener id
  uint64_t len;
  const char* data;  // valid until the next rt_poll on this loop
};

struct Buf {
  char* data;
  size_t len;
  size_t off;  // bytes already written
};

struct Conn {
  uint64_t id = 0;
  int fd = -1;
  bool connecting = false;  // nonblocking connect in flight
  std::atomic<bool> closed{false};
  std::shared_ptr<FastKV> fastkv;  // set at accept if the listener has one

  // ---- write side + epoll mask (guarded by mu) ----
  std::mutex mu;
  std::condition_variable wcv;  // backpressure wakeup
  std::deque<Buf> wq;
  size_t wq_bytes = 0;
  bool registered = false;   // fd added to epoll
  bool read_paused = false;     // inbound event-queue flow control
  bool read_paused_wq = false;  // outbound (reply) backlog flow control
  uint32_t cur_mask = 0;
  uint64_t last_send_ns = 0;  // burst detection for write coalescing

  // ---- read state (poller only) ----
  char hdr[16];
  size_t hdr_got = 0;
  char* body = nullptr;
  uint64_t body_len = 0;
  uint64_t body_got = 0;
  uint64_t cur_req = 0;

  ~Conn() { free(body); }
};

struct Listener {
  uint64_t id = 0;
  int fd = -1;
  int port = 0;
  std::shared_ptr<FastKV> fastkv;  // non-null once rt_fastpath_enable ran
};

struct Op {
  enum Kind { CLOSE, STOP } kind;
  uint64_t id = 0;
};

struct Event {
  uint8_t type;
  uint64_t conn_id;
  uint64_t req_id;
  char* data;
  uint64_t len;
};

struct Loop {
  int epfd = -1;
  int evfd = -1;
  std::atomic<bool> stopping{false};

  std::mutex mu;  // conns/listeners maps + op queue + id alloc
  std::unordered_map<uint64_t, std::shared_ptr<Conn>> conns;
  std::unordered_map<uint64_t, std::shared_ptr<Listener>> listeners;
  std::deque<Op> ops;
  uint64_t next_id = 1;

  // poller-owned: parsed-but-undelivered events + last batch handed out
  std::deque<Event> q;
  size_t q_bytes = 0;
  bool reads_paused = false;
  std::vector<Event> delivered;
  std::atomic<unsigned long> poller_tid{0};  // last thread inside rt_poll

  void wake() {
    uint64_t one = 1;
    ssize_t r = write(evfd, &one, 8);
    (void)r;
  }
};

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// rt_send's latency fast path (inline writev when the conn is quiet) can
// be disabled to force poller-side batched flushing — A/B knob for hosts
// where the sender-side syscall + poller mutex contention costs more than
// the wakeup it saves (RTPU_SEND_INLINE=0).
bool inline_send_enabled() {
  static const bool on = [] {
    const char* v = getenv("RTPU_SEND_INLINE");
    return v == nullptr || v[0] != '0';
  }();
  return on;
}

char* dup_bytes(const char* p, size_t n) {
  char* out = static_cast<char*>(malloc(n ? n : 1));
  if (n) memcpy(out, p, n);
  return out;
}

// epoll mask from canonical conn state; call with c->mu held
void sync_mask(Loop* L, Conn* c) {
  if (c->fd < 0 || !c->registered || c->closed.load()) return;
  uint32_t mask = 0;
  if (!c->read_paused && !c->read_paused_wq) mask |= EPOLLIN;
  if (c->connecting || !c->wq.empty()) mask |= EPOLLOUT;
  if (mask == c->cur_mask) return;
  epoll_event ev{};
  ev.data.u64 = c->id;
  ev.events = mask;
  if (epoll_ctl(L->epfd, EPOLL_CTL_MOD, c->fd, &ev) == 0) c->cur_mask = mask;
}

// poller only. Closes the fd under c->mu so a concurrent inline send can
// never write to a reused fd number.
void destroy_conn(Loop* L, std::shared_ptr<Conn> c, const char* reason,
                  bool emit_event) {
  if (c->closed.exchange(true)) return;
  {
    std::lock_guard<std::mutex> g(c->mu);
    if (c->fd >= 0) {
      epoll_ctl(L->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
      close(c->fd);
      c->fd = -1;
    }
    for (auto& b : c->wq) free(b.data);
    c->wq.clear();
    c->wq_bytes = 0;
    c->wcv.notify_all();
  }
  {
    std::lock_guard<std::mutex> g(L->mu);
    L->conns.erase(c->id);
  }
  if (emit_event) {
    size_t n = strlen(reason);
    L->q.push_back(Event{EV_DISCONNECT, c->id, 0, dup_bytes(reason, n),
                         static_cast<uint64_t>(n)});
    L->q_bytes += n;
  }
}

// flush queued writes; returns false on fatal socket error
bool flush_writes(Loop* L, Conn* c) {
  std::unique_lock<std::mutex> g(c->mu);
  while (!c->wq.empty()) {
    iovec iov[RT_IOV_BATCH];
    int n = 0;
    for (auto it = c->wq.begin(); it != c->wq.end() && n < RT_IOV_BATCH;
         ++it, ++n) {
      iov[n].iov_base = it->data + it->off;
      iov[n].iov_len = it->len - it->off;
    }
    ssize_t w = writev(c->fd, iov, n);
    if (w < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      if (errno == EINTR) continue;
      return false;
    }
    size_t left = static_cast<size_t>(w);
    c->wq_bytes -= left;
    while (left > 0 && !c->wq.empty()) {
      Buf& b = c->wq.front();
      size_t avail = b.len - b.off;
      if (left >= avail) {
        left -= avail;
        free(b.data);
        c->wq.pop_front();
      } else {
        b.off += left;
        left = 0;
      }
    }
    if (c->wq_bytes < RT_WQ_LOW_BYTES) c->wcv.notify_all();
  }
  // reply backlog drained: resume reading requests from this peer
  if (c->read_paused_wq && c->wq_bytes < RT_WQ_LOW_BYTES) {
    c->read_paused_wq = false;
  }
  sync_mask(L, c);
  return true;
}

// queue one frame on a conn and kick the write path (poller or any thread;
// no backpressure wait — used for fast-path replies). Burst-coalescing
// applies as in rt_send.
void enqueue_frame(Loop* L, Conn* c, uint64_t req_id, const char* data,
                   uint64_t len) {
  char* buf = static_cast<char*>(malloc(16 + len));
  memcpy(buf, &req_id, 8);
  memcpy(buf + 8, &len, 8);
  if (len) memcpy(buf + 16, data, len);
  std::lock_guard<std::mutex> g(c->mu);
  if (c->closed.load()) {
    free(buf);
    return;
  }
  bool was_empty = c->wq.empty();
  c->wq.push_back(Buf{buf, 16 + static_cast<size_t>(len), 0});
  c->wq_bytes += 16 + len;
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t now_ns =
      static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
  bool bursting = now_ns - c->last_send_ns < 200000;
  c->last_send_ns = now_ns;
  if (was_empty && !bursting && !c->connecting && c->fd >= 0) {
    iovec iov{buf, 16 + static_cast<size_t>(len)};
    ssize_t w = writev(c->fd, &iov, 1);
    if (w > 0) {
      size_t sw = static_cast<size_t>(w);
      c->wq_bytes -= sw;
      if (sw == iov.iov_len) {
        free(buf);
        c->wq.pop_front();
      } else {
        c->wq.front().off = sw;
      }
    }
  }
  sync_mask(L, c);
}

// serve one fast-path KV frame inline on the poller; consumes (frees) body
void handle_fast(Loop* L, Conn* c, uint64_t req_id, char* body,
                 uint64_t blen) {
  uint8_t status = 0;
  std::string out;
  if (blen >= 14) {
    uint8_t op = static_cast<uint8_t>(body[0]);
    uint8_t flags = static_cast<uint8_t>(body[1]);
    uint32_t klen;
    uint64_t vlen;
    memcpy(&klen, body + 2, 4);
    memcpy(&vlen, body + 6, 8);
    // overflow-safe bounds: klen/vlen are attacker-controlled; summing
    // them can wrap and a wrapped check would std::length_error (and
    // terminate) on the string constructors below
    if (klen <= blen - 14 && vlen <= blen - 14 - klen) {
      const char* key = body + 14;
      const char* val = body + 14 + klen;
      FastKV* kv = c->fastkv.get();
      std::lock_guard<std::mutex> g(kv->mu);
      switch (op) {
        case FOP_PUT: {
          auto it = kv->kv.find(std::string(key, klen));
          bool exists = it != kv->kv.end();
          if ((flags & 1) || !exists) {
            kv->kv[std::string(key, klen)] = std::string(val, vlen);
            kv->version.fetch_add(1);
          }
          status = exists ? 0 : 1;
          break;
        }
        case FOP_GET: {
          auto it = kv->kv.find(std::string(key, klen));
          if (it != kv->kv.end()) {
            status = 1;
            out = it->second;
          }
          break;
        }
        case FOP_DEL: {
          status = kv->kv.erase(std::string(key, klen)) ? 1 : 0;
          if (status) kv->version.fetch_add(1);
          break;
        }
        case FOP_PING: {
          status = 1;
          out.assign(reinterpret_cast<const char*>(&kv->incarnation), 8);
          break;
        }
        case FOP_LEASE_ACQ: {
          if (klen == 8) {
            uint64_t sig;
            memcpy(&sig, key, 8);
            FastLease& fl = kv->lease;
            auto pit = fl.pools.find(sig);
            if (pit != fl.pools.end() && !pit->second.empty()) {
              auto& front = pit->second.front();
              uint64_t lkey = front.first;
              out = std::move(front.second);
              pit->second.pop_front();
              fl.held[lkey] = FastLease::Held{c->id, sig, out};
              fl.hits++;
              status = 1;
            } else {
              fl.misses++;
            }
          }
          break;
        }
        case FOP_LEASE_REL: {
          if (klen == 8) {
            uint64_t lkey;
            memcpy(&lkey, key, 8);
            FastLease& fl = kv->lease;
            auto hit = fl.held.find(lkey);
            // only the holding connection may re-pool: a stale or
            // malicious release from another conn (e.g. a retried
            // release racing a reconnect that re-acquired the key)
            // would hand the same grant to two workers. status 0 sends
            // the caller down the Python release_lease fallback, which
            // validates ownership under the head lock.
            if (hit != fl.held.end() && hit->second.conn_id == c->id) {
              fl.pools[hit->second.sig].emplace_back(
                  lkey, std::move(hit->second.grant));
              fl.held.erase(hit);
              fl.releases++;
              status = 1;
            }
          }
          break;
        }
        default:
          break;
      }
    }
  }
  free(body);
  std::string reply;
  reply.resize(9 + out.size());
  reply[0] = static_cast<char>(status);
  uint64_t vlen = out.size();
  memcpy(&reply[1], &vlen, 8);
  if (!out.empty()) memcpy(&reply[9], out.data(), out.size());
  enqueue_frame(L, c, req_id | RT_REPLY_BIT, reply.data(), reply.size());
}

// route one completed inbound frame: fast-path KV inline, else event queue
void deliver_frame(Loop* L, Conn* c) {
  if ((c->cur_req & RT_FAST_BIT) && c->fastkv &&
      !(c->cur_req & RT_REPLY_BIT)) {
    handle_fast(L, c, c->cur_req, c->body, c->body_len);
    // fast replies bypass the event queue, so the inbound q_bytes pause
    // never fires for them — bound the REPLY backlog instead: stop
    // reading a peer that streams requests faster than it drains replies
    // (resumed by flush_writes once wq falls below the low-water mark)
    if (c->wq_bytes > RT_WQ_HIGH_BYTES) {
      std::lock_guard<std::mutex> g(c->mu);
      c->read_paused_wq = true;
      sync_mask(L, c);
    }
  } else {
    L->q.push_back(Event{EV_MSG, c->id, c->cur_req, c->body, c->body_len});
    L->q_bytes += c->body_len;
  }
  c->body = nullptr;
  c->hdr_got = 0;
}

// read everything available; append MSG events. Returns false when the
// conn died (peer closed or protocol violation).
bool drain_reads(Loop* L, Conn* c) {
  char buf[256 * 1024];
  for (;;) {
    // fast path: read large bodies straight into their destination buffer
    if (c->hdr_got == 16 && c->body_len - c->body_got >= sizeof(buf)) {
      ssize_t r =
          read(c->fd, c->body + c->body_got, c->body_len - c->body_got);
      if (r == 0) return false;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
        if (errno == EINTR) continue;
        return false;
      }
      c->body_got += static_cast<uint64_t>(r);
    } else {
      ssize_t r = read(c->fd, buf, sizeof(buf));
      if (r == 0) return false;
      if (r < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
        if (errno == EINTR) continue;
        return false;
      }
      size_t off = 0;
      size_t got = static_cast<size_t>(r);
      while (off < got) {
        if (c->hdr_got < 16) {
          size_t take = std::min(16 - c->hdr_got, got - off);
          memcpy(c->hdr + c->hdr_got, buf + off, take);
          c->hdr_got += take;
          off += take;
          if (c->hdr_got < 16) break;  // need more header bytes
          memcpy(&c->cur_req, c->hdr, 8);
          memcpy(&c->body_len, c->hdr + 8, 8);
          if (c->body_len > RT_MAX_FRAME) return false;  // desynced stream
          c->body = static_cast<char*>(malloc(c->body_len ? c->body_len : 1));
          if (c->body == nullptr) return false;  // treat like corruption:
          c->body_got = 0;                       // kill the conn, not us
        }
        size_t take =
            std::min<uint64_t>(c->body_len - c->body_got, got - off);
        memcpy(c->body + c->body_got, buf + off, take);
        c->body_got += take;
        off += take;
        if (c->body_got == c->body_len) {
          deliver_frame(L, c);
        }
      }
    }
    if (c->hdr_got == 16 && c->body != nullptr && c->body_got == c->body_len) {
      deliver_frame(L, c);
    }
    if (L->q_bytes > RT_INQ_HIGH_BYTES) {
      // inbound pressure: stop reading this conn; resumed once the caller
      // drains the parsed queue below the low-water mark
      std::lock_guard<std::mutex> g(c->mu);
      c->read_paused = true;
      L->reads_paused = true;
      sync_mask(L, c);
      return true;
    }
  }
}

void handle_accept(Loop* L, Listener* lst) {
  for (;;) {
    sockaddr_storage ss{};
    socklen_t sl = sizeof(ss);
    int fd = accept4(lst->fd, reinterpret_cast<sockaddr*>(&ss), &sl,
                     SOCK_NONBLOCK | SOCK_CLOEXEC);
    if (fd < 0) return;
    set_nodelay(fd);
    auto c = std::make_shared<Conn>();
    c->fd = fd;
    c->fastkv = lst->fastkv;
    {
      std::lock_guard<std::mutex> g(L->mu);
      c->id = L->next_id++;
      L->conns[c->id] = c;
    }
    {
      std::lock_guard<std::mutex> g(c->mu);
      epoll_event ev{};
      ev.data.u64 = c->id;
      ev.events = EPOLLIN;
      epoll_ctl(L->epfd, EPOLL_CTL_ADD, fd, &ev);
      c->registered = true;
      c->cur_mask = EPOLLIN;
    }
    char peer[64] = "?";
    if (ss.ss_family == AF_INET) {
      auto* in = reinterpret_cast<sockaddr_in*>(&ss);
      char ip[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &in->sin_addr, ip, sizeof(ip));
      snprintf(peer, sizeof(peer), "%s:%d", ip, ntohs(in->sin_port));
    }
    size_t n = strlen(peer);
    L->q.push_back(Event{EV_ACCEPT, c->id, lst->id, dup_bytes(peer, n),
                         static_cast<uint64_t>(n)});
    L->q_bytes += n;
  }
}

void process_ops(Loop* L) {
  std::deque<Op> ops;
  {
    std::lock_guard<std::mutex> g(L->mu);
    ops.swap(L->ops);
  }
  for (auto& op : ops) {
    if (op.kind == Op::STOP) {
      L->stopping.store(true);
      continue;
    }
    std::shared_ptr<Conn> c;
    {
      std::lock_guard<std::mutex> g(L->mu);
      auto it = L->conns.find(op.id);
      if (it != L->conns.end()) c = it->second;
    }
    if (op.kind == Op::CLOSE && c) {
      destroy_conn(L, c, "closed locally", false);
    }
  }
}

// one epoll pass; parses frames into L->q
void poll_io(Loop* L, int timeout_ms) {
  epoll_event evs[128];
  int n = epoll_wait(L->epfd, evs, 128, timeout_ms);
  if (n <= 0) return;
  for (int i = 0; i < n; i++) {
    uint64_t id = evs[i].data.u64;
    if (id == 0) {  // eventfd: ops pending
      uint64_t junk;
      ssize_t r = read(L->evfd, &junk, 8);
      (void)r;
      process_ops(L);
      continue;
    }
    std::shared_ptr<Conn> c;
    std::shared_ptr<Listener> lst;
    {
      std::lock_guard<std::mutex> g(L->mu);
      auto it = L->conns.find(id);
      if (it != L->conns.end()) {
        c = it->second;
      } else {
        auto lit = L->listeners.find(id);
        if (lit != L->listeners.end()) lst = lit->second;
      }
    }
    if (lst) {
      handle_accept(L, lst.get());
      continue;
    }
    if (!c || c->closed.load()) continue;
    uint32_t flags = evs[i].events;
    if (flags & EPOLLERR) {
      destroy_conn(L, c,
                   c->connecting ? "connection refused" : "socket error",
                   true);
      continue;
    }
    if (flags & EPOLLOUT) {
      if (c->connecting) {
        int err = 0;
        socklen_t el = sizeof(err);
        getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &el);
        if (err != 0) {
          destroy_conn(L, c, "connection refused", true);
          continue;
        }
        c->connecting = false;
        {
          std::lock_guard<std::mutex> g(c->mu);
          sync_mask(L, c.get());
        }
      }
      if (!flush_writes(L, c.get())) {
        destroy_conn(L, c, "write failed: peer gone", true);
        continue;
      }
    }
    if (flags & (EPOLLIN | EPOLLHUP)) {
      if (!drain_reads(L, c.get())) {
        destroy_conn(L, c, "peer closed", true);
        continue;
      }
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI

extern "C" {

void* rt_loop_new(void) {
  auto* L = new Loop();
  L->epfd = epoll_create1(EPOLL_CLOEXEC);
  L->evfd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  epoll_event ev{};
  ev.data.u64 = 0;  // id 0 reserved for the eventfd
  ev.events = EPOLLIN;
  epoll_ctl(L->epfd, EPOLL_CTL_ADD, L->evfd, &ev);
  return L;
}

void rt_loop_free(void* loop) {
  auto* L = static_cast<Loop*>(loop);
  L->stopping.store(true);
  std::lock_guard<std::mutex> g(L->mu);
  for (auto& kv : L->conns) {
    bool was_closed = kv.second->closed.exchange(true);
    std::lock_guard<std::mutex> wg(kv.second->mu);
    if (!was_closed && kv.second->fd >= 0) close(kv.second->fd);
    kv.second->fd = -1;
    for (auto& b : kv.second->wq) free(b.data);
    kv.second->wq.clear();
    kv.second->wcv.notify_all();
  }
  for (auto& kv : L->listeners) close(kv.second->fd);
  for (auto& e : L->delivered) free(e.data);
  for (auto& e : L->q) free(e.data);
  close(L->epfd);
  close(L->evfd);
  // L itself leaks deliberately: another thread may still be inside an
  // rt_send that looked the loop up; process teardown reclaims it
}

// returns listener id (>0) or 0 on failure
uint64_t rt_listen(void* loop, const char* host, int port) {
  auto* L = static_cast<Loop*>(loop);
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) return 0;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 1024) != 0) {
    close(fd);
    return 0;
  }
  sockaddr_in got{};
  socklen_t gl = sizeof(got);
  getsockname(fd, reinterpret_cast<sockaddr*>(&got), &gl);
  auto lst = std::make_shared<Listener>();
  lst->fd = fd;
  lst->port = ntohs(got.sin_port);
  uint64_t id;
  {
    std::lock_guard<std::mutex> g(L->mu);
    id = L->next_id++;
    lst->id = id;
    L->listeners[id] = lst;
  }
  epoll_event ev{};
  ev.data.u64 = id;
  ev.events = EPOLLIN;
  epoll_ctl(L->epfd, EPOLL_CTL_ADD, fd, &ev);
  return id;
}

int rt_listen_port(void* loop, uint64_t listener_id) {
  auto* L = static_cast<Loop*>(loop);
  std::lock_guard<std::mutex> g(L->mu);
  auto it = L->listeners.find(listener_id);
  return it == L->listeners.end() ? -1 : it->second->port;
}

// ---- fast-path KV (native head kv/ping service + direct host access) ----

static std::shared_ptr<FastKV> find_fastkv(Loop* L, uint64_t listener_id) {
  std::lock_guard<std::mutex> g(L->mu);
  auto it = L->listeners.find(listener_id);
  return it == L->listeners.end() ? nullptr : it->second->fastkv;
}

int rt_fastpath_enable(void* loop, uint64_t listener_id,
                       uint64_t incarnation) {
  auto* L = static_cast<Loop*>(loop);
  std::lock_guard<std::mutex> g(L->mu);
  auto it = L->listeners.find(listener_id);
  if (it == L->listeners.end()) return -1;
  if (!it->second->fastkv) it->second->fastkv = std::make_shared<FastKV>();
  it->second->fastkv->incarnation = incarnation;
  return 0;
  // NOTE: conns accepted BEFORE enable keep a null fastkv and route fast
  // frames to Python (no handler -> error reply); enable before serving.
}

// returns 1 if newly created, 0 if key existed (value replaced only when
// overwrite), -1 if no fastpath
int rt_fastpath_put(void* loop, uint64_t listener_id, const char* key,
                    uint32_t klen, const char* val, uint64_t vlen,
                    int overwrite) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  auto it = kv->kv.find(std::string(key, klen));
  bool exists = it != kv->kv.end();
  if (overwrite || !exists) {
    kv->kv[std::string(key, klen)] = std::string(val, vlen);
    kv->version.fetch_add(1);
  }
  return exists ? 0 : 1;
}

// returns 1 hit (out/out_len set, free with rt_buf_free), 0 miss, -1 no fp
int rt_fastpath_get(void* loop, uint64_t listener_id, const char* key,
                    uint32_t klen, char** out, uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  auto it = kv->kv.find(std::string(key, klen));
  if (it == kv->kv.end()) return 0;
  *out = dup_bytes(it->second.data(), it->second.size());
  *out_len = it->second.size();
  return 1;
}

int rt_fastpath_del(void* loop, uint64_t listener_id, const char* key,
                    uint32_t klen) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  bool hit = kv->kv.erase(std::string(key, klen)) > 0;
  if (hit) kv->version.fetch_add(1);
  return hit ? 1 : 0;
}

uint64_t rt_fastpath_version(void* loop, uint64_t listener_id) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  return kv ? kv->version.load() : 0;
}

// dump the whole table: (u32 klen, key, u64 vlen, val)*; free via
// rt_buf_free. Returns entry count, -1 if no fastpath.
int64_t rt_fastpath_dump(void* loop, uint64_t listener_id, char** out,
                         uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  size_t total = 0;
  for (auto& e : kv->kv) total += 12 + e.first.size() + e.second.size();
  char* buf = static_cast<char*>(malloc(total ? total : 1));
  char* p = buf;
  for (auto& e : kv->kv) {
    uint32_t kl = e.first.size();
    uint64_t vl = e.second.size();
    memcpy(p, &kl, 4);
    p += 4;
    memcpy(p, e.first.data(), kl);
    p += kl;
    memcpy(p, &vl, 8);
    p += 8;
    memcpy(p, e.second.data(), vl);
    p += vl;
  }
  *out = buf;
  *out_len = total;
  return static_cast<int64_t>(kv->kv.size());
}

// keys-only dump with C-side prefix filter: (u32 klen, key)*; free via
// rt_buf_free. Values never cross the boundary (they can be megabytes).
// Returns matching-key count, -1 if no fastpath.
int64_t rt_fastpath_keys(void* loop, uint64_t listener_id,
                         const char* prefix, uint32_t plen, char** out,
                         uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  size_t total = 0;
  int64_t n = 0;
  for (auto& e : kv->kv) {
    if (e.first.size() >= plen && memcmp(e.first.data(), prefix, plen) == 0) {
      total += 4 + e.first.size();
      n++;
    }
  }
  char* buf = static_cast<char*>(malloc(total ? total : 1));
  char* p = buf;
  for (auto& e : kv->kv) {
    if (e.first.size() >= plen && memcmp(e.first.data(), prefix, plen) == 0) {
      uint32_t kl = e.first.size();
      memcpy(p, &kl, 4);
      p += 4;
      memcpy(p, e.first.data(), kl);
      p += kl;
    }
  }
  *out = buf;
  *out_len = total;
  return n;
}

void rt_buf_free(char* p) { free(p); }

// ---- fast-path lease pool (host-side policy APIs; see FastLease above) ----

// deposit one ready grant into the pool for `sig`. 0 ok, -1 no fastpath.
int rt_fastlease_stock(void* loop, uint64_t listener_id, uint64_t sig,
                       uint64_t lease_key, const char* grant, uint64_t glen) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  kv->lease.pools[sig].emplace_back(lease_key, std::string(grant, glen));
  return 0;
}

// pop one pooled (un-held) grant back out, e.g. for idle drain.
// 1 popped (out_key/out/out_len set, free out via rt_buf_free), 0 empty,
// -1 no fastpath.
int rt_fastlease_unstock(void* loop, uint64_t listener_id, uint64_t sig,
                         uint64_t* out_key, char** out, uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  auto pit = kv->lease.pools.find(sig);
  if (pit == kv->lease.pools.end() || pit->second.empty()) return 0;
  auto& back = pit->second.back();  // LIFO: keep the hottest grants pooled
  *out_key = back.first;
  *out = dup_bytes(back.second.data(), back.second.size());
  *out_len = back.second.size();
  pit->second.pop_back();
  return 1;
}

// drop lease_key wherever it is (worker died / node lost):
// 2 = removed from held, 1 = removed from a pool, 0 = unknown, -1 = no fp.
int rt_fastlease_invalidate(void* loop, uint64_t listener_id,
                            uint64_t lease_key) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  if (kv->lease.held.erase(lease_key)) return 2;
  for (auto& p : kv->lease.pools) {
    for (auto it = p.second.begin(); it != p.second.end(); ++it) {
      if (it->first == lease_key) {
        p.second.erase(it);
        return 1;
      }
    }
  }
  return 0;
}

// reclaim every grant held by a disconnected conn. Out buffer:
// (u64 lease_key, u64 sig, u64 blen, blob)* — free via rt_buf_free.
// Returns reclaimed count, -1 if no fastpath.
int64_t rt_fastlease_reclaim_conn(void* loop, uint64_t listener_id,
                                  uint64_t conn_id, char** out,
                                  uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  size_t total = 0;
  int64_t n = 0;
  for (auto& e : kv->lease.held) {
    if (e.second.conn_id == conn_id) {
      total += 24 + e.second.grant.size();
      n++;
    }
  }
  char* buf = static_cast<char*>(malloc(total ? total : 1));
  char* p = buf;
  for (auto it = kv->lease.held.begin(); it != kv->lease.held.end();) {
    if (it->second.conn_id == conn_id) {
      uint64_t lkey = it->first, sig = it->second.sig,
               blen = it->second.grant.size();
      memcpy(p, &lkey, 8);
      memcpy(p + 8, &sig, 8);
      memcpy(p + 16, &blen, 8);
      memcpy(p + 24, it->second.grant.data(), blen);
      p += 24 + blen;
      it = kv->lease.held.erase(it);
    } else {
      ++it;
    }
  }
  *out = buf;
  *out_len = total;
  return n;
}

// pooled (un-held, grantable) entries: (u64 sig, u64 lease_key)* — free
// via rt_buf_free. Returns count, -1 if no fastpath. Lets Python report
// pooled capacity as AVAILABLE (it is reclaimable in one drain call).
int64_t rt_fastlease_pooled(void* loop, uint64_t listener_id, char** out,
                            uint64_t* out_len) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  size_t n = 0;
  for (auto& p : kv->lease.pools) n += p.second.size();
  char* buf = static_cast<char*>(malloc(n ? n * 16 : 1));
  char* w = buf;
  for (auto& p : kv->lease.pools) {
    for (auto& e : p.second) {
      memcpy(w, &p.first, 8);
      memcpy(w + 8, &e.first, 8);
      w += 16;
    }
  }
  *out = buf;
  *out_len = n * 16;
  return static_cast<int64_t>(n);
}

// out4 = {hits, misses, pooled_total, held_total}. 0 ok, -1 no fastpath.
int rt_fastlease_stats(void* loop, uint64_t listener_id, uint64_t* out4) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  uint64_t pooled = 0;
  for (auto& p : kv->lease.pools) pooled += p.second.size();
  out4[0] = kv->lease.hits;
  out4[1] = kv->lease.misses;
  out4[2] = pooled;
  out4[3] = kv->lease.held.size();
  return 0;
}

// pool depth for one sig. -1 if no fastpath.
int64_t rt_fastlease_depth(void* loop, uint64_t listener_id, uint64_t sig) {
  auto kv = find_fastkv(static_cast<Loop*>(loop), listener_id);
  if (!kv) return -1;
  std::lock_guard<std::mutex> g(kv->mu);
  auto pit = kv->lease.pools.find(sig);
  return pit == kv->lease.pools.end()
             ? 0
             : static_cast<int64_t>(pit->second.size());
}

// resolve + start a nonblocking connect; the poller completes it.
// Returns conn id (>0), or 0 if the address didn't resolve.
uint64_t rt_connect(void* loop, const char* host, int port) {
  auto* L = static_cast<Loop*>(loop);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", port);
  addrinfo* res = nullptr;
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr) {
    return 0;
  }
  auto c = std::make_shared<Conn>();
  {
    std::lock_guard<std::mutex> g(L->mu);
    c->id = L->next_id++;
    L->conns[c->id] = c;
  }
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK | SOCK_CLOEXEC, 0);
  if (fd < 0) {
    freeaddrinfo(res);
    std::lock_guard<std::mutex> g(L->mu);
    L->conns.erase(c->id);
    return 0;
  }
  set_nodelay(fd);
  int rc = connect(fd, res->ai_addr, res->ai_addrlen);
  freeaddrinfo(res);
  std::lock_guard<std::mutex> g(c->mu);
  c->fd = fd;
  if (rc == 0) {
    c->connecting = false;
  } else if (errno == EINPROGRESS) {
    c->connecting = true;
  } else {
    // immediate refusal: keep the conn registered and let the poller
    // deliver the DISCONNECT via EPOLLERR after ADD below
    c->connecting = true;
  }
  epoll_event ev{};
  ev.data.u64 = c->id;
  ev.events = EPOLLIN | (c->connecting ? EPOLLOUT : 0);
  epoll_ctl(L->epfd, EPOLL_CTL_ADD, fd, &ev);
  c->registered = true;
  c->cur_mask = ev.events;
  return c->id;
}

// 0 = ok, -1 = unknown/closed conn
int rt_send(void* loop, uint64_t conn_id, uint64_t req_id, const char* data,
            uint64_t len) {
  auto* L = static_cast<Loop*>(loop);
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> g(L->mu);
    auto it = L->conns.find(conn_id);
    if (it != L->conns.end()) c = it->second;
  }
  if (!c || c->closed.load()) return -1;
  char* buf = static_cast<char*>(malloc(16 + len));
  memcpy(buf, &req_id, 8);
  memcpy(buf + 8, &len, 8);
  if (len) memcpy(buf + 16, data, len);
  std::unique_lock<std::mutex> g(c->mu);
  // Backpressure: block until the poller drains the queue. Exemptions keep
  // it deadlock-free: the poller thread itself must never wait (it is the
  // only flusher), tiny control frames pass (a blocked GIL-holding sender
  // of small frames would freeze the Python side that drives the poller),
  // and the wait is bounded — unbounded memory is worse than a stall, but
  // a stall must not be forever.
  if (len >= 65536 &&
      L->poller_tid.load() != (unsigned long)pthread_self()) {
    int waited_ms = 0;
    while (c->wq_bytes > RT_WQ_HIGH_BYTES && !c->closed.load() &&
           waited_ms < 10000) {
      c->wcv.wait_for(g, std::chrono::milliseconds(200));
      waited_ms += 200;
    }
  }
  if (c->closed.load()) {
    free(buf);
    return -1;
  }
  bool was_empty = c->wq.empty();
  c->wq.push_back(Buf{buf, 16 + static_cast<size_t>(len), 0});
  c->wq_bytes += 16 + len;
  // Burst detection: every small writev to a watched socket wakes the
  // receiver process — on a busy single-CPU host that's a ~100µs scheduler
  // round-trip PER FRAME. If another send hit this conn within the last
  // 200µs we are in a burst: leave the frame queued so the poller flushes
  // many frames in ONE writev (receiver wakes once per batch). Isolated
  // sends keep the inline write for latency.
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t now_ns =
      static_cast<uint64_t>(ts.tv_sec) * 1000000000ull + ts.tv_nsec;
  bool bursting = now_ns - c->last_send_ns < 200000;
  c->last_send_ns = now_ns;
  if (was_empty && !bursting && !c->connecting && c->fd >= 0 &&
      inline_send_enabled()) {
    // latency fast-path: try the write inline; leftovers flushed on
    // EPOLLOUT by the poller
    iovec iov{buf, 16 + static_cast<size_t>(len)};
    ssize_t w = writev(c->fd, &iov, 1);
    if (w > 0) {
      size_t sw = static_cast<size_t>(w);
      c->wq_bytes -= sw;
      if (sw == iov.iov_len) {
        free(buf);
        c->wq.pop_front();
      } else {
        c->wq.front().off = sw;
      }
    }
    // fatal errors surface via the poller (EPOLLERR/read) — frame stays
    // queued and is dropped at destroy
  }
  sync_mask(L, c.get());  // arms EPOLLOUT if bytes remain queued
  return 0;
}

void rt_close_conn(void* loop, uint64_t conn_id) {
  auto* L = static_cast<Loop*>(loop);
  {
    std::lock_guard<std::mutex> g(L->mu);
    if (L->conns.find(conn_id) == L->conns.end()) return;
    L->ops.push_back(Op{Op::CLOSE, conn_id});
  }
  L->wake();
}

void rt_close_listener(void* loop, uint64_t listener_id) {
  auto* L = static_cast<Loop*>(loop);
  std::shared_ptr<Listener> lst;
  {
    std::lock_guard<std::mutex> g(L->mu);
    auto it = L->listeners.find(listener_id);
    if (it == L->listeners.end()) return;
    lst = it->second;
    L->listeners.erase(it);
  }
  epoll_ctl(L->epfd, EPOLL_CTL_DEL, lst->fd, nullptr);
  close(lst->fd);
}

// Single consumer. Frees payloads handed out by the PREVIOUS call, runs
// one IO pass (epoll + reads, GIL released by the ctypes binding), and
// returns up to max_events parsed messages.
int rt_poll(void* loop, rt_event* out, int max_events, int timeout_ms) {
  auto* L = static_cast<Loop*>(loop);
  L->poller_tid.store((unsigned long)pthread_self());
  for (auto& e : L->delivered) free(e.data);
  L->delivered.clear();
  if (L->stopping.load()) return 0;
  if (L->q.empty()) {
    poll_io(L, timeout_ms);
  } else if (static_cast<int>(L->q.size()) < max_events) {
    poll_io(L, 0);  // opportunistic top-up, no sleep
  }
  int n = 0;
  while (!L->q.empty() && n < max_events) {
    Event e = L->q.front();
    L->q.pop_front();
    L->q_bytes -= e.len;
    out[n].type = e.type;
    out[n].conn_id = e.conn_id;
    out[n].req_id = e.req_id;
    out[n].len = e.len;
    out[n].data = e.data;
    n++;
    L->delivered.push_back(e);
  }
  if (L->reads_paused && L->q_bytes < RT_INQ_LOW_BYTES) {
    L->reads_paused = false;
    std::lock_guard<std::mutex> g(L->mu);
    for (auto& kv : L->conns) {
      std::lock_guard<std::mutex> cg(kv.second->mu);
      if (kv.second->read_paused) {
        kv.second->read_paused = false;
        sync_mask(L, kv.second.get());
      }
    }
  }
  return n;
}

}  // extern "C"
