// Cluster resource scheduler — node selection policies and placement-group
// bundle packing, as a process-embeddable C++ library.
//
// Role-equivalent to the reference's raylet scheduling stack (reference:
// src/ray/raylet/scheduling/cluster_resource_scheduler.h:44,
// policy/hybrid_scheduling_policy.h:50, policy/bundle_scheduling_policy.h:82-106,
// policy/scorer.h:41 LeastResourceScorer) and its fixed-point resource model
// (reference: src/ray/common/scheduling/fixed_point.h, resource_set.h,
// cluster_resource_data.h). Differences for the TPU rebuild:
//  - resources are interned string -> index maps per cluster state, with
//    fixed-point (x10000) arithmetic so fractional CPUs/chips are exact;
//  - TPU gang constraints surface as label-style resources
//    ("TPU-v5p-8-head") handled uniformly as custom resources;
//  - the whole scheduler is a passive library: the Python/daemon layers feed
//    node updates in and ask for decisions, so the identical logic runs in
//    the head (GCS placement) and in each node daemon (spillback checks).
//
// Exposed C API (used via ctypes from ray_tpu/core/_native.py):
//   cluster_new/free, cluster_add_node, cluster_remove_node,
//   cluster_update_available, cluster_schedule (hybrid/spread/random/
//   node_affinity), cluster_schedule_bundles (PACK/SPREAD/STRICT_*),
//   cluster_acquire/release (resource bookkeeping).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <random>
#include <string>
#include <vector>

namespace {

using FixedPoint = int64_t;  // value * 10000
constexpr FixedPoint kUnit = 10000;

struct ResourceSet {
  // resource index -> amount (sparse)
  std::map<int, FixedPoint> amounts;

  bool covers(const ResourceSet& demand) const {
    for (const auto& [idx, amt] : demand.amounts) {
      auto it = amounts.find(idx);
      if (it == amounts.end() || it->second < amt) return false;
    }
    return true;
  }
  void subtract(const ResourceSet& demand) {
    for (const auto& [idx, amt] : demand.amounts) amounts[idx] -= amt;
  }
  void add(const ResourceSet& demand) {
    for (const auto& [idx, amt] : demand.amounts) amounts[idx] += amt;
  }
};

struct Node {
  std::string id;
  ResourceSet total;
  ResourceSet available;
  bool alive = true;
  std::map<std::string, std::string> labels;
};

struct Cluster {
  std::vector<Node> nodes;                    // dense, dead nodes compacted out
  std::map<std::string, int> node_index;      // id -> index
  std::map<std::string, int> resource_ids;    // name -> index (interned)
  std::mt19937_64 rng{0x52545055};
  float spread_threshold = 0.5f;

  int intern(const std::string& name) {
    auto it = resource_ids.find(name);
    if (it != resource_ids.end()) return it->second;
    int idx = (int)resource_ids.size();
    resource_ids.emplace(name, idx);
    return idx;
  }
};

// Wire format for resource sets crossing the C boundary:
//   n_entries u32, then per entry: name_len u32, name bytes, amount_fp i64
ResourceSet parse_resources(Cluster* c, const uint8_t* buf, uint64_t len) {
  ResourceSet rs;
  if (len < 4) return rs;
  uint32_t n;
  memcpy(&n, buf, 4);
  uint64_t off = 4;
  for (uint32_t i = 0; i < n && off + 4 <= len; i++) {
    uint32_t name_len;
    memcpy(&name_len, buf + off, 4);
    off += 4;
    std::string name(reinterpret_cast<const char*>(buf + off), name_len);
    off += name_len;
    int64_t amt;
    memcpy(&amt, buf + off, 8);
    off += 8;
    rs.amounts[c->intern(name)] += amt;
  }
  return rs;
}

// LeastResourceScorer (reference: policy/scorer.h:41): score a node for a
// demand = sum over demanded resources of available/total after placement;
// higher is better for PACK (critical resources get used up), we invert for
// spread. We implement the reference's hybrid scoring: utilization-based.
float node_utilization_after(const Node& n, const ResourceSet& demand) {
  float worst = 0.0f;
  for (const auto& [idx, amt] : demand.amounts) {
    auto tot_it = n.total.amounts.find(idx);
    if (tot_it == n.total.amounts.end() || tot_it->second == 0) return 1.0f;
    auto avail_it = n.available.amounts.find(idx);
    FixedPoint avail = avail_it == n.available.amounts.end() ? 0 : avail_it->second;
    float util = 1.0f - (float)(avail - amt) / (float)tot_it->second;
    worst = std::max(worst, util);
  }
  return worst;
}

enum Policy : int {
  kHybrid = 0,
  kSpread = 1,
  kRandom = 2,
  kNodeAffinity = 3,
};

enum BundleStrategy : int {
  kPack = 0,
  kBundleSpread = 1,
  kStrictPack = 2,
  kStrictSpread = 3,
};

}  // namespace

extern "C" {

void* rtpu_cluster_new() { return new Cluster(); }
void rtpu_cluster_free(void* h) { delete reinterpret_cast<Cluster*>(h); }

void rtpu_cluster_set_spread_threshold(void* h, float t) {
  reinterpret_cast<Cluster*>(h)->spread_threshold = t;
}

int rtpu_cluster_add_node(void* h, const char* node_id, const uint8_t* res,
                          uint64_t res_len) {
  auto* c = reinterpret_cast<Cluster*>(h);
  if (c->node_index.count(node_id)) return -1;
  Node n;
  n.id = node_id;
  n.total = parse_resources(c, res, res_len);
  n.available = n.total;
  c->node_index[n.id] = (int)c->nodes.size();
  c->nodes.push_back(std::move(n));
  return 0;
}

int rtpu_cluster_remove_node(void* h, const char* node_id) {
  auto* c = reinterpret_cast<Cluster*>(h);
  auto it = c->node_index.find(node_id);
  if (it == c->node_index.end()) return -1;
  int idx = it->second;
  c->node_index.erase(it);
  c->nodes.erase(c->nodes.begin() + idx);
  c->node_index.clear();
  for (int i = 0; i < (int)c->nodes.size(); i++) c->node_index[c->nodes[i].id] = i;
  return 0;
}

// Replace a node's available resources (gossip update from the node daemon;
// reference: ray_syncer.h resource broadcast).
int rtpu_cluster_update_available(void* h, const char* node_id,
                                  const uint8_t* res, uint64_t res_len) {
  auto* c = reinterpret_cast<Cluster*>(h);
  auto it = c->node_index.find(node_id);
  if (it == c->node_index.end()) return -1;
  c->nodes[it->second].available = parse_resources(c, res, res_len);
  return 0;
}

// Book-keep an allocation decided elsewhere. Returns 0 on success, -1 if the
// node can no longer cover the demand (caller should reschedule).
int rtpu_cluster_acquire(void* h, const char* node_id, const uint8_t* res,
                         uint64_t res_len) {
  auto* c = reinterpret_cast<Cluster*>(h);
  auto it = c->node_index.find(node_id);
  if (it == c->node_index.end()) return -1;
  Node& n = c->nodes[it->second];
  ResourceSet demand = parse_resources(c, res, res_len);
  if (!n.available.covers(demand)) return -1;
  n.available.subtract(demand);
  return 0;
}

int rtpu_cluster_release(void* h, const char* node_id, const uint8_t* res,
                         uint64_t res_len) {
  auto* c = reinterpret_cast<Cluster*>(h);
  auto it = c->node_index.find(node_id);
  if (it == c->node_index.end()) return -1;
  Node& n = c->nodes[it->second];
  n.available.add(parse_resources(c, res, res_len));
  return 0;
}

// Pick a node for one task. Returns index into out_node_id (caller buffer of
// >=64 bytes) or -1 if infeasible everywhere.
// policy: Policy enum. affinity_node: used by kNodeAffinity (soft flag says
// whether to fall back to hybrid when the target is infeasible).
int rtpu_cluster_schedule(void* h, const uint8_t* res, uint64_t res_len,
                          int policy, const char* affinity_node, int soft,
                          char* out_node_id) {
  auto* c = reinterpret_cast<Cluster*>(h);
  ResourceSet demand = parse_resources(c, res, res_len);

  if (policy == kNodeAffinity && affinity_node && affinity_node[0]) {
    auto it = c->node_index.find(affinity_node);
    if (it != c->node_index.end() && c->nodes[it->second].available.covers(demand)) {
      strncpy(out_node_id, affinity_node, 63);
      out_node_id[63] = 0;
      return 0;
    }
    if (!soft) return -1;
    policy = kHybrid;
  }

  std::vector<int> feasible;
  for (int i = 0; i < (int)c->nodes.size(); i++) {
    if (c->nodes[i].alive && c->nodes[i].available.covers(demand)) {
      feasible.push_back(i);
    }
  }
  if (feasible.empty()) return -1;

  int chosen = -1;
  if (policy == kRandom) {
    chosen = feasible[c->rng() % feasible.size()];
  } else if (policy == kSpread) {
    // round-robin-ish: lowest utilization first (reference spread policy)
    float best = 2.0f;
    for (int i : feasible) {
      float u = node_utilization_after(c->nodes[i], demand);
      if (u < best) {
        best = u;
        chosen = i;
      }
    }
  } else {  // hybrid: pack onto nodes below threshold (prefer highest
            // utilization below threshold => consolidation), else spread
            // (reference: policy/hybrid_scheduling_policy.h:50)
    float best_pack = -1.0f;
    int pack_node = -1;
    float best_spread = 2.0f;
    int spread_node = -1;
    for (int i : feasible) {
      float u = node_utilization_after(c->nodes[i], demand);
      if (u <= c->spread_threshold) {
        if (u > best_pack) {
          best_pack = u;
          pack_node = i;
        }
      }
      if (u < best_spread) {
        best_spread = u;
        spread_node = i;
      }
    }
    chosen = pack_node >= 0 ? pack_node : spread_node;
  }
  if (chosen < 0) return -1;
  strncpy(out_node_id, c->nodes[chosen].id.c_str(), 63);
  out_node_id[63] = 0;
  return 0;
}

// Placement-group bundle scheduling (reference:
// policy/bundle_scheduling_policy.h:82-106 — PACK/SPREAD/STRICT_PACK/
// STRICT_SPREAD). Input: n_bundles resource sets concatenated (each prefixed
// with u64 byte length). Output: out_assignments gets n_bundles node-id
// strings of 64 bytes each. All-or-nothing: returns -1 and changes nothing
// if the set cannot be placed.
int rtpu_cluster_schedule_bundles(void* h, const uint8_t* bundles,
                                  uint64_t bundles_len, uint32_t n_bundles,
                                  int strategy, char* out_assignments) {
  auto* c = reinterpret_cast<Cluster*>(h);
  std::vector<ResourceSet> demands;
  uint64_t off = 0;
  for (uint32_t i = 0; i < n_bundles; i++) {
    if (off + 8 > bundles_len) return -2;
    uint64_t blen;
    memcpy(&blen, bundles + off, 8);
    off += 8;
    demands.push_back(parse_resources(c, bundles + off, blen));
    off += blen;
  }

  // Work on a copy of availability; commit only on success.
  std::vector<ResourceSet> avail;
  avail.reserve(c->nodes.size());
  for (auto& n : c->nodes) avail.push_back(n.available);

  std::vector<int> assignment(n_bundles, -1);

  auto covers = [&](int node, const ResourceSet& d) {
    return c->nodes[node].alive && avail[node].covers(d);
  };

  if (strategy == kStrictPack) {
    // all bundles on one node
    for (int i = 0; i < (int)c->nodes.size(); i++) {
      ResourceSet tmp = avail[i];
      bool ok = true;
      for (auto& d : demands) {
        if (!c->nodes[i].alive || !tmp.covers(d)) {
          ok = false;
          break;
        }
        tmp.subtract(d);
      }
      if (ok) {
        for (uint32_t b = 0; b < n_bundles; b++) assignment[b] = i;
        break;
      }
    }
  } else if (strategy == kStrictSpread) {
    // each bundle on a distinct node; greedy biggest-first
    std::vector<uint32_t> order(n_bundles);
    for (uint32_t i = 0; i < n_bundles; i++) order[i] = i;
    std::vector<bool> used(c->nodes.size(), false);
    bool ok = true;
    for (uint32_t b : order) {
      int pick = -1;
      float best = 2.0f;
      for (int i = 0; i < (int)c->nodes.size(); i++) {
        if (used[i] || !covers(i, demands[b])) continue;
        float u = node_utilization_after(c->nodes[i], demands[b]);
        if (u < best) {
          best = u;
          pick = i;
        }
      }
      if (pick < 0) {
        ok = false;
        break;
      }
      used[pick] = true;
      avail[pick].subtract(demands[b]);
      assignment[b] = pick;
    }
    if (!ok) return -1;
  } else {
    // PACK (best effort consolidate) / SPREAD (best effort distribute)
    for (uint32_t b = 0; b < n_bundles; b++) {
      int pick = -1;
      float best = strategy == kPack ? -1.0f : 2.0f;
      for (int i = 0; i < (int)c->nodes.size(); i++) {
        if (!covers(i, demands[b])) continue;
        float u = node_utilization_after(c->nodes[i], demands[b]);
        bool better = strategy == kPack ? u > best : u < best;
        if (better) {
          best = u;
          pick = i;
        }
      }
      if (pick < 0) return -1;
      avail[pick].subtract(demands[b]);
      assignment[b] = pick;
    }
  }

  for (uint32_t b = 0; b < n_bundles; b++) {
    if (assignment[b] < 0) return -1;
  }
  // commit
  for (uint32_t b = 0; b < n_bundles; b++) {
    c->nodes[assignment[b]].available.subtract(demands[b]);
    strncpy(out_assignments + 64 * b, c->nodes[assignment[b]].id.c_str(), 63);
    out_assignments[64 * b + 63] = 0;
  }
  return 0;
}

uint32_t rtpu_cluster_num_nodes(void* h) {
  return (uint32_t)reinterpret_cast<Cluster*>(h)->nodes.size();
}

}  // extern "C"
