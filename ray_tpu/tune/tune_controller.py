"""TuneController — the experiment event loop.

Role-equivalent to the reference's TuneController (reference:
tune/execution/tune_controller.py:68): owns trial lifecycle (launch as
actors with reserved resources, pull results, apply scheduler decisions,
PBT exploit restarts, failure retries) and experiment-state checkpointing
so an interrupted experiment resumes (reference: tune/execution/
experiment_state.py).
"""

from __future__ import annotations

import json
import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import Decision, FIFOScheduler, TrialScheduler
from ray_tpu.tune.trial import DONE, Trial, TrialRunner, TrialStatus

logger = logging.getLogger(__name__)


class TuneController:
    def __init__(self, trainable: Callable, *, param_space: Dict[str, Any],
                 variants: List[Dict[str, Any]], metric: str, mode: str,
                 scheduler: Optional[TrialScheduler] = None,
                 max_concurrent: int = 0,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 storage_path: Optional[str] = None,
                 max_failures_per_trial: int = 0,
                 restore_state: Optional[List[Dict[str, Any]]] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.trainable = trainable
        self.metric = metric
        self.mode = mode
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_experiment(metric, mode, param_space)
        self.resources = resources_per_trial or {"CPU": 1.0}
        self.storage = storage_path or os.path.join(
            "/tmp/ray_tpu_tune", f"exp_{uuid.uuid4().hex[:8]}")
        os.makedirs(self.storage, exist_ok=True)
        self.max_concurrent = max_concurrent
        self.max_failures = max_failures_per_trial
        self.trials = [
            Trial(trial_id=f"t{i:04d}", config=cfg)
            for i, cfg in enumerate(variants)]
        if restore_state:
            # Resume semantics: TERMINATED trials keep their results;
            # anything else restarts from its latest in-trial checkpoint
            # (reference: experiment_state.py resume path).
            by_id = {s["trial_id"]: s for s in restore_state}
            for t in self.trials:
                s = by_id.get(t.trial_id)
                if s is None:
                    continue
                t.checkpoint_path = s.get("checkpoint_path")
                t.last_result = s.get("last_result") or {}
                t.iteration = s.get("iteration", 0)
                if s.get("status") == TrialStatus.TERMINATED:
                    t.status = TrialStatus.TERMINATED
                    if t.last_result:
                        t.results.append(t.last_result)
        self._failures: Dict[str, int] = {}

    # ------------------------------------------------------------ lifecycle
    def _trial_dir(self, trial: Trial) -> str:
        return os.path.join(self.storage, trial.trial_id)

    def _launch(self, trial: Trial,
                restore_path: Optional[str] = None) -> None:
        cls = ray_tpu.remote(**{
            "num_cpus": self.resources.get("CPU", 1.0),
            "resources": {k: v for k, v in self.resources.items()
                          if k != "CPU"} or None,
        })(TrialRunner)
        trial.actor = cls.remote(self.trainable, trial.config,
                                 self._trial_dir(trial),
                                 restore_path or trial.checkpoint_path)
        trial.status = TrialStatus.RUNNING
        trial.pending_ref = trial.actor.next_result.remote()

    def _stop_actor(self, trial: Trial) -> None:
        if trial.actor is not None:
            try:
                # Cooperative stop first: the stop() call enqueues behind the
                # outstanding next_result and unwinds the fn thread (sets the
                # stop event, drains the result queue so a blocked report()
                # returns, then StopTrial is raised at the next report).
                # kill() alone would leave the fn thread parked forever on a
                # full queue in local mode. The local actor queue is FIFO, so
                # stop is processed before the kill tombstone.
                trial.actor.stop.remote()
                ray_tpu.kill(trial.actor)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        trial.actor = None
        trial.pending_ref = None

    def _capacity(self) -> int:
        if self.max_concurrent > 0:
            return self.max_concurrent
        try:
            avail = ray_tpu.cluster_resources().get("CPU", 1.0)
            need = max(self.resources.get("CPU", 1.0), 1e-9)
            return max(1, int(avail / need))
        except Exception:  # noqa: BLE001 — local mode w/o resource table
            return 4

    @staticmethod
    def _note_running_gauge(n: int) -> None:
        """Built-in L5 metric: trials currently holding an actor in this
        tuner process (best-effort — tuning never depends on telemetry)."""
        try:
            from ray_tpu.util import metrics as metrics_mod
            metrics_mod.tune_running_trials_gauge().set(n)
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------ main loop
    def run(self) -> List[Trial]:
        pending = [t for t in self.trials if t.status == TrialStatus.PENDING]
        running: List[Trial] = []
        cap = self._capacity()
        while pending or running:
            while pending and len(running) < cap:
                t = pending.pop(0)
                self._launch(t)
                running.append(t)
            self._note_running_gauge(len(running))
            ref_to_trial = {t.pending_ref: t for t in running}
            done, _ = ray_tpu.wait(list(ref_to_trial), num_returns=1,
                                   timeout=60)
            if not done:
                continue
            trial = ref_to_trial[done[0]]
            # Round-robin fairness: wait() scans refs in order, so without
            # rotation one always-ready trial would monopolize the loop and
            # the population would advance wildly unevenly — which breaks
            # PBT (exploit would clone checkpoints from trials many steps
            # ahead). Rotating keeps trials within ~1 iteration of lockstep.
            running.remove(trial)
            running.append(trial)
            try:
                result = ray_tpu.get(done[0])
            except Exception as e:  # noqa: BLE001 — trial fault boundary
                self._on_trial_error(trial, e, pending, running)
                self._save_experiment_state()
                continue
            if result.get(DONE):
                trial.status = TrialStatus.TERMINATED
                self._stop_actor(trial)
                running.remove(trial)
                self.scheduler.on_trial_complete(trial.trial_id)
                self._save_experiment_state()
                continue
            self._on_trial_result(trial, result, pending, running)
        self._note_running_gauge(0)
        self._save_experiment_state()
        return self.trials

    def _on_trial_result(self, trial: Trial, result: Dict[str, Any],
                         pending: List[Trial], running: List[Trial]) -> None:
        trial.iteration = int(result.get("training_iteration",
                                         trial.iteration + 1))
        if "__checkpoint__" in result:
            trial.checkpoint_path = result.pop("__checkpoint__")
        trial.last_result = result
        trial.results.append(result)
        decision = self.scheduler.on_result(trial, result, self.trials)
        exploit = getattr(trial, "_pbt_exploit", None)
        if exploit is not None:
            del trial._pbt_exploit
            self._exploit(trial, exploit)
            return
        if decision == Decision.STOP:
            trial.status = TrialStatus.TERMINATED
            self._stop_actor(trial)
            running.remove(trial)
            self.scheduler.on_trial_complete(trial.trial_id)
        else:
            trial.pending_ref = trial.actor.next_result.remote()
        self._save_experiment_state()

    def _exploit(self, trial: Trial, directive: Dict[str, Any]) -> None:
        """PBT exploit: restart this trial from the source's checkpoint with
        the explored config (reference pbt.py _exploit)."""
        logger.info("tune/pbt: %s exploits %s", trial.trial_id,
                    directive["source_id"])
        self._stop_actor(trial)
        trial.config = directive["config"]
        trial.checkpoint_path = directive["checkpoint_path"]
        self._launch(trial, restore_path=directive["checkpoint_path"])

    def _on_trial_error(self, trial: Trial, error: Exception,
                        pending: List[Trial], running: List[Trial]) -> None:
        n = self._failures.get(trial.trial_id, 0) + 1
        self._failures[trial.trial_id] = n
        self._stop_actor(trial)
        if n <= self.max_failures:
            logger.warning("tune: trial %s failed (%d/%d), restarting: %r",
                           trial.trial_id, n, self.max_failures, error)
            self._launch(trial, restore_path=trial.checkpoint_path)
        else:
            trial.status = TrialStatus.ERRORED
            trial.error = repr(error)
            running.remove(trial)
            self.scheduler.on_trial_complete(trial.trial_id)

    # --------------------------------------------------------- persistence
    def _save_experiment_state(self) -> None:
        state = {
            "metric": self.metric, "mode": self.mode,
            "trials": [{
                "trial_id": t.trial_id,
                "config": _jsonable(t.config),
                "status": t.status,
                "iteration": t.iteration,
                "last_result": _jsonable(t.last_result),
                "checkpoint_path": t.checkpoint_path,
                "error": t.error,
            } for t in self.trials],
            "saved_at": time.time(),
        }
        tmp = os.path.join(self.storage, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, os.path.join(self.storage, "experiment_state.json"))
        # Pickle sidecar holds configs losslessly for Tuner.restore (the
        # JSON file is the human-readable view; see tuner.py restore).
        import cloudpickle
        state_pkl = dict(state)
        state_pkl["trials"] = [dict(s) for s in state["trials"]]
        for s, t in zip(state_pkl["trials"], self.trials):
            s["config"] = dict(t.config)
            s["last_result"] = dict(t.last_result)
        tmp = os.path.join(self.storage, ".experiment_state.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(state_pkl, f)
        os.replace(tmp, os.path.join(self.storage, "experiment_state.pkl"))


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out
