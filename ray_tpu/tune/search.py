"""Search spaces + variant generation.

Role-equivalent to the reference's sample domains and BasicVariantGenerator
(reference: python/ray/tune/search/sample.py, search/basic_variant.py):
``param_space`` dicts mix literals, domain objects, and ``grid_search``
markers; the generator expands the grid cross-product and draws
``num_samples`` random variants of the stochastic domains per grid point.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform needs lower > 0")
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng: random.Random) -> int:
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        return self.fn()


class GridSearch:
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


# -- public constructors (reference: tune.uniform/loguniform/choice/...) ----

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


# ---------------------------------------------------------------------------

def _split_space(space: Dict[str, Any]):
    grid: Dict[str, GridSearch] = {}
    stochastic: Dict[str, Domain] = {}
    const: Dict[str, Any] = {}
    for k, v in space.items():
        if isinstance(v, GridSearch) or (
                isinstance(v, dict) and set(v) == {"grid_search"}):
            grid[k] = v if isinstance(v, GridSearch) \
                else GridSearch(v["grid_search"])
        elif isinstance(v, Domain):
            stochastic[k] = v
        else:
            const[k] = v
    return grid, stochastic, const


def generate_variants(space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None) -> List[Dict[str, Any]]:
    """Grid cross-product × num_samples random draws (reference
    basic_variant semantics: num_samples repeats the whole grid)."""
    rng = random.Random(seed)
    grid, stochastic, const = _split_space(space)
    grid_keys = list(grid)
    grid_rows = [dict(zip(grid_keys, combo)) for combo in
                 itertools.product(*(grid[k].values for k in grid_keys))] \
        or [{}]
    variants: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for row in grid_rows:
            cfg = dict(const)
            cfg.update(row)
            for k, dom in stochastic.items():
                cfg[k] = dom.sample(rng)
            variants.append(cfg)
    return variants


def resample_key(space: Dict[str, Any], key: str,
                 rng: random.Random) -> Optional[Any]:
    """Draw a fresh value for one hyperparameter (PBT explore)."""
    v = space.get(key)
    if isinstance(v, Domain):
        return v.sample(rng)
    if isinstance(v, GridSearch):
        return rng.choice(v.values)
    if isinstance(v, (list, tuple)) and v:
        return rng.choice(list(v))
    return None
