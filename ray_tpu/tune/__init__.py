"""ray_tpu.tune — hyperparameter search & trial orchestration.

Capability target: the reference's Ray Tune core (reference:
python/ray/tune — Tuner.fit at tuner.py:312, TuneController at
execution/tune_controller.py:68, ASHA at schedulers/async_hyperband.py,
PBT at schedulers/pbt.py:221). Trials run as ray_tpu actors with reserved
resources; on TPU clusters a trial's resources are a slice-shaped gang
(e.g. {"TPU": 4}), which is how PBT spans multi-slice pods.
"""

from typing import Any, Dict

from ray_tpu.tune.schedulers import (ASHAScheduler, FIFOScheduler,
                                     MedianStoppingRule,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.searcher import (BasicVariantSearcher,
                                   HyperOptLikeSearcher, Searcher)
from ray_tpu.tune.search import (choice, grid_search, loguniform, randint,
                                 sample_from, uniform)
from ray_tpu.tune.trial import Trial, TrialStatus, get_session
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, TuneRunConfig, Tuner

__all__ = [
    "Tuner", "TuneConfig", "TuneRunConfig", "ResultGrid", "Trial",
    "TrialStatus", "TrialScheduler", "FIFOScheduler", "ASHAScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining", "uniform", "loguniform", "randint", "choice",
    "sample_from", "grid_search", "report", "get_checkpoint",
    "Searcher", "BasicVariantSearcher", "HyperOptLikeSearcher",
]


def report(metrics: Dict[str, Any], *, checkpoint: Any = None) -> None:
    """Report one iteration's metrics (and optionally a checkpoint object)
    from inside a trial (reference: tune report/session API)."""
    get_session().report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Any:
    """The checkpoint object this trial should resume from, or None.
    After a PBT exploit this is the *source* trial's checkpoint."""
    return get_session().get_checkpoint()
