"""Trial bookkeeping + the trial-runner actor.

Role-equivalent to the reference's Trial (reference: tune/experiment/
trial.py) and the function-trainable wrapper (tune/trainable/function_
trainable.py): the user function runs on a thread inside a trial actor,
streaming ``tune.report`` results through a queue; the controller pulls one
result at a time (``next_result``), which is what gives schedulers
per-iteration control (stop/pause/exploit between iterations).
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

DONE = "__trial_done__"


class TrialStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERRORED = "ERRORED"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = TrialStatus.PENDING
    iteration: int = 0
    last_result: Dict[str, Any] = field(default_factory=dict)
    results: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    actor: Any = None  # live ActorHandle while RUNNING
    pending_ref: Any = None  # outstanding next_result ObjectRef

    def metric_value(self, metric: str) -> Optional[float]:
        v = self.last_result.get(metric)
        return float(v) if v is not None else None


# ---------------------------------------------------------------- actor side

class _TrialSession:
    """tune.report/get_checkpoint binding inside the trial thread."""

    def __init__(self, config: Dict[str, Any], trial_dir: str,
                 restore_path: Optional[str]):
        self.config = config
        self.trial_dir = trial_dir
        self.restore_path = restore_path
        self.queue: "queue.Queue" = queue.Queue(maxsize=4)
        self.step = 0
        self.stop_event = threading.Event()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Any = None) -> None:
        if self.stop_event.is_set():
            raise StopTrial()
        self.step += 1
        entry = dict(metrics)
        entry["training_iteration"] = self.step
        if checkpoint is not None:
            os.makedirs(self.trial_dir, exist_ok=True)
            path = os.path.join(self.trial_dir, f"ckpt_{self.step:08d}.pkl")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                cloudpickle.dump(checkpoint, f)
            os.replace(tmp, path)
            entry["__checkpoint__"] = path
        self.queue.put(("result", entry))
        if self.stop_event.is_set():
            raise StopTrial()

    def get_checkpoint(self) -> Any:
        if self.restore_path and os.path.exists(self.restore_path):
            with open(self.restore_path, "rb") as f:
                return cloudpickle.load(f)
        return None


class StopTrial(Exception):
    """Raised inside the user fn when the controller stopped the trial."""


_session_local = threading.local()


def get_session() -> _TrialSession:
    s = getattr(_session_local, "s", None)
    if s is None:
        raise RuntimeError("tune.report called outside a tune trial")
    return s


class TrialRunner:
    """Actor body: owns the user-fn thread and the result queue."""

    def __init__(self, fn: Callable[[Dict[str, Any]], Any],
                 config: Dict[str, Any], trial_dir: str,
                 restore_path: Optional[str] = None):
        self._session = _TrialSession(config, trial_dir, restore_path)

        def runner():
            _session_local.s = self._session
            try:
                fn(dict(config))
                self._session.queue.put((DONE, None))
            except StopTrial:
                self._session.queue.put((DONE, None))
            except BaseException as e:  # noqa: BLE001 — trial fault boundary
                self._session.queue.put(("error", e))
        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="tune-trial-fn")
        self._thread.start()

    def next_result(self) -> Dict[str, Any]:
        """Block until the fn reports, finishes, or errors."""
        kind, payload = self._session.queue.get()
        if kind == DONE:
            return {DONE: True}
        if kind == "error":
            raise payload
        return payload

    def stop(self) -> bool:
        """Ask the fn thread to unwind at its next report()."""
        self._session.stop_event.set()
        try:
            while True:
                self._session.queue.get_nowait()
        except queue.Empty:
            pass
        return True
