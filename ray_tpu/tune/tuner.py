"""Tuner / TuneConfig / ResultGrid — the user-facing surface.

Role-equivalent to the reference's Tuner (reference: tune/tuner.py:312
Tuner.fit) and ResultGrid (tune/result_grid.py). ``Tuner.restore``
re-hydrates a crashed experiment from the experiment_state.json the
controller checkpoints after every event.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.trial import Trial, TrialStatus
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    scheduler: Optional[TrialScheduler] = None
    max_concurrent_trials: int = 0
    seed: Optional[int] = None
    # Searcher plugin (reference: tune/search/searcher.py seam). When set,
    # trials run in waves sized by max_concurrent_trials and results feed
    # back through on_trial_complete between waves, so sequential
    # model-based searchers actually see earlier results.
    search_alg: Optional[Any] = None


@dataclass
class TuneRunConfig:
    storage_path: Optional[str] = None
    name: Optional[str] = None
    max_failures_per_trial: int = 0
    resources_per_trial: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: str, mode: str,
                 storage_path: str):
        self.trials = trials
        self.metric = metric
        self.mode = mode
        self.storage_path = storage_path

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Trial:
        metric = metric or self.metric
        sign = 1.0 if (mode or self.mode) == "max" else -1.0
        scored = [t for t in self.trials
                  if t.metric_value(metric) is not None]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        return max(scored, key=lambda t: sign * t.metric_value(metric))

    def get_dataframe(self) -> List[Dict[str, Any]]:
        """Rows of (trial_id, status, config.*, last_result.*) — plain
        dicts, not pandas (numpy-first policy)."""
        rows = []
        for t in self.trials:
            row = {"trial_id": t.trial_id, "status": t.status,
                   "iterations": t.iteration}
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row.update(t.last_result)
            rows.append(row)
        return rows

    @property
    def errors(self) -> List[Trial]:
        return [t for t in self.trials if t.status == TrialStatus.ERRORED]


class Tuner:
    def __init__(self, trainable: Callable[[Dict[str, Any]], Any], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[TuneRunConfig] = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or TuneRunConfig()
        self._restored_variants: Optional[List[Dict[str, Any]]] = None
        self._restored_state: Optional[Dict[str, Any]] = None

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        if tc.search_alg is not None and self._restored_variants is None:
            return self._fit_with_searcher()
        variants = self._restored_variants or generate_variants(
            self.param_space, tc.num_samples, seed=tc.seed)
        storage = self.run_config.storage_path
        if storage and self.run_config.name:
            storage = os.path.join(storage, self.run_config.name)
        controller = TuneController(
            self.trainable,
            param_space=self.param_space,
            variants=variants,
            metric=tc.metric, mode=tc.mode,
            scheduler=tc.scheduler,
            max_concurrent=tc.max_concurrent_trials,
            resources_per_trial=self.run_config.resources_per_trial,
            storage_path=storage,
            max_failures_per_trial=self.run_config.max_failures_per_trial,
            restore_state=(self._restored_state or {}).get("trials"))
        trials = controller.run()
        return ResultGrid(trials, tc.metric, tc.mode, controller.storage)

    def _fit_with_searcher(self) -> ResultGrid:
        """Wave-based execution for Searcher plugins. Note: searcher
        experiments persist per-wave state under wave_N/ and do NOT
        support Tuner.restore() of the whole run (the searcher's model
        state is not checkpointed — reference parity gap shared with
        stateful search plugins)."""
        tc = self.tune_config
        searcher = tc.search_alg
        searcher.set_search_properties(tc.metric, tc.mode, self.param_space)
        wave_size = tc.max_concurrent_trials or 4
        storage = self.run_config.storage_path
        if storage and self.run_config.name:
            storage = os.path.join(storage, self.run_config.name)
        all_trials: List[Trial] = []
        final_storage = storage
        wave = 0
        next_id = 0
        while True:
            batch = []  # [(searcher_id, config)]
            while len(batch) < wave_size:
                sid = f"srch_{next_id}"
                cfg = searcher.suggest(sid)
                if cfg is None:
                    break
                batch.append((sid, cfg))
                next_id += 1
            if not batch:
                break
            controller = TuneController(
                self.trainable,
                param_space=self.param_space,
                variants=[cfg for _, cfg in batch],
                metric=tc.metric, mode=tc.mode,
                scheduler=tc.scheduler,
                max_concurrent=tc.max_concurrent_trials,
                resources_per_trial=self.run_config.resources_per_trial,
                storage_path=(os.path.join(storage, f"wave_{wave}")
                              if storage else None),
                max_failures_per_trial=self.run_config
                .max_failures_per_trial)
            trials = controller.run()
            final_storage = controller.storage
            # feed results back in suggestion order (the controller keeps
            # variant order) so the searcher's model sees this wave before
            # proposing the next
            for (sid, _), t in zip(batch, trials):
                searcher.on_trial_complete(
                    sid, t.last_result if t.last_result else None)
                # disambiguate across waves: each controller restarts its
                # id counter at t0000
                t.trial_id = f"w{wave}_{t.trial_id}"
            all_trials.extend(trials)
            wave += 1
        return ResultGrid(all_trials, tc.metric, tc.mode, final_storage)

    @classmethod
    def restore(cls, storage_path: str,
                trainable: Callable[[Dict[str, Any]], Any], *,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[TuneRunConfig] = None) -> "Tuner":
        """Resume an experiment: finished trials keep their results,
        unfinished ones re-run from their latest in-trial checkpoint."""
        # Prefer the pickle sidecar: JSON mangles non-JSON config values
        # (numpy scalars become repr strings, tuples become lists), which
        # must not be fed back into trainables as live hyperparameters.
        pkl = os.path.join(storage_path, "experiment_state.pkl")
        if os.path.exists(pkl):
            import cloudpickle
            with open(pkl, "rb") as f:
                state = cloudpickle.load(f)
        else:
            state_file = os.path.join(storage_path, "experiment_state.json")
            with open(state_file) as f:
                state = json.load(f)
        if tune_config is None:
            tune_config = TuneConfig(metric=state["metric"],
                                     mode=state["mode"])
        run_config = run_config or TuneRunConfig()
        run_config.storage_path = storage_path
        run_config.name = None
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config)
        tuner._restored_variants = [t["config"] for t in state["trials"]]
        tuner._restored_state = state
        return tuner
