"""Searcher plugin interface + built-in implementations.

Role-equivalent to the reference's Searcher ABC (reference:
python/ray/tune/search/searcher.py — the seam Optuna/HyperOpt/BOHB
plugins implement: ``suggest(trial_id)`` proposes a config,
``on_trial_complete`` feeds the result back). The built-ins cover the
non-plugin reference searchers: BasicVariantSearcher replays
grid/random variant generation through the seam, and HyperOptLikeSearcher
is a dependency-free sequential model-based searcher (TPE-flavored:
sample candidates, prefer the neighborhood of the best observed trials)
demonstrating that sequential-feedback searchers work end to end.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search import (Categorical, Domain, Float, GridSearch,
                                 Integer, generate_variants)


class Searcher:
    """Plugin ABC. ``set_search_properties`` is called once by the Tuner
    with (metric, mode, param_space); then ``suggest`` / ``on_trial_complete``
    alternate (suggestions may arrive in concurrent batches)."""

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        self.metric = metric
        self.mode = mode
        self.param_space = param_space

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config to try; None = the searcher is exhausted."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        """Feedback for a finished trial (None result = errored)."""


class BasicVariantSearcher(Searcher):
    """Grid/random expansion served through the Searcher seam (reference:
    search/basic_variant.py BasicVariantGenerator)."""

    def __init__(self, num_samples: int = 1, seed: Optional[int] = None):
        self._num_samples = num_samples
        self._seed = seed
        self._queue: Optional[List[Dict[str, Any]]] = None

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._queue is None:
            self._queue = list(generate_variants(
                self.param_space, self._num_samples, seed=self._seed))
        return self._queue.pop(0) if self._queue else None


class HyperOptLikeSearcher(Searcher):
    """Sequential model-based search without external deps: after a
    warmup of uniform samples, candidates are drawn and scored by
    proximity to the best-performing observed configs (a TPE-shaped
    heuristic standing in for the reference's Optuna/HyperOpt plugins —
    the seam, feedback loop, and numeric handling are identical)."""

    def set_search_properties(self, metric: str, mode: str,
                              param_space: Dict[str, Any]) -> None:
        super().set_search_properties(metric, mode, param_space)
        grids = [k for k, v in param_space.items()
                 if isinstance(v, GridSearch)
                 or (isinstance(v, dict) and "grid_search" in v)]
        if grids:
            # passing a grid marker through as a live hyperparameter would
            # silently hand the trainable a spec object
            raise ValueError(
                f"HyperOptLikeSearcher does not support grid_search keys "
                f"{grids}; use BasicVariantSearcher or a Domain")

    def __init__(self, num_samples: int = 16, warmup: int = 5,
                 candidates_per_suggest: int = 16,
                 seed: Optional[int] = None):
        self._budget = num_samples
        self._warmup = warmup
        self._n_cand = candidates_per_suggest
        self._rng = random.Random(seed)
        self._suggested = 0
        self._observed: List[tuple] = []  # (score, config)
        self._pending: Dict[str, Dict[str, Any]] = {}

    # -- internals --

    def _sample_config(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.param_space.items():
            out[k] = v.sample(self._rng) if isinstance(v, Domain) else v
        return out

    def _numeric_keys(self) -> List[str]:
        return [k for k, v in self.param_space.items()
                if isinstance(v, (Float, Integer))]

    def _distance(self, a: Dict[str, Any], b: Dict[str, Any]) -> float:
        d = 0.0
        for k, dom in self.param_space.items():
            if isinstance(dom, (Float, Integer)):
                span = float(dom.upper - dom.lower) or 1.0
                d += ((float(a[k]) - float(b[k])) / span) ** 2
            elif isinstance(dom, Categorical):
                d += 0.0 if a[k] == b[k] else 1.0
        return d

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._budget:
            return None
        self._suggested += 1
        if len(self._observed) < self._warmup:
            cfg = self._sample_config()
        else:
            # elite set = best quartile of observations; pick the random
            # candidate closest to an elite (exploit) with an exploration
            # escape hatch
            # key= guards against score ties falling through to dict
            # comparison (TypeError)
            ranked = sorted(self._observed, key=lambda t: t[0])
            elites = [c for _, c in
                      ranked[:max(1, len(ranked) // 4)]]
            cands = [self._sample_config() for _ in range(self._n_cand)]
            if self._rng.random() < 0.25:
                cfg = cands[0]  # explore
            else:
                cfg = min(cands, key=lambda c: min(
                    self._distance(c, e) for e in elites))
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score  # store as minimization
        self._observed.append((score, cfg))
