"""Trial schedulers: FIFO, ASHA, PBT.

Role-equivalent to the reference's TrialScheduler family (reference:
tune/schedulers/trial_scheduler.py, async_hyperband.py ASHAScheduler,
pbt.py:221 PopulationBasedTraining). Decisions are made per-result, between
trial iterations — the controller delivers one result at a time per trial.
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search import resample_key
from ray_tpu.tune.trial import Trial


class Decision:
    CONTINUE = "CONTINUE"
    STOP = "STOP"


class TrialScheduler:
    def set_experiment(self, metric: str, mode: str,
                       param_space: Dict[str, Any]) -> None:
        self.metric = metric
        self.sign = 1.0 if mode == "max" else -1.0
        self.param_space = param_space

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        return Decision.CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        """Trial terminated/errored: schedulers drop per-trial state so
        long sweeps don't accumulate it unboundedly."""

    def score(self, trial_or_result) -> Optional[float]:
        src = trial_or_result.last_result \
            if isinstance(trial_or_result, Trial) else trial_or_result
        v = src.get(self.metric)
        return None if v is None else self.sign * float(v)


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: async_hyperband.py).

    Rung milestones are grace_period * reduction_factor**k. When a trial
    reaches a milestone its score joins the rung; trials below the top
    1/reduction_factor quantile of their rung stop immediately — no
    synchronized brackets, so fast trials never wait on slow ones.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(t)
            t *= reduction_factor
        self._rungs: Dict[int, List[float]] = defaultdict(list)
        self._passed: Dict[str, set] = defaultdict(set)

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        t = int(result.get(self.time_attr, 0))
        if t >= self.max_t:
            return Decision.STOP
        s = self.score(result)
        if s is None:
            return Decision.CONTINUE
        decision = Decision.CONTINUE
        for m in self.milestones:
            if t >= m and m not in self._passed[trial.trial_id]:
                self._passed[trial.trial_id].add(m)
                rung = self._rungs[m]
                rung.append(s)
                cutoff = self._cutoff(rung)
                if cutoff is not None and s < cutoff:
                    decision = Decision.STOP
        return decision

    def _cutoff(self, rung: List[float]) -> Optional[float]:
        if len(rung) < self.rf:
            return None  # not enough evidence at this rung yet
        ordered = sorted(rung, reverse=True)
        k = max(1, len(ordered) // self.rf)
        return ordered[k - 1]

    def on_trial_complete(self, trial_id: str) -> None:
        # rung scores stay (they gate later trials); the per-trial
        # milestone set is only consulted while the trial reports
        self._passed.pop(trial_id, None)


class PopulationBasedTraining(TrialScheduler):
    """PBT with truncation selection (reference: tune/schedulers/pbt.py:221).

    Every ``perturbation_interval`` iterations a trial becomes ready; if it
    sits in the bottom quantile it EXPLOITS a random top-quantile trial
    (clone its checkpoint + config) and EXPLORES the cloned config
    (perturb numeric keys ×1.2 / ×0.8 or resample with prob
    ``resample_probability``). The controller performs the actual actor
    restart when we return an exploit directive via trial._pbt_exploit.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = defaultdict(int)

    def on_trial_complete(self, trial_id: str) -> None:
        self._last_perturb.pop(trial_id, None)

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        t = int(result.get(self.time_attr, 0))
        if t - self._last_perturb[trial.trial_id] < self.interval:
            return Decision.CONTINUE
        self._last_perturb[trial.trial_id] = t
        scored = [(self.score(x), x) for x in all_trials
                  if self.score(x) is not None]
        if len(scored) < 2:
            return Decision.CONTINUE
        scored.sort(key=lambda p: p[0])
        n = len(scored)
        k = max(1, int(n * self.quantile))
        bottom = [x for _, x in scored[:k]]
        top = [x for _, x in scored[-k:]]
        if trial in bottom and trial not in top:
            # Exploit clones the source's STATE; a source that never
            # checkpointed has none to give — cloning would just reset the
            # target to iteration 0 every interval.
            eligible = [t for t in top if t.checkpoint_path is not None]
            if not eligible:
                return Decision.CONTINUE
            source = self.rng.choice(eligible)
            new_config = self._explore(dict(source.config))
            # directive consumed by the controller (restart w/ clone state)
            trial._pbt_exploit = {  # noqa: SLF001
                "source_id": source.trial_id,
                "checkpoint_path": source.checkpoint_path,
                "config": new_config,
            }
        return Decision.CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        for key, space in self.mutations.items():
            if self.rng.random() < self.resample_p:
                fresh = resample_key({key: space}, key, self.rng)
                if fresh is not None:
                    config[key] = fresh
                    continue
            cur = config.get(key)
            if isinstance(cur, (int, float)) and not isinstance(cur, bool):
                factor = 1.2 if self.rng.random() < 0.5 else 0.8
                config[key] = type(cur)(cur * factor) \
                    if isinstance(cur, float) else max(1, int(cur * factor))
            else:
                fresh = resample_key({key: space}, key, self.rng)
                if fresh is not None:
                    config[key] = fresh
        return config


class MedianStoppingRule(TrialScheduler):
    """Median stopping (reference: tune/schedulers/median_stopping_rule.py,
    the Vizier rule): a trial stops at step t when its RUNNING-AVERAGE
    result is worse than the median of the other trials' running averages
    at the same step — a distribution-free early-stopping rule that
    complements ASHA (quantile-per-rung) with a per-step median gate.

    ``grace_period`` steps always run; the rule activates once
    ``min_samples_required`` other trials have reported at step t.
    """

    def __init__(self, *, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> (sum, count) of scores; and per-step running-average
        # snapshots: step -> {trial_id: running_avg}
        self._sums: Dict[str, List[float]] = {}
        self._at_step: Dict[int, Dict[str, float]] = defaultdict(dict)
        self._seen_steps: Dict[str, set] = defaultdict(set)

    def on_trial_complete(self, trial_id: str) -> None:
        # a finished trial's running average can't change: drop its
        # accumulator + dedupe set. The per-step snapshots STAY — they
        # are the median pool that gates later-arriving trials (removing
        # them would let every straggler run ungated once the strong
        # early trials finish).
        self._sums.pop(trial_id, None)
        self._seen_steps.pop(trial_id, None)

    def on_result(self, trial: Trial, result: Dict[str, Any],
                  all_trials: List[Trial]) -> str:
        s = self.score(result)
        if s is None:
            return Decision.CONTINUE
        t = int(result.get(self.time_attr, 0))
        if t in self._seen_steps[trial.trial_id]:
            # restore/replay re-reports a step already counted — feeding
            # it into the running average would double-weight that step
            # and skew the median gate
            return Decision.CONTINUE
        self._seen_steps[trial.trial_id].add(t)
        acc = self._sums.setdefault(trial.trial_id, [0.0, 0])
        acc[0] += s
        acc[1] += 1
        running = acc[0] / acc[1]
        self._at_step[t][trial.trial_id] = running
        if t <= self.grace_period:
            return Decision.CONTINUE
        others = [v for tid, v in self._at_step[t].items()
                  if tid != trial.trial_id]
        if len(others) < self.min_samples:
            return Decision.CONTINUE
        ordered = sorted(others)
        mid = len(ordered) // 2
        # true median: even counts average the middle pair (taking the
        # upper-middle would stop trials that beat the real median)
        median = ordered[mid] if len(ordered) % 2 \
            else (ordered[mid - 1] + ordered[mid]) / 2.0
        if running < median:
            return Decision.STOP
        return Decision.CONTINUE
