"""Wire form of task/actor specs for cross-process submission.

Role-equivalent to the reference's protobuf TaskSpec (reference:
src/ray/protobuf/common.proto via src/ray/common/task/task_spec.h): the
driver-side spec is flattened into a plain dict whose argument values are
pre-serialized with the framework serializer (core/serialization.py) so that

 - nested ObjectRefs inside argument values are discovered and pinned by the
   owner until the task's reply (the reference's inlined-arg borrow
   accounting, transport/dependency_resolver.h), and
 - the executing worker deserializes values through the same path used by
   the object store, registering borrows for refs it retains.

Functions ship by content hash: the pickled function is exported once to the
head KV (reference: python/ray/_private/function_manager.py export path) and
workers cache by key.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Dict, List, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu.core.task_spec import ActorCreationSpec, TaskArg, TaskSpec
from ray_tpu.util import trace_context


def export_function(fn: Any) -> Tuple[str, bytes]:
    """Pickle a function/class; key is the content hash (dedup per job)."""
    blob = cloudpickle.dumps(fn)
    return f"fn:{hashlib.sha1(blob).hexdigest()}", blob


def _args_to_wire(args: List[TaskArg]) -> Tuple[List[dict], list]:
    out = []
    contained = []
    for a in args:
        if a.is_ref:
            out.append({"ref": (a.object_id.binary(), a.owner.binary())})
        else:
            so = serialization.serialize(a.value)
            contained.extend(so.contained_refs)
            out.append({"inline": so.to_bytes()})
    return out, contained


def task_to_wire(spec: TaskSpec, function_key: str = "") -> Tuple[dict, list]:
    """Returns (payload, contained_refs). Caller pins contained_refs until
    the push reply arrives."""
    args, contained = _args_to_wire(spec.args)
    kw = serialization.serialize(spec.kwargs)
    contained.extend(kw.contained_refs)
    payload = {
        "task_id": spec.task_id.binary(),
        "name": spec.name,
        "function_key": function_key,
        "args": args,
        "kwargs": kw.to_bytes(),
        "num_returns": spec.num_returns,
        "streaming": spec.streaming,
        "resources": spec.resources,
        "max_retries": spec.max_retries,
        "retry_exceptions": spec.retry_exceptions,
        "owner": spec.owner.binary() if spec.owner else b"",
        "actor_id": spec.actor_id.binary() if spec.actor_id else None,
        "method_name": spec.method_name,
        "seq_no": spec.seq_no,
        # scheduler-phase anchor: lets the worker separate queueing delay
        # (submit → start) from execution in its recorded spans
        "submit_ts": time.time(),
    }
    # trace_id/parent_span_id/span_id: the child joins the submitter's
    # ambient trace (util/trace_context). Receivers read these with
    # .get(), so frames from a peer without them stay accepted.
    trace_context.stamp(payload)
    return payload, contained


def task_from_wire(p: dict) -> TaskSpec:
    args = []
    for a in p["args"]:
        if "ref" in a:
            oid, owner = a["ref"]
            args.append(TaskArg(is_ref=True, object_id=ObjectID(oid),
                                owner=WorkerID(owner)))
        else:
            args.append(TaskArg(is_ref=False, value=a["inline"]))
    return TaskSpec(
        task_id=TaskID(p["task_id"]),
        name=p["name"],
        function_key=p["function_key"].encode() if p["function_key"] else None,
        args=args,
        kwargs=p["kwargs"],  # serialized blob; executor deserializes
        num_returns=p["num_returns"],
        streaming=p.get("streaming", False),
        resources=p["resources"],
        max_retries=p["max_retries"],
        retry_exceptions=p["retry_exceptions"],
        owner=WorkerID(p["owner"]) if p["owner"] else None,
        actor_id=ActorID(p["actor_id"]) if p["actor_id"] else None,
        method_name=p["method_name"],
        seq_no=p["seq_no"],
    )


def lease_sig(resources) -> int:
    """Stable u64 signature of a plain resource shape — the key of the
    head's native lease pool (transport.cc FastLease). Head and clients
    must compute it identically; only pg-less, default-policy,
    default-runtime-env shapes are pooled."""
    import hashlib
    items = ",".join(f"{k}={float(resources[k]):.6f}"
                     for k in sorted(resources))
    return int.from_bytes(
        hashlib.blake2b(items.encode(), digest_size=8).digest(), "little")


def actor_to_wire(spec: ActorCreationSpec) -> Tuple[dict, list]:
    args, contained = _args_to_wire(spec.args)
    kw = serialization.serialize(spec.kwargs)
    contained.extend(kw.contained_refs)
    payload = {
        "actor_id": spec.actor_id.binary(),
        "name": spec.name,
        "registered_name": spec.registered_name,
        "namespace": spec.namespace,
        "cls_bytes": cloudpickle.dumps(spec.cls),
        "args": args,
        "kwargs": kw.to_bytes(),
        "resources": spec.resources,
        "max_restarts": spec.max_restarts,
        "max_task_retries": spec.max_task_retries,
        "max_concurrency": spec.max_concurrency,
        "concurrency_groups": dict(spec.concurrency_groups),
        "method_groups": dict(spec.method_groups),
        "owner": spec.owner.binary() if spec.owner else b"",
    }
    return payload, contained
