"""Cluster backend — the client-side transport for the multiprocess runtime.

Role-equivalent to the reference's owner-side CoreWorker submission machinery
(reference: src/ray/core_worker/core_worker.cc:2476 SubmitTask, :2557
CreateActor, :2804 SubmitActorTask) with its two transports:

 - _TaskSubmitter: lease-based pipelined submission for normal tasks
   (reference: transport/normal_task_submitter.h:74) — leases are requested
   from the head, cached while the same resource shape has pending work
   (the lease-reuse trick that makes reference task throughput possible),
   and tasks are pushed directly to the leased worker.
 - _ActorSubmitter: direct worker-to-worker pushes with per-handle ordering
   and restart-aware address re-resolution (reference:
   transport/actor_task_submitter.h:75).

`connect_or_start` is the process-supervision role of the reference's Node
(reference: python/ray/_private/node.py:1189 start_gcs_server, :1223
start_raylet): it boots the head and a node daemon as subprocesses when no
address is given.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.runtime")

from ray_tpu.core import config as config_mod
from ray_tpu.core import serialization
from ray_tpu.core._native import ShmStore
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, JobID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import ActorCreationSpec, TaskSpec
from ray_tpu.exceptions import (ActorDiedError, OutOfMemoryError,
                                PlacementGroupUnschedulableError,
                                TaskCancelledError, TaskError,
                                WorkerCrashedError)
from ray_tpu.runtime import wire
from ray_tpu.runtime.object_plane import ObjectPlane
from ray_tpu.runtime.spawn import child_env as _child_env
from ray_tpu.runtime.protocol import (ClientPool, RpcClient, RpcError,
                                      RpcServer)


class _Lease:
    __slots__ = ("lease_id", "worker_addr", "worker_id", "node_addr",
                 "busy", "idle_since", "fast_key")

    def __init__(self, lease_id: str, worker_addr: str, worker_id: bytes,
                 node_addr: str = "", fast_key: Optional[int] = None):
        self.lease_id = lease_id
        self.worker_addr = worker_addr
        self.worker_id = worker_id
        self.node_addr = node_addr
        self.busy = False
        self.idle_since = time.monotonic()
        # set when granted by the head's native lease pool: release can
        # then be a single fast frame served inside the head's C loop
        self.fast_key = fast_key


class _PendingTask:
    __slots__ = ("payload", "spec", "pins", "attempts", "failed_addrs")

    def __init__(self, payload: dict, spec: TaskSpec, pins: list):
        self.payload = payload
        self.spec = spec
        self.pins = pins          # ObjectIDs pinned until reply
        self.attempts = 0
        # addresses this task already failed on: the retry budget counts
        # DISTINCT workers, so a slow corpse-detection window (attempts
        # 1..N all landing on one dead port in microseconds) cannot
        # exhaust max_retries (reference semantics: owner-side
        # max_retries counts EXECUTIONS, task_manager.h:219 — a push
        # that never reached a live worker is not an execution)
        self.failed_addrs: set = set()


class _BatchState:
    """In-flight batch of tasks pushed to one lease in a single frame."""

    __slots__ = ("lease", "tasks", "remaining", "failed")

    def __init__(self, lease: _Lease, tasks: list):
        self.lease = lease
        self.tasks = tasks
        self.remaining = len(tasks)
        self.failed: list = []  # (task, exc) — handled when batch drains


class _TaskSubmitter:
    """Lease-cached pipelined submission for one resource shape."""

    def __init__(self, backend: "ClusterBackend", shape_key: tuple,
                 resources: Dict[str, float],
                 pg: Optional[Tuple[bytes, int]] = None,
                 runtime_env: Optional[dict] = None):
        self.backend = backend
        self.shape_key = shape_key
        self.resources = resources
        self.pg = pg
        self.runtime_env = runtime_env
        self.pending: collections.deque = collections.deque()
        self.leases: Dict[str, _Lease] = {}
        self.requesting = 0
        self._infeasible_since: Optional[float] = None
        self.lock = threading.Lock()
        self._last_submit = 0.0
        self._sig: Optional[int] = None  # lazy wire.lease_sig(resources)

    # -- public --

    def submit(self, payload: dict, spec: TaskSpec, pins: list) -> None:
        now = time.monotonic()
        with self.lock:
            self.pending.append(_PendingTask(payload, spec, pins))
            # Burst deferral: back-to-back submits (<200us apart) let
            # pending ACCUMULATE for the shared flusher, whose _pump then
            # ships proportional combined batches; isolated submits pump
            # inline for latency. Timing-window only: gating on queue
            # depth as well was measured 25% SLOWER on a loaded 1-core
            # host (every submit deferred -> flusher handoff per pump and
            # batches that serialize against execution).
            bursting = now - self._last_submit < 0.0002 \
                and config_mod.GlobalConfig.task_burst_defer
            self._last_submit = now
        if bursting:
            self.backend._defer_actor_flush(self)
        else:
            self._pump()

    # flusher-thread entry (shared with _ActorSubmitter deferrals)
    def _flush(self) -> None:
        self._pump()

    def cancel(self, task_id: bytes) -> bool:
        with self.lock:
            for t in list(self.pending):
                if t.payload["task_id"] == task_id:
                    self.pending.remove(t)
                    self.backend._store_task_error(
                        t.spec, TaskCancelledError(task_id.hex()), t.pins)
                    return True
        for lease in list(self.leases.values()):
            try:
                self.backend.peers.get(lease.worker_addr).call(
                    "cancel_task", {"task_id": task_id}, timeout=5.0)
            except RpcError:
                pass
        return False

    # -- internals --

    def _pump(self) -> None:
        """Assign pending tasks to idle leases; request more leases if short.

        Lease requests in flight are capped (reference: the submitter
        pipelines at most max_pending_lease_requests_per_scheduling_category
        lease requests, normal_task_submitter.h:74) — without the cap, a
        1000-task batch spawns a requester thread per task and the retry
        storm starves the head's RPC pool of the pushes/replies that
        actually drain the queue (measured: 75x throughput loss).
        """
        spawn = 0
        while True:
            with self.lock:
                if not self.pending:
                    break
                lease = next((l for l in self.leases.values() if not l.busy),
                             None)
                if lease is None:
                    if not self.backend._closed:
                        cap = config_mod.GlobalConfig \
                            .max_pending_lease_requests
                        want = min(len(self.pending), cap)
                        spawn = max(0, want - self.requesting)
                        self.requesting += spawn
                    break
                # Parallelism-neutral batching: pack at most an equal
                # share of the queue onto this lease (pending divided by
                # every lease that exists or is being requested). A lease
                # is a concurrency slot — packing a small burst onto the
                # FIRST grant serialized work that belonged on other
                # workers (verified regression: 4 sleeping tasks on one
                # worker). With the share rule a burst of B <= slots tasks
                # batches as 1 per lease, while a 1000-task burst ships in
                # 32-task frames that amortize the per-frame scheduler
                # round-trip without changing who-runs-what.
                slots = max(1, len(self.leases) + self.requesting)
                share = -(-len(self.pending) // slots)  # ceil div
                n = min(share, config_mod.GlobalConfig.task_push_batch)
                tasks = [self.pending.popleft() for _ in range(n)]
                lease.busy = True
            self._push_batch(lease, tasks)
        for _ in range(spawn):
            threading.Thread(target=self._request_lease, daemon=True,
                             name="lease-req").start()

    def _fast_acquire(self) -> Optional[dict]:
        """Try the head's native lease pool (one binary frame served inside
        the head's C loop — transport.cc FOP_LEASE_ACQ). None on miss or
        ineligibility; the Python RPC path then arms the pool server-side
        so the next request hits."""
        if (self.pg is not None or self.runtime_env is not None
                or not self.backend._head_fast
                or not config_mod.GlobalConfig.fast_lease_client):
            return None
        from ray_tpu.runtime.protocol import _chaos_should_fail
        if _chaos_should_fail("request_lease"):
            return None  # chaos tests target the Python path; don't dodge it
        from ray_tpu.runtime import protocol_native as _pn
        if self._sig is None:
            self._sig = wire.lease_sig(self.resources)
        try:
            status, blob = self.backend.head.call_fast(
                _pn.FAST_LEASE_ACQ, key=_pn._U64.pack(self._sig),
                timeout=5.0)
        except Exception:  # noqa: BLE001 — any failure: use the RPC path
            return None
        if status != 1:
            return None
        import pickle
        try:
            return pickle.loads(blob)
        except Exception:  # noqa: BLE001
            return None

    def _request_lease(self) -> None:
        try:
            while not self.backend._closed:
                with self.lock:
                    if not self.pending:
                        return
                grant = self._fast_acquire()
                if grant is not None:
                    lease = _Lease(grant["lease_id"], grant["worker_addr"],
                                   grant["worker_id"],
                                   node_addr=grant.get("node_addr", ""),
                                   fast_key=grant.get("fast_key"))
                    if self.backend.is_dead_addr(lease.worker_addr):
                        # pooled corpse: release via the PYTHON path so the
                        # head invalidates it instead of re-pooling
                        self._release_to_cluster(lease, fast_ok=False)
                        time.sleep(0.1)
                        continue
                    with self.lock:
                        self.leases[lease.lease_id] = lease
                    break
                with self.lock:
                    n_pending = len(self.pending)
                payload = {"resources": self.resources,
                           "pending": n_pending}
                if self.runtime_env is not None:
                    payload["runtime_env"] = self.runtime_env
                if self.pg is not None:
                    payload["pg_id"], payload["bundle_index"] = self.pg
                try:
                    grant = self.backend.head.call_retrying(
                        "request_lease", payload)
                except RpcError:
                    time.sleep(0.2)
                    continue
                if grant.get("infeasible"):
                    # infeasible NOW is the autoscaler's signal to add a
                    # node (the head recorded the demand): keep waiting
                    # for a grace period before declaring it impossible
                    # (reference: infeasible tasks pend + autoscaler
                    # warning, not immediate failure)
                    if self._infeasible_since is None:
                        self._infeasible_since = time.monotonic()
                        logger.warning(
                            "no node can currently satisfy resources %s; "
                            "waiting %.0fs for the cluster to scale",
                            self.resources,
                            config_mod.GlobalConfig.infeasible_grace_s)
                    elif time.monotonic() - self._infeasible_since > \
                            config_mod.GlobalConfig.infeasible_grace_s:
                        grace = config_mod.GlobalConfig.infeasible_grace_s
                        # reset so a LATER submission of this shape gets a
                        # fresh grace window (the submitter object persists
                        # per shape)
                        self._infeasible_since = None
                        self._fail_pending(TaskError(
                            "PlacementError",
                            f"no node can satisfy resources "
                            f"{self.resources} (waited {grace:.0f}s)",
                            "<scheduler>"))
                        return
                    time.sleep(0.2)
                    continue
                self._infeasible_since = None
                if grant.get("retry"):
                    time.sleep(0.05)
                    continue
                lease = _Lease(grant["lease_id"], grant["worker_addr"],
                               grant["worker_id"],
                               node_addr=grant.get("node_addr", ""))
                if self.backend.is_dead_addr(lease.worker_addr):
                    # the head re-granted a worker we watched die (its
                    # corpse detection hasn't fired yet): hand it back
                    # and wait out the window instead of burning a push
                    self._release_to_cluster(lease)
                    time.sleep(0.1)
                    continue
                with self.lock:
                    self.leases[lease.lease_id] = lease
                break
        finally:
            with self.lock:
                self.requesting = max(0, self.requesting - 1)
            # Always re-pump: a task may have been enqueued in the window
            # where this thread still counted toward `requesting` but was
            # about to exit (e.g. the early return on empty pending).
            self._pump()

    def _fail_pending(self, exc: BaseException) -> None:
        with self.lock:
            tasks = list(self.pending)
            self.pending.clear()
        for t in tasks:
            self.backend._store_task_error(t.spec, exc, t.pins)

    def _push_batch(self, lease: _Lease, tasks: list) -> None:
        now = time.time()
        for t in tasks:
            t.attempts += 1
            # scheduler-phase marker: lease assignment time, carried on the
            # wire so the worker's sched:: span can split queue vs transport
            t.payload["lease_ts"] = now
        state = _BatchState(lease, tasks)
        client = self.backend.peers.get(lease.worker_addr)
        cb = lambda i, v, e: self._on_reply(state, i, v, e)  # noqa: E731
        if len(tasks) > 1 and config_mod.GlobalConfig.task_combined_push:
            # combined fast path: one frame + one pickle each way for the
            # whole batch (worker half: worker_main.handle_push_task_batch)
            client.call_combined_cb(
                "push_task_batch", [t.payload for t in tasks], cb)
        else:
            client.call_batch_cb("push_task",
                                 [t.payload for t in tasks], cb)

    def _on_reply(self, state: _BatchState, i: int, value,
                  exc: Optional[BaseException]) -> None:
        task = state.tasks[i]
        if exc is None:
            self.backend._store_task_reply(task.spec, value, task.pins)
        else:
            state.failed.append((task, exc))
        with self.lock:
            state.remaining -= 1
            done = state.remaining == 0
            if done and not state.failed:
                state.lease.busy = False
                state.lease.idle_since = time.monotonic()
        if not done:
            return
        if state.failed:
            # Transport failure: the leased worker is gone (crash/chaos).
            # Handled on a fresh thread: this callback runs on the transport
            # dispatcher, and the failure path makes blocking RPCs
            # (release_lease / worker_fate) the dispatcher must not wait on.
            threading.Thread(target=self._on_push_failed, args=(state,),
                             daemon=True, name="push-fail").start()
        else:
            self._pump()

    def _on_push_failed(self, state: _BatchState) -> None:
        self._drop_lease(state.lease)
        # the worker behind this ADDRESS is gone: every cached lease on it
        # is a corpse too — retrying onto one would burn the whole retry
        # budget in microseconds (native transport fails dead-addr pushes
        # instantly)
        dead_addr = state.lease.worker_addr
        self.backend.mark_dead_addr(dead_addr)
        with self.lock:
            stale = [l for l in self.leases.values()
                     if l.worker_addr == dead_addr]
        for l in stale:
            self._drop_lease(l)
        retry = []
        for task, exc in state.failed:
            if dead_addr in task.failed_addrs:
                # repeat hit on an address this task ALREADY died on: the
                # push never reached a live worker, so it doesn't consume
                # retry budget (budget counts distinct leases/addresses)
                task.attempts -= 1
            else:
                task.failed_addrs.add(dead_addr)
            if isinstance(exc, RpcError) and \
                    task.attempts <= task.spec.max_retries:
                retry.append(task)
                continue
            fate = self._worker_fate(state.lease)
            if fate == "oom":
                err: BaseException = OutOfMemoryError(
                    f"worker was OOM-killed running {task.spec.name} "
                    f"(attempt {task.attempts}); raise the task's memory "
                    f"request or the node's memory_usage_threshold")
            else:
                err = WorkerCrashedError(
                    f"worker died running {task.spec.name} "
                    f"(attempt {task.attempts}): {exc}")
            self.backend._store_task_error(task.spec, err, task.pins)
        if retry:
            with self.lock:
                # preserve original submission order at the queue front
                self.pending.extendleft(reversed(retry))
        self._pump()

    def _worker_fate(self, lease: _Lease) -> Optional[str]:
        """Ask the worker's node daemon WHY it died (the submitter only
        sees a dropped connection; the node records OOM kills —
        reference: raylet death-cause propagation into task errors)."""
        if not lease.node_addr:
            return None
        try:
            return self.backend.peers.get(lease.node_addr).call(
                "worker_fate",
                {"worker_id": WorkerID(lease.worker_id).hex()},
                timeout=5.0)
        except RpcError:
            return None

    def _release_to_cluster(self, lease: _Lease, timeout: float = 5.0,
                            fast_ok: bool = True) -> None:
        """Release via the head; if the head forgot the lease (it restarted
        and leases are process state), return the worker straight to its
        node daemon so the pool slot isn't leaked.

        fast_ok: a healthy-worker release of a native-pool grant goes back
        as one fast frame (the head's C loop re-pools it instantly, zero
        Python). Corpse releases pass fast_ok=False so the head's Python
        invalidates the grant instead of re-pooling a dead worker.

        The fallback fires ONLY on an explicit "unknown lease" reply. A
        transport failure is ambiguous — the head may have completed the
        release after we gave up, after which the worker can be re-leased
        to someone else, and a late direct return would hand one worker to
        two leases. Leaking a slot on an unreachable head is the safe side.
        """
        if fast_ok and lease.fast_key is not None \
                and self.backend._head_fast \
                and config_mod.GlobalConfig.fast_lease_client:
            from ray_tpu.runtime import protocol_native as _pn
            try:
                status, _ = self.backend.head.call_fast(
                    _pn.FAST_LEASE_REL, key=_pn._U64.pack(lease.fast_key),
                    timeout=timeout)
                if status == 1:
                    return
            except Exception:  # noqa: BLE001 — fall through to the RPC
                pass
        try:
            known = bool(self.backend.head.call(
                "release_lease", {"lease_id": lease.lease_id},
                timeout=timeout))
        except RpcError:
            return
        if not known and lease.node_addr:
            # "unknown lease" has two causes: the head restarted (fall back
            # — nobody else will free the worker), or THIS head already
            # reclaimed it via our own connection blip (_on_client_disconnect)
            # — in which case the worker may be re-leased already and a
            # direct return would hand it to two leases. Lease ids embed the
            # granting head's incarnation; only fall back across a change.
            try:
                pong = self.backend.head.call("ping", timeout=timeout)
            except RpcError:
                return
            inc = pong.get("incarnation") if isinstance(pong, dict) else None
            if inc is None or lease.lease_id.startswith(f"l{inc}."):
                return
            try:
                self.backend.peers.get(lease.node_addr).call(
                    "return_worker", {"worker_id": lease.worker_id},
                    timeout=timeout)
            except RpcError:
                pass

    def _drop_lease(self, lease: _Lease) -> None:
        with self.lock:
            self.leases.pop(lease.lease_id, None)
        self.backend.peers.invalidate(lease.worker_addr)
        # corpse path: never fast-release (the head must invalidate the
        # grant, not hand the dead worker to the next acquirer)
        self._release_to_cluster(lease, fast_ok=False)

    def reap_idle(self, linger_s: float) -> None:
        now = time.monotonic()
        with self.lock:
            idle = [l for l in self.leases.values()
                    if not l.busy and now - l.idle_since > linger_s
                    and not self.pending]
        for lease in idle:
            with self.lock:
                if lease.busy:
                    continue
                self.leases.pop(lease.lease_id, None)
            self._release_to_cluster(lease)

    def shutdown(self) -> None:
        with self.lock:
            leases = list(self.leases.values())
            self.leases.clear()
        for lease in leases:
            self._release_to_cluster(lease, timeout=2.0)


class _ActorSubmitter:
    """Per-actor ordered submission with restart-aware re-resolution."""

    def __init__(self, backend: "ClusterBackend", actor_id: ActorID,
                 creation_pins: Optional[list] = None):
        self.backend = backend
        self.actor_id = actor_id
        self.address: Optional[str] = None
        self.state = "RESOLVING"
        self.dead_reason = ""
        self.pending: collections.deque = collections.deque()
        self.lock = threading.Lock()
        self.resolving = False
        self._flushing = False
        self._last_submit = 0.0
        self.creation_pins = creation_pins or []
        if self.creation_pins:
            self._ensure_resolver()

    def submit(self, payload: dict, spec: TaskSpec, pins: list) -> None:
        t = _PendingTask(payload, spec, pins)
        now = time.monotonic()
        with self.lock:
            if self.state == "DEAD":
                dead = True
                bursting = False
            else:
                dead = False
                self.pending.append(t)
                # burst detection (same idea as the transport's write
                # coalescing): back-to-back submits defer to the shared
                # flusher thread, which drains them as ONE batched frame;
                # isolated submits flush inline for latency
                bursting = now - self._last_submit < 0.0002
                self._last_submit = now
        if dead:
            self.backend._store_task_error(
                spec, ActorDiedError(self.actor_id.hex(), self.dead_reason),
                pins)
            return
        if self.state == "ALIVE":
            if bursting:
                self.backend._defer_actor_flush(self)
            else:
                self._flush()
        else:
            self._ensure_resolver()

    def _ensure_resolver(self) -> None:
        with self.lock:
            if self.resolving:
                return
            self.resolving = True
        threading.Thread(target=self._resolve_loop, daemon=True,
                         name="actor-resolve").start()

    def _resolve_loop(self) -> None:
        try:
            while not self.backend._closed:
                try:
                    info = self.backend.head.call_retrying(
                        "get_actor", {"actor_id": self.actor_id.binary()})
                except RpcError:
                    time.sleep(0.2)
                    continue
                if info is None:
                    self._mark_dead("actor not registered")
                    return
                if info["state"] == "ALIVE":
                    with self.lock:
                        self.address = info["address"]
                        self.state = "ALIVE"
                    self._release_creation_pins()
                    self._flush()
                    return
                if info["state"] == "DEAD":
                    self._mark_dead(info.get("reason", "actor died"))
                    self._release_creation_pins()
                    return
                time.sleep(0.02)
        finally:
            with self.lock:
                self.resolving = False

    def _release_creation_pins(self) -> None:
        with self.lock:
            pins, self.creation_pins = self.creation_pins, []
        for oid in pins:
            self.backend.worker.refcounter.on_serialized_ref_done(oid)

    def _requeue_ordered(self, task: _PendingTask) -> None:
        """Re-insert a failed in-flight task preserving seq_no order —
        several pipelined calls can fail together and their completion
        callbacks run in arbitrary order, so a plain appendleft would
        replay them reversed (per-handle ordering contract, reference:
        ActorSchedulingQueue seq enforcement)."""
        with self.lock:
            items = list(self.pending)
            items.append(task)
            items.sort(key=lambda t: t.spec.seq_no)
            self.pending = collections.deque(items)

    def _mark_dead(self, reason: str) -> None:
        with self.lock:
            self.state = "DEAD"
            self.dead_reason = reason
            tasks = list(self.pending)
            self.pending.clear()
        for t in tasks:
            self.backend._store_task_error(
                t.spec, ActorDiedError(self.actor_id.hex(), reason), t.pins)

    def _flush(self) -> None:
        # Single-flusher discipline: exactly one thread drains the queue at
        # a time, so tasks hit the wire (and the actor's FIFO queue) in
        # seq_no order even when the resolver thread and a submitting user
        # thread race into _flush together.
        while True:
            with self.lock:
                if self._flushing:
                    return
                self._flushing = True
            try:
                batch_max = config_mod.GlobalConfig.task_push_batch
                while True:
                    with self.lock:
                        if self.state != "ALIVE" or not self.pending:
                            break
                        tasks = [self.pending.popleft() for _ in
                                 range(min(len(self.pending), batch_max))]
                        addr = self.address
                    for t in tasks:
                        t.attempts += 1
                    try:
                        client = self.backend.peers.get(addr)
                        # one frame for the whole run of queued calls; the
                        # actor executes them in seq order either way
                        if len(tasks) > 1 and \
                                config_mod.GlobalConfig.task_combined_push:
                            client.call_combined_cb(
                                "push_task_batch",
                                [t.payload for t in tasks],
                                lambda i, v, e, ts=tasks:
                                    self._on_reply(ts[i], v, e))
                        else:
                            client.call_batch_cb(
                                "push_task", [t.payload for t in tasks],
                                lambda i, v, e, ts=tasks:
                                    self._on_reply(ts[i], v, e))
                    except BaseException as e:  # noqa: BLE001
                        # Synchronous submit failure (stale address etc):
                        # popped tasks must NOT vanish — requeue in order
                        # and re-resolve (critical on the deferred-flush
                        # path, where no caller would see the raise). The
                        # attempt COUNTS: a deterministic failure (actor
                        # reported ALIVE at an unreachable address) must
                        # exhaust the retry budget, not loop forever.
                        # KeyboardInterrupt/SystemExit re-raise AFTER the
                        # requeue below, so an interrupted inline flush
                        # still leaves every task accounted for.
                        for t in tasks:
                            if t.attempts <= t.spec.max_retries:
                                self._requeue_ordered(t)
                            else:
                                self.backend._store_task_error(
                                    t.spec,
                                    ActorDiedError(
                                        self.actor_id.hex(),
                                        f"submit to {addr} kept failing: "
                                        f"{e!r}"),
                                    t.pins)
                        with self.lock:
                            self.address = None
                            if self.state == "ALIVE":
                                self.state = "RESOLVING"
                        self._ensure_resolver()
                        if isinstance(e, (KeyboardInterrupt, SystemExit)):
                            raise
                        break
            finally:
                with self.lock:
                    self._flushing = False
            with self.lock:
                if self.state != "ALIVE" or not self.pending:
                    return
                # work arrived while we were clearing the flag — go again

    def _on_reply(self, task: _PendingTask, value,
                  exc: Optional[BaseException]) -> None:
        if exc is None:
            self.backend._store_task_reply(task.spec, value, task.pins)
            return
        # connection to the actor broke: restart-aware handling
        # (reference: ActorTaskSubmitter disconnect path + max_task_retries,
        # transport/actor_task_submitter.h:75)
        with self.lock:
            self.address = None
            if self.state == "ALIVE":
                self.state = "RESOLVING"
        if isinstance(exc, RpcError) and task.attempts <= task.spec.max_retries:
            self._requeue_ordered(task)
            self._ensure_resolver()
        else:
            self.backend._store_task_error(
                task.spec,
                ActorDiedError(self.actor_id.hex(),
                               f"actor task {task.spec.name} interrupted: "
                               f"{exc}"),
                task.pins)
            self._ensure_resolver()


class ClusterBackend:
    """Backend interface implementation over the multiprocess runtime."""

    def __init__(self, worker, head_addr: str, role: str,
                 shm_name: Optional[str] = None,
                 worker_id: Optional[WorkerID] = None,
                 owned_procs: Optional[list] = None):
        self.worker = worker
        self.role = role
        self.head = RpcClient(head_addr, name=f"{role}->head")
        self.head_addr = head_addr
        self.peers = ClientPool(name=f"{role}-peers")
        self._closed = False
        self._owned_procs = owned_procs or []
        self._submitters: Dict[tuple, _TaskSubmitter] = {}
        self._actor_submitters: Dict[ActorID, _ActorSubmitter] = {}
        self._actor_name_cache: Dict[str, dict] = {}
        self._export_epoch = os.urandom(8).hex()  # per-backend cache tag
        # working_dir path -> uploaded package uri (upload-once semantics,
        # reference: runtime_env working_dir URI cache)
        self._rtenv_uploads: Dict[str, str] = {}
        # owner-side lineage: return-object id -> creating TaskSpec, so a
        # lost shm object can be rebuilt by re-executing its task
        # (reference: ObjectRecoveryManager, object_recovery_manager.h:38,
        # lineage pinned in TaskManager bounded by max_lineage_bytes)
        self._lineage: "collections.OrderedDict[bytes, TaskSpec]" = \
            collections.OrderedDict()
        self._lineage_cap = 8192
        self._lock = threading.Lock()
        # worker addresses observed dead (push transport failure), with
        # expiry: lease grants naming one are released and re-requested
        # instead of burning a push on a known corpse — covers the window
        # between a worker's death and the node/head noticing it
        self._dead_addrs: Dict[str, float] = {}
        self._dead_addrs_lock = threading.Lock()

        worker.worker_id = worker_id or WorkerID.from_random()

        # Native-KV probe: with the C++ transport on both ends, kv/ping
        # traffic is served inside the head's event loop (fast frames —
        # protocol_native.call_fast). One ping detects it; a pure-Python
        # peer answers with an error and we stay on the pickle path.
        self._head_fast = False
        if hasattr(self.head, "call_fast"):
            try:
                from ray_tpu.runtime import protocol_native as _pn
                status, _ = self.head.call_fast(_pn.FAST_PING, timeout=5.0)
                self._head_fast = status == 1
            except Exception:  # noqa: BLE001 — fall back to pickle path
                self._head_fast = False

        # node registry + local shm store
        nodes = self.head.call_retrying("list_nodes")
        node_addrs = {n["node_id"]: n["address"] for n in nodes}
        node_shm = {n["node_id"]: n["shm_name"] for n in nodes}
        if shm_name is None:
            alive = [n for n in nodes if n["alive"]]
            if not alive:
                raise RuntimeError("cluster has no alive nodes")
            local = alive[0]
            shm_name = local["shm_name"]
            local_node_id = local["node_id"]
        else:
            local_node_id = next(
                (n["node_id"] for n in nodes if n["shm_name"] == shm_name),
                nodes[0]["node_id"] if nodes else "")
        store = ShmStore.attach(shm_name)
        self.object_plane = ObjectPlane(
            worker, local_node_id, store, self.head, node_addrs, node_shm)
        self.local_node_id = local_node_id

        # streaming-generator states by task id (reference: the owner-side
        # streaming generator metadata in TaskManager)
        self._streams: Dict[bytes, Any] = {}

        # owner service: every process is reachable for object resolution
        self.server = RpcServer({
            "get_object": self.object_plane.handle_get_object,
            "add_location": self.object_plane.handle_add_location,
            "add_borrower": self.object_plane.handle_add_borrower,
            "remove_borrower": self.object_plane.handle_remove_borrower,
            "stream_item": self._h_stream_item,
            "log_batch": self._h_log_batch,
            "borrow_batch": self._h_borrow_batch,
            "ping": lambda p, c: "pong",
        }, name=f"{role}-owner")
        self.kv_put(f"addr:{worker.worker_id.hex()}",
                    self.server.address)

        # borrowed-ref owner map for unborrow notifications
        self._borrowed_owner: Dict[ObjectID, WorkerID] = {}
        worker.refcounter.notify_owner_unborrow = self._notify_unborrow
        # Borrow traffic batcher: add/remove-borrower notifications queue
        # here and flush as one RPC per owner, preserving per-owner FIFO
        # order (adds for refs nested in a container always reach the
        # owner before the container's own unborrow, so the owner can
        # never free the container — and with it the nested containment
        # borrows — while our nested adds are still in flight). Turns the
        # deserialize/drop of a 10k-ref container from 10k round trips
        # into a handful (reference: batched WaitForRefRemoved pubsub).
        self._borrow_q: collections.deque = collections.deque()
        self._borrow_wake = threading.Event()
        # serializes flushers: concurrent drains could split one owner's
        # add/remove pair across two in-flight RPCs and reorder them
        self._borrow_flush_lock = threading.Lock()
        self._borrow_thread = threading.Thread(
            target=self._borrow_flush_loop, daemon=True,
            name=f"{role}-borrow")
        self._borrow_thread.start()

        self._reaper = threading.Thread(target=self._reap_loop, daemon=True,
                                        name="lease-reaper")
        self._reaper.start()

        # shared actor-submit flusher: bursting submitters defer here so
        # a tight .remote() loop coalesces into batched frames (the GIL
        # timeslice between the submitting thread and this one sets the
        # natural batch size). Dedicated lock: this is the hottest submit
        # path — it must not contend on the backend-wide _lock.
        from ray_tpu.runtime.protocol import NATIVE_TRANSPORT
        self._native_transport = NATIVE_TRANSPORT  # fixed at process start
        self._aflush_subs: set = set()
        self._aflush_lock = threading.Lock()
        self._aflush_wake = threading.Event()
        self._aflush_thread = threading.Thread(
            target=self._actor_flush_loop, daemon=True,
            name=f"{role}-aflush")
        self._aflush_thread.start()

        # telemetry: metric snapshots + task-event spans → head
        # (reference: metrics agent push + TaskEventBuffer→GcsTaskManager)
        from ray_tpu.runtime.events import TaskEventBuffer
        self.event_buffer = TaskEventBuffer()
        self._telemetry = threading.Thread(target=self._telemetry_loop,
                                           daemon=True,
                                           name=f"{role}-telemetry")
        self._telemetry.start()
        # continuous wall-clock stack sampler for this process (worker or
        # driver); windows drain through _flush_telemetry into the head's
        # ProfileStore ('python -m ray_tpu profile')
        try:
            from ray_tpu.util import stack_profiler
            stack_profiler.ensure_started()
        except Exception:  # noqa: BLE001 — profiling never stops connect
            pass
        # structured log plane for DRIVERS (workers install theirs in
        # worker_main with the node/worker identity the daemon passed;
        # installing a generic one here first would shadow it)
        if role == "driver":
            try:
                from ray_tpu.util import log_plane
                wid12 = self.worker.worker_id.hex()[:12]
                log_plane.ensure_started(
                    role="driver",
                    node=(self.local_node_id or "")[:12], worker=wid12,
                    log_dir=log_plane.session_log_dir(
                        os.environ.get("RTPU_SESSION", "")),
                    filename=f"driver-{wid12}.log")
            except Exception:  # noqa: BLE001 — never stops connect
                pass
            # XLA compile tracker for DRIVERS (workers install theirs
            # in worker_main, same shadowing argument as the log plane;
            # jax listeners only hook if/when this process imports jax)
            try:
                from ray_tpu.util import compile_tracker
                compile_tracker.ensure_started(
                    role="driver",
                    node=(self.local_node_id or "")[:12],
                    worker=self.worker.worker_id.hex()[:12])
            except Exception:  # noqa: BLE001 — never stops connect
                pass

    def _defer_actor_flush(self, sub) -> None:
        if not self._native_transport:
            # the pure-Python client connects SYNCHRONOUSLY inside the
            # flush; one unreachable actor on the shared flusher thread
            # would head-of-line-block every other bursting actor for a
            # full connect timeout. The native transport connects
            # asynchronously, so only it gets the shared-thread deferral.
            sub._flush()
            return
        with self._aflush_lock:
            self._aflush_subs.add(sub)
        self._aflush_wake.set()

    def _actor_flush_loop(self) -> None:
        while not self._closed:
            self._aflush_wake.wait(timeout=0.5)
            self._aflush_wake.clear()
            self._drain_actor_flushes()

    def _drain_actor_flushes(self) -> None:
        with self._aflush_lock:
            subs, self._aflush_subs = self._aflush_subs, set()
        for sub in subs:
            try:
                sub._flush()
            except Exception:  # noqa: BLE001 — _flush requeues its tasks
                pass           # and re-resolves on submit failures

    def _telemetry_loop(self) -> None:
        from ray_tpu.core.config import GlobalConfig
        interval = max(GlobalConfig.metrics_export_period_s, 0.1)
        while not self._closed:
            time.sleep(interval)
            self._flush_telemetry()

    def _flush_telemetry(self) -> None:
        from ray_tpu.util import metrics as metrics_mod
        try:
            # scheduler-backlog gauge: tasks enqueued but not yet pushed to
            # a leased worker (len() is atomic; no submitter locks needed)
            depth = sum(len(s.pending)
                        for s in list(self._submitters.values()))
            metrics_mod.queue_depth_gauge().set(depth)
            snap = metrics_mod.snapshot()
            events = self.event_buffer.drain()
            # bounded object-table summary for `list objects` (reference:
            # util/state object listing; owners are authoritative, so each
            # process reports its own table). snapshot(limit=...) keeps
            # the under-lock work O(limit), not O(all refs).
            tracked = self.worker.refcounter.num_tracked()
            sample = [{"object_id": oid, **counts}
                      for oid, counts in
                      self.worker.refcounter.snapshot(limit=50).items()]
            objects = {"tracked": tracked, "sample": sample,
                       # reconciled per-object directory of everything this
                       # process sealed into shm/spill ('ray_tpu memory')
                       **self.object_plane.directory_export()}
            # cluster events staged process-side (spill overflows) are
            # sequenced by the head's journal when they land
            journal = self.object_plane.drain_journal()
            # accelerator memory rides the worker flush: only worker
            # processes have jax live (the node daemon must never import
            # it), so HBM gauges originate here, tagged per worker since
            # device indices are process-local
            from ray_tpu.runtime.hw_sampler import tpu_memory_samples
            samples = tpu_memory_samples()
            wid12 = self.worker.worker_id.hex()[:12]
            for s in samples:
                s.setdefault("tags", {})["worker"] = wid12
            # LLM request records (llm/request_log.py flight recorders):
            # drained only when some engine in this process already
            # imported the module — resolved via sys.modules so
            # non-serving workers never pull it in
            reqlog = sys.modules.get("ray_tpu.llm.request_log")
            llm_requests = reqlog.drain_all_exports() \
                if reqlog is not None else []
            # this process's collapsed-stack profiler window (None when
            # profiling is disabled or nothing was sampled)
            from ray_tpu.util import stack_profiler
            profiles = stack_profiler.drain_export()
            # this process's structured-log window + staged error-storm
            # events (None/[] when the plane is off or nothing logged)
            from ray_tpu.util import log_plane
            logs = log_plane.drain_export()
            journal = journal + log_plane.drain_journal_events()
            # this process's XLA compile window + staged compile_storm /
            # invariant-breach events (None/[] when the tracker is off
            # or this process never compiled anything)
            from ray_tpu.util import compile_tracker
            compiles = compile_tracker.drain_export()
            journal = journal + compile_tracker.drain_journal_events()
            if snap or events or tracked or samples or llm_requests \
                    or journal or profiles or logs or compiles:
                self.head.oneway("telemetry_push", {
                    "worker": self.worker.worker_id.hex(),
                    "role": self.role,
                    "node": self.local_node_id,
                    "metrics": snap, "events": events,
                    "objects": objects, "samples": samples,
                    "llm_requests": llm_requests, "journal": journal,
                    "profiles": profiles, "logs": logs,
                    "compiles": compiles})
        except Exception:  # noqa: BLE001 — telemetry must never kill
            pass

    # ------------------------------------------------------------ head KV

    def kv_put(self, key: str, value: Any, overwrite: bool = True) -> bool:
        """Head KV write — native fast frame when both ends are C++
        transport (no Python runs on the head), pickle RPC otherwise."""
        if self._head_fast:
            import pickle
            from ray_tpu.runtime import protocol_native as _pn
            try:
                status, _ = self._fast_retry(
                    _pn.FAST_PUT, key.encode(),
                    pickle.dumps(value, protocol=5),
                    flags=1 if overwrite else 0)
                return status == 1
            except RpcError:
                pass  # head unreachable via fast path: use retrying RPC
        return bool(self.head.call_retrying("kv_put", {
            "key": key, "value": value, "overwrite": overwrite}))

    def kv_get(self, key: str) -> Any:
        if self._head_fast:
            import pickle
            from ray_tpu.runtime import protocol_native as _pn
            try:
                status, raw = self._fast_retry(_pn.FAST_GET, key.encode())
                return pickle.loads(raw) if status == 1 else None
            except RpcError:
                pass
        return self.head.call_retrying("kv_get", {"key": key})

    def kv_del(self, key: str) -> bool:
        if self._head_fast:
            from ray_tpu.runtime import protocol_native as _pn
            try:
                status, _ = self._fast_retry(_pn.FAST_DEL, key.encode())
                return status == 1
            except RpcError:
                pass
        return bool(self.head.call("kv_del", {"key": key}, timeout=5.0))

    def kv_keys(self, prefix: str = "") -> list:
        keys = self.head.call_retrying("kv_keys", {"prefix": prefix})
        return list(keys or [])

    #: how long a dead address stays blacklisted — a fresh worker at the
    #: same host gets a new port, so false positives only cost one
    #: re-request; sized to the worst observed corpse-detection window
    DEAD_ADDR_TTL_S = 5.0

    def mark_dead_addr(self, addr: str) -> None:
        with self._dead_addrs_lock:
            self._dead_addrs[addr] = time.monotonic() + self.DEAD_ADDR_TTL_S
            if len(self._dead_addrs) > 256:
                now = time.monotonic()
                self._dead_addrs = {a: t for a, t in
                                    self._dead_addrs.items() if t > now}

    def is_dead_addr(self, addr: str) -> bool:
        with self._dead_addrs_lock:
            t = self._dead_addrs.get(addr)
            if t is None:
                return False
            if t <= time.monotonic():
                del self._dead_addrs[addr]
                return False
            return True

    def _fast_retry(self, op: int, key: bytes, val: bytes = b"",
                    flags: int = 0) -> tuple:
        from ray_tpu.runtime.protocol import FastPathUnavailable
        cfg = config_mod.GlobalConfig
        attempts = max(1, cfg.rpc_retry_max_attempts)
        delay = cfg.rpc_retry_base_ms / 1000.0
        last: Optional[Exception] = None
        for i in range(attempts):
            try:
                return self.head.call_fast(op, key, val, flags=flags)
            except FastPathUnavailable:
                # the head answered via its Python path (restarted without
                # the fastpath): deterministic — retrying the fast frame
                # would burn the whole backoff budget on EVERY kv call.
                # Demote this backend to the pickle path for good.
                self._head_fast = False
                raise
            except RpcError as e:
                last = e
                if i + 1 < attempts:  # no pointless sleep before the raise
                    time.sleep(delay)
                    delay = min(delay * 2, 5.0)
        raise last  # type: ignore[misc]

    # ------------------------------------------------------------- factories

    @classmethod
    def connect_as_driver(cls, worker, head_addr: str,
                          owned_procs: Optional[list] = None
                          ) -> "ClusterBackend":
        backend = cls(worker, head_addr, role="driver",
                      owned_procs=owned_procs)
        info = backend.head.call_retrying("connect_driver", {})
        worker.job_id = JobID.from_int(info["job_id"])
        from ray_tpu.core.ids import TaskID
        worker.current_task_id = TaskID.for_driver(worker.job_id)
        worker.node_id = backend.local_node_id
        worker.connect_cluster(backend)
        backend._install_cluster_hooks()
        return backend

    @classmethod
    def connect_as_worker(cls, worker, head_addr: str, shm_name: str,
                          worker_id: WorkerID) -> "ClusterBackend":
        backend = cls(worker, head_addr, role="worker", shm_name=shm_name,
                      worker_id=worker_id)
        worker.job_id = JobID.from_int(0)
        from ray_tpu.core.ids import TaskID
        worker.current_task_id = None
        worker.node_id = backend.local_node_id
        worker.mode = "worker"
        worker.backend = backend
        worker._install_hooks()
        backend._install_cluster_hooks()
        return backend

    def _install_cluster_hooks(self) -> None:
        from ray_tpu.core import object_ref as object_ref_mod
        object_ref_mod.install_refcount_hooks(
            add=lambda oid: self.worker.refcounter.add_local(oid),
            remove=self._on_ref_removed,
            borrow=lambda oid: self.worker.refcounter.on_ref_serialized(oid),
            deserialized=self._on_ref_deserialized,
        )
        self.worker.refcounter.free_object = self.worker._free_object

    # ----------------------------------------------------- refcount plumbing

    def _on_ref_deserialized(self, ref: ObjectRef) -> None:
        if ref.owner_id() == self.worker.worker_id or ref.owner_id().is_nil():
            return
        with self._lock:
            first = ref.id() not in self._borrowed_owner
            self._borrowed_owner[ref.id()] = ref.owner_id()
        if first:
            self._enqueue_borrow("add", ref.owner_id(), ref.id())
        self.worker.refcounter.on_ref_deserialized(ref.id())

    def _on_ref_removed(self, oid: ObjectID) -> None:
        self.worker.refcounter.remove_local(oid)

    def _notify_unborrow(self, oid: ObjectID) -> None:
        with self._lock:
            owner = self._borrowed_owner.pop(oid, None)
        self.object_plane.release_local_pin(oid)
        if owner is None:
            return
        self._enqueue_borrow("remove", owner, oid)

    # -------------------------------------------------------- borrow batching

    def _enqueue_borrow(self, kind: str, owner: WorkerID,
                        oid: ObjectID) -> None:
        self._borrow_q.append((kind, owner.binary(), oid.binary()))
        if len(self._borrow_q) >= 200:
            self._borrow_wake.set()

    def _borrow_flush_loop(self) -> None:
        # 200ms idle cadence: borrow traffic is advisory bookkeeping whose
        # only cost-of-delay is deferred frees, and a 5ms timer measurably
        # taxed single-CPU hosts with GIL handoffs (~20% on the hot-path
        # microbenches). Bursts don't wait: _enqueue_borrow sets the event
        # at >=200 queued, so big batches flush immediately.
        while not self._closed:
            self._borrow_wake.wait(timeout=0.2)
            self._borrow_wake.clear()
            self.flush_borrows()

    def flush_borrows(self) -> None:
        """Drain the borrow queue and notify owners, one batched RPC per
        owner. Called by the flush loop, by shutdown, and by worker_main
        BEFORE every task reply: the reply releases the submitter's
        serialize-time pins, so our adds for borrowed args must be at
        their owners first (transfer-before-release, reply side)."""
        # Lock BEFORE the emptiness check: a caller that needs the
        # adds-before-reply guarantee must also wait out a drain the
        # background loop already popped and is mid-RPC on — an empty
        # queue alone doesn't mean the adds have landed.
        with self._borrow_flush_lock:
            if not self._borrow_q:
                return
            batch = []
            while self._borrow_q:
                batch.append(self._borrow_q.popleft())
            # Send every add before any remove. Within one drain a remove
            # to owner O2 (e.g. dropping a container) can transitively
            # release protection for a ref whose add targets a DIFFERENT
            # owner O1, so per-owner FIFO alone is not enough — the
            # protect/release phases must be globally ordered. Across
            # drains FIFO holds already: drains are serialized by this
            # lock, and an add enqueued after a remove may legitimately
            # be sent after it.
            me = self.worker.worker_id.binary()
            for phase in ("add", "remove"):
                by_owner: Dict[bytes, list] = {}
                for kind, owner, oid in batch:
                    if kind == phase:
                        by_owner.setdefault(owner, []).append((kind, oid))
                for owner, ops in by_owner.items():
                    try:
                        self.object_plane.owner_client(WorkerID(owner)).call(
                            "borrow_batch", {"borrower": me, "ops": ops})
                    except Exception:  # noqa: BLE001 — owner gone: refs
                        pass           # resolve to ObjectLost on use

    def _h_borrow_batch(self, p, ctx):
        borrower = p["borrower"]
        for kind, oid in p["ops"]:
            if kind == "add":
                self.worker.refcounter.add_borrower(ObjectID(oid), borrower)
            else:
                self.worker.refcounter.remove_borrower(ObjectID(oid),
                                                       borrower)
        return True

    # --------------------------------------------------------------- objects

    def put_object(self, object_id: ObjectID, value: Any) -> None:
        self.object_plane.put_object(object_id, value)

    def free_object(self, object_id: ObjectID) -> None:
        with self._lock:
            # freed objects must not be reconstructable (and dead
            # TaskSpecs with inline args are driver-memory ballast)
            dropped = self._lineage.pop(object_id.binary(), None)
        # the popped spec dies OUTSIDE the lock: a spec holding the last
        # handle to inline-arg ObjectRefs fires their __del__ -> nested
        # free_object, which must re-acquire self._lock (self-deadlock on
        # this non-reentrant lock if the drop happened inside)
        del dropped
        self.object_plane.free_object(object_id)

    def try_resolve(self, ref: ObjectRef) -> bool:
        return self.object_plane.try_resolve(ref)

    def poke_resolve(self, ref: ObjectRef) -> None:
        self.object_plane.poke_resolve(ref)

    def get_from_store(self, ref: ObjectRef) -> Tuple[Any, bool]:
        return self.object_plane.get_from_store(ref)

    # ----------------------------------------------------------------- tasks

    def _export_function(self, fn) -> str:
        # Cache the export key ON the function object, never keyed by
        # id(fn): ids are reused after GC, and a stale id->key entry makes
        # a NEW function silently execute a DEAD function's code on
        # workers (wrong-function corruption, was a real bug). The cache
        # carries this backend's epoch so a key cached against a previous
        # cluster (whose KV died with it) re-exports here.
        cached = getattr(fn, "__rtpu_export_key__", None)
        if cached is not None and cached[0] == self._export_epoch:
            return cached[1]
        key, blob = wire.export_function(fn)
        self.kv_put(key, blob, overwrite=False)
        try:
            fn.__rtpu_export_key__ = (self._export_epoch, key)
        except (AttributeError, TypeError):
            pass  # unsettable callables just re-export every call
        return key

    def resolve_runtime_env(self, descriptor: Optional[dict]
                            ) -> Optional[dict]:
        """Upload-once packaging: working_dir paths become content-hash
        URIs in the head KV; env_vars pass through (reference:
        runtime_env working_dir.py upload_package_if_needed)."""
        if not descriptor:
            return None
        from ray_tpu.runtime import runtime_env as rtenv
        out = dict(descriptor)
        wd = out.pop("working_dir", None)
        if wd is not None:
            wd = os.path.abspath(wd)
            with self._lock:
                uri = self._rtenv_uploads.get(wd)
            if uri is None:
                uri, blob = rtenv.package_working_dir(wd)
                self.kv_put(rtenv.kv_key(uri), blob, overwrite=False)
                with self._lock:
                    self._rtenv_uploads[wd] = uri
            out["working_dir_uri"] = uri
        return out or None

    def submit_task(self, spec: TaskSpec) -> None:
        key = self._export_function(spec.function)
        payload, contained = wire.task_to_wire(spec, function_key=key)
        pins = self._pin_args(spec, contained)
        pg = None
        if spec.placement_group_id is not None:
            pg = (spec.placement_group_id, spec.placement_bundle_index)
        renv = self.resolve_runtime_env(spec.runtime_env)
        from ray_tpu.runtime.runtime_env import descriptor_key
        shape_key = (tuple(sorted(spec.resources.items())), pg,
                     descriptor_key(renv))
        with self._lock:
            sub = self._submitters.get(shape_key)
            if sub is None:
                sub = _TaskSubmitter(self, shape_key, dict(spec.resources),
                                     pg=pg, runtime_env=renv)
                self._submitters[shape_key] = sub
            # lineage: stateless tasks only (actor calls mutate state and
            # cannot be replayed — reference restriction)
            if spec.actor_id is None:
                for oid in spec.return_ids():
                    self._lineage[oid.binary()] = spec
                    self._lineage.move_to_end(oid.binary())
                while len(self._lineage) > self._lineage_cap:
                    self._lineage.popitem(last=False)
        sub.submit(payload, spec, pins)

    def try_reconstruct(self, ref: ObjectRef) -> bool:
        """Rebuild a lost object by re-executing its creating task
        (reference: ObjectRecoveryManager lineage reconstruction). The
        respawned task reuses the SAME spec, so results land under the
        original return object ids."""
        with self._lock:
            spec = self._lineage.get(ref.id().binary())
        if spec is None or spec.actor_id is not None:
            return False
        # forget ONLY the lost object's ready marker (deleting healthy
        # sibling returns would race their concurrent getters into a
        # spurious ObjectLost); resubmission re-stores every return
        self.worker.memory_store.delete(ref.id())
        # re-pin top-level ref args: the reconstruction reply will run the
        # standard unpin (on_serialized_ref_done) per ref arg, and without
        # a matching pin here the arg's submitted-count underflows and a
        # LIVE object gets freed
        for a in spec.args:
            if a.is_ref:
                self.worker.refcounter.on_ref_serialized(a.object_id)
        self.submit_task(spec)
        return True

    def _pin_args(self, spec: TaskSpec, contained: list) -> list:
        """Collect refs pinned until the task's reply arrives.

        Top-level ref args were pinned by worker.make_task_args
        (on_ref_serialized); nested refs inside inline values were pinned by
        the serialize-time borrow hook (ObjectRef.__reduce__). Each gets
        exactly one on_serialized_ref_done at reply time.
        """
        pins = [a.object_id for a in spec.args if a.is_ref]
        pins.extend(r.id() for r in contained)
        return pins

    # ------------------------------------------------------------- streaming

    def register_stream(self, spec: TaskSpec):
        """Create owner-side state + generator for a streaming task."""
        from ray_tpu.core.generator import ObjectRefGenerator, StreamState
        state = StreamState()
        with self._lock:
            self._streams[spec.task_id.binary()] = state
        return ObjectRefGenerator(spec.task_id, self.worker.worker_id,
                                  self.worker, state)

    def _h_log_batch(self, p, ctx):
        """Worker stdout/stderr shipped by the executing worker's log
        shipper (reference: log_monitor -> driver prints with the
        (pid=...) prefix, _private/worker.py:1970). Only processes that
        submitted work receive logs — output follows the caller."""
        if not config_mod.GlobalConfig.log_to_driver:
            return True
        prefix = f"({p.get('worker', '?')} pid={p.get('pid', '?')})"
        for stream, line in p.get("lines", ()):
            out = sys.stderr if stream == "stderr" else sys.stdout
            try:
                out.write(f"{prefix} {line}\n")
                out.flush()
            except Exception:  # noqa: BLE001
                break
        return True

    def _h_stream_item(self, p, ctx):
        """A worker shipped one yielded value of a streaming task we own."""
        oid = ObjectID(p["object_id"])
        self.worker.refcounter.mark_owned(oid)
        if "in_shm" in p:
            self.object_plane.record_remote_location(oid, p["in_shm"])
        else:
            value = serialization.deserialize(p["inline"])
            self.worker.memory_store.put(oid, value, is_error=False)
        # state lookup AFTER the store: checking before would let a
        # concurrent generator cleanup (which drains the arrival set and
        # unregisters) slip between the check and the store, stranding the
        # freshly-stored item outside both cleanup paths
        with self._lock:
            state = self._streams.get(p["task_id"])
        recorded = state is not None and \
            state.record_arrival(p.get("index", 0))
        if not recorded:
            # straggler after the generator was dropped and cleaned up:
            # nothing will ever consume or free this item — free it now
            self.worker.refcounter.untrack(oid)
            self.worker._free_object(oid)
        return True

    def unregister_stream(self, task_id) -> None:
        with self._lock:
            self._streams.pop(task_id.binary(), None)

    def _finish_stream(self, spec: TaskSpec, total, error) -> None:
        # the entry stays in _streams until the generator is GC'd
        # (unregister_stream): stragglers arriving after the reply must
        # still find the state, and the generator's cleanup needs the
        # arrival set to free unconsumed items
        with self._lock:
            state = self._streams.get(spec.task_id.binary())
        if state is not None:
            state.finish(total, error)

    def _store_task_reply(self, spec: TaskSpec, reply: dict,
                          pins: list) -> None:
        if reply.get("cancelled"):
            self._store_task_error(
                spec, TaskCancelledError(spec.task_id.hex()), pins)
            return
        if spec.streaming:
            error = None
            if "streaming_error" in reply:
                error = serialization.deserialize(reply["streaming_error"])
            self._finish_stream(spec, reply.get("streaming_count"), error)
            self._unpin(pins)
            return
        rids = spec.return_ids()
        for rid, res in zip(rids, reply["results"]):
            if "in_shm" in res:
                self.object_plane.record_remote_location(rid, res["in_shm"])
            else:
                value = serialization.deserialize(res["inline"])
                self.worker.memory_store.put(rid, value,
                                             is_error=res["is_error"])
        self._unpin(pins)

    def _store_task_error(self, spec: TaskSpec, exc: BaseException,
                          pins: list) -> None:
        if spec.streaming:
            # no total recorded: consumer raises once received items drain
            self._finish_stream(spec, None, exc)
        for rid in spec.return_ids():
            self.worker.memory_store.put(rid, exc, is_error=True)
        self._unpin(pins)

    def _unpin(self, pins: list) -> None:
        for oid in pins:
            self.worker.refcounter.on_serialized_ref_done(oid)

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> None:
        tid = ref.id().task_id().binary()
        with self._lock:
            subs = list(self._submitters.values())
        for sub in subs:
            if sub.cancel(tid):
                return

    # ---------------------------------------------------------------- actors

    def create_actor(self, spec: ActorCreationSpec) -> None:
        payload, contained = wire.actor_to_wire(spec)
        pins = [a.object_id for a in spec.args if a.is_ref]
        pins.extend(r.id() for r in contained)
        import pickle
        name_key = (f"{spec.namespace}:{spec.registered_name}"
                    if spec.registered_name else "")
        self.head.call_retrying("create_actor", {
            "actor_id": spec.actor_id.binary(),
            "spec_bytes": pickle.dumps(payload, protocol=5),
            "max_restarts": spec.max_restarts,
            "max_task_retries": spec.max_task_retries,
            "name_key": name_key,
            "resources": spec.resources,
            "owner_addr": self.server.address,
            "class_name": spec.name,
            "pg_id": spec.placement_group_id,
            "bundle_index": spec.placement_bundle_index,
            "runtime_env": self.resolve_runtime_env(spec.runtime_env),
        })
        with self._lock:
            self._actor_submitters[spec.actor_id] = _ActorSubmitter(
                self, spec.actor_id, creation_pins=pins)

    def submit_actor_task(self, spec: TaskSpec) -> None:
        payload, contained = wire.task_to_wire(spec)
        pins = self._pin_args(spec, contained)
        with self._lock:
            sub = self._actor_submitters.get(spec.actor_id)
            if sub is None:
                sub = _ActorSubmitter(self, spec.actor_id)
                self._actor_submitters[spec.actor_id] = sub
        sub.submit(payload, spec, pins)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.head.call_retrying("kill_actor", {
            "actor_id": actor_id.binary(), "no_restart": no_restart})

    def get_actor_by_name(self, name: str, namespace: str):
        info = self.head.call_retrying("get_actor_by_name", {
            "name": name, "namespace": namespace})
        if info is None:
            return None
        spec = ActorCreationSpec(
            actor_id=ActorID(info["actor_id"]), name=info["class_name"],
            registered_name=name, namespace=namespace,
            max_task_retries=info["max_task_retries"])
        return spec

    # ------------------------------------------------------ placement groups

    def create_placement_group(self, pg_id: bytes, bundles: list,
                               strategy: str, name: str = "") -> None:
        self.head.call_retrying("create_placement_group", {
            "pg_id": pg_id, "bundles": bundles, "strategy": strategy,
            "name": name})

    def remove_placement_group(self, pg_id: bytes) -> bool:
        return self.head.call_retrying("remove_placement_group",
                                       {"pg_id": pg_id})

    def get_placement_group(self, pg_id: bytes):
        return self.head.call_retrying("get_placement_group",
                                       {"pg_id": pg_id})

    # ------------------------------------------------------------------ misc

    def cluster_resources(self) -> Dict[str, float]:
        return self.head.call_retrying("cluster_resources")

    def available_resources(self) -> Dict[str, float]:
        return self.head.call_retrying("available_resources")

    def nodes(self) -> list:
        out = []
        for n in self.head.call_retrying("list_nodes"):
            out.append({"NodeID": n["node_id"], "Alive": n["alive"],
                        "Resources": n["resources"],
                        "Address": n["address"]})
        return out

    def state_dump(self, task_limit: int = 200) -> dict:
        return self.head.call_retrying("state_dump",
                                       {"task_limit": task_limit})

    def _reap_loop(self) -> None:
        cfg = config_mod.GlobalConfig
        while not self._closed:
            time.sleep(0.2)
            with self._lock:
                subs = list(self._submitters.values())
            for sub in subs:
                try:
                    sub.reap_idle(cfg.lease_idle_linger_s)
                except Exception:
                    pass

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush_telemetry()  # last-interval metrics/spans must land
        # stop the flush loop before the final drain: a concurrent drain
        # could split one owner's add/remove pair across two in-flight
        # RPCs; after the join, any late enqueue from teardown is caught
        # by this (locked) final flush
        self._borrow_wake.set()
        self._borrow_thread.join(timeout=2.0)
        self.flush_borrows()     # queued unborrows must reach owners
        # burst-deferred actor submits must hit the wire before teardown
        # closes the peers (the flush loop exits on _closed)
        self._aflush_wake.set()
        self._aflush_thread.join(timeout=2.0)
        self._drain_actor_flushes()
        with self._lock:
            subs = list(self._submitters.values())
        for sub in subs:
            sub.shutdown()
        try:
            self.kv_del(f"addr:{self.worker.worker_id.hex()}")
        except RpcError:
            pass
        self.server.stop()
        self.object_plane.shutdown()
        self.peers.close_all()
        self.head.close()
        # tear down processes we started (driver that booted the cluster)
        for proc in reversed(self._owned_procs):
            try:
                proc.terminate()
                proc.wait(timeout=5.0)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass


# ---------------------------------------------------------------------------
# bootstrap

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_ready(address: str, proc: subprocess.Popen, what: str,
                timeout: float = 30.0) -> None:
    client = RpcClient(address, name="bootstrap")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited rc={proc.returncode} during startup")
        try:
            client.call("ping", timeout=1.0)
            client.close()
            return
        except RpcError:
            time.sleep(0.05)
    client.close()
    raise RuntimeError(f"{what} not ready after {timeout}s")


def start_head(session: str, port: Optional[int] = None,
               persist_path: Optional[str] = None
               ) -> Tuple[subprocess.Popen, str]:
    """persist_path enables KV durability: a restarted head pointed at
    the same file serves the previous KV table (reference role: GCS
    Redis persistence, scoped to the KV/jobs tables)."""
    port = port or _free_port()
    cmd = [sys.executable, "-m", "ray_tpu.runtime.head", str(port), session,
           config_mod.GlobalConfig.to_json()]
    if persist_path:
        cmd.append(persist_path)
    proc = subprocess.Popen(cmd, env=_child_env())
    address = f"127.0.0.1:{port}"
    _wait_ready(address, proc, "head")
    return proc, address


def start_node(head_addr: str, session: str,
               resources: Optional[Dict[str, float]] = None,
               object_store_bytes: Optional[int] = None,
               node_id: Optional[str] = None) -> subprocess.Popen:
    args = {"resources": resources,
            "object_store_bytes": object_store_bytes,
            "node_id": node_id,
            "config": json.loads(config_mod.GlobalConfig.to_json())}
    cmd = [sys.executable, "-m", "ray_tpu.runtime.node", head_addr, session,
           json.dumps(args)]
    return subprocess.Popen(cmd, env=_child_env())


def connect_or_start(worker, address: Optional[str] = None,
                     num_cpus: Optional[int] = None,
                     num_tpus: Optional[int] = None,
                     resources: Optional[Dict[str, float]] = None,
                     object_store_memory: Optional[int] = None,
                     namespace: str = "default") -> Dict[str, Any]:
    owned: list = []
    if address is None:
        session = os.urandom(4).hex()
        # the driver's own log plane (and any process it spawns) files
        # under the same session log directory as the daemons
        os.environ["RTPU_SESSION"] = session
        head_proc, address = start_head(session)
        owned.append(head_proc)
        merged = dict(resources or {})
        merged.setdefault("CPU", float(num_cpus if num_cpus is not None
                                       else (os.cpu_count() or 1)))
        if num_tpus is not None:
            merged["TPU"] = float(num_tpus)
        node_proc = start_node(address, session, resources=merged,
                               object_store_bytes=object_store_memory)
        owned.append(node_proc)
        # wait until the node registers
        probe = RpcClient(address, name="probe")
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if node_proc.poll() is not None:
                raise RuntimeError(
                    f"node daemon exited rc={node_proc.returncode}")
            try:
                if any(n["alive"] for n in probe.call("list_nodes")):
                    break
            except RpcError:
                pass
            time.sleep(0.05)
        else:
            raise RuntimeError("node daemon never registered")
        probe.close()

    backend = ClusterBackend.connect_as_driver(worker, address,
                                               owned_procs=owned)
    return {"address": address, "node_id": backend.local_node_id}
