"""Per-task/actor runtime environments: env_vars + working_dir.

Role-equivalent to the reference's runtime_env subsystem (reference:
python/ray/_private/runtime_env/ — working_dir.py packaging + URI cache,
plugin.py validation; the per-node agent that materializes envs). Scoped to
the two capabilities that matter on a TPU cluster image (the machine image
pins jax/libtpu versions, so pip/conda envs are a foot-gun there):

 - ``env_vars``: spawned into the worker process environment BEFORE any
   runtime initializes (critical on TPU: libtpu reads TPU_* at import).
 - ``working_dir``: a local directory content-hash-zipped by the driver,
   uploaded once to the head KV (reference: working_dir URI upload to GCS),
   materialized into a per-node cache by the node daemon, and used as the
   worker's cwd + sys.path[0].

Workers are pooled per environment signature — a worker started with one
env never serves leases for another (reference: WorkerPool keys workers by
runtime_env hash, worker_pool.h:224). Unsupported keys raise immediately
instead of being silently dropped.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile
from typing import Any, Callable, Dict, Optional, Tuple

SUPPORTED_KEYS = {"env_vars", "working_dir"}

#: reference caps working_dir at 100 MiB by default
#: (ray_constants: RAY_RUNTIME_ENV_WORKING_DIR_SIZE_LIMIT ~ 100 MiB)
MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024

_KV_PREFIX = "rtenv:pkg:"


def validate(runtime_env: Optional[dict]) -> Optional[dict]:
    """Check keys/types up-front, at decoration/option time.

    Raises ValueError for malformed values and NotImplementedError for
    reference keys outside this build's scope (pip/conda/py_modules/...),
    so a user never gets a silently-ignored environment.
    """
    if runtime_env is None:
        return None
    if not isinstance(runtime_env, dict):
        raise ValueError(
            f"runtime_env must be a dict, got {type(runtime_env).__name__}")
    if not runtime_env:
        return None
    unsupported = set(runtime_env) - SUPPORTED_KEYS
    if unsupported:
        raise NotImplementedError(
            f"runtime_env keys {sorted(unsupported)} are not supported by "
            f"this build (supported: {sorted(SUPPORTED_KEYS)}); pin "
            f"python-level dependencies in the cluster image instead")
    out: Dict[str, Any] = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None:
        if not isinstance(env_vars, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in env_vars.items()):
            raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
        if env_vars:
            out["env_vars"] = dict(env_vars)
    wd = runtime_env.get("working_dir")
    if wd is not None:
        if not isinstance(wd, str):
            raise ValueError("runtime_env['working_dir'] must be a path str")
        out["working_dir"] = wd
    return out or None


def package_working_dir(path: str) -> Tuple[str, bytes]:
    """Deterministic content-hashed zip of a directory.

    Fixed timestamps + sorted entries make the archive a pure function of
    the directory contents, so the URI doubles as a cache key across
    drivers (reference: working_dir upload is content-addressed into GCS).
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs.sort()
            # skip caches that would churn the hash without changing code
            dirs[:] = [d for d in dirs if d not in ("__pycache__", ".git")]
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                try:
                    mode = os.stat(full).st_mode & 0o777
                    data = open(full, "rb").read()
                except OSError:
                    continue  # vanished/broken-symlink files are skipped
                total += len(data)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"working_dir {path!r} exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20} MiB; ship data "
                        f"through the object store, not the runtime env")
                info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
                info.external_attr = mode << 16
                zf.writestr(info, data)
    blob = buf.getvalue()
    uri = hashlib.sha256(blob).hexdigest()[:24]
    return uri, blob


def kv_key(uri: str) -> str:
    return _KV_PREFIX + uri


def descriptor_key(descriptor: Optional[dict]) -> str:
    """Stable signature used to pool workers per environment ('' = none)."""
    if not descriptor:
        return ""
    return hashlib.sha1(
        json.dumps(descriptor, sort_keys=True).encode()).hexdigest()[:16]


def materialize(cache_root: str, uri: str,
                fetch: Callable[[str], Optional[bytes]]) -> str:
    """Extract a packaged working_dir into the node-local cache (idempotent;
    reference: per-node runtime-env agent URI cache). `fetch` maps a KV key
    to the zip bytes (the head KV holds the uploaded package)."""
    dest = os.path.join(cache_root, uri)
    marker = os.path.join(dest, ".rtenv_ready")
    if os.path.exists(marker):
        return dest
    blob = fetch(kv_key(uri))
    if blob is None:
        raise RuntimeError(
            f"working_dir package {uri} missing from the cluster KV "
            f"(head restarted without persistence?)")
    # unique tmp per attempt: concurrent materializations of the same URI
    # must not rmtree each other's half-extracted trees
    import shutil
    import threading
    tmp = f"{dest}.tmp{os.getpid()}_{threading.get_ident()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
        for info in zf.infolist():
            mode = (info.external_attr >> 16) & 0o777
            if mode:
                os.chmod(os.path.join(tmp, info.filename), mode)
    open(os.path.join(tmp, ".rtenv_ready"), "w").close()
    try:
        os.replace(tmp, dest)
    except OSError:
        # lost a concurrent-materialize race: the winner's copy is complete
        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def worker_env(descriptor: Optional[dict],
               working_dir_path: Optional[str]) -> Dict[str, str]:
    """Environment additions for a worker spawned under this descriptor."""
    env: Dict[str, str] = {}
    if descriptor:
        env.update(descriptor.get("env_vars") or {})
    if working_dir_path:
        env["RTPU_WORKING_DIR"] = working_dir_path
    return env
