"""cgroup-v2 worker isolation — resource bounds BEFORE a worker can dirty
the host (SURVEY §2.1 "cgroup support" row; reference:
src/ray/common/cgroup/cgroup_setup.h — per-worker cgroup under the node's
application slice, memory/cpu controllers).

Redesigned for the unified (v2) hierarchy only:

    <root>/rtpu-<session>/          node slice (controllers enabled here)
    <root>/rtpu-<session>/w-<id>/   one leaf per worker (pid in cgroup.procs)

 - memory.max  <- the worker's `memory` resource request (hard OOM bound —
   the kernel kills the worker instead of the host swapping; the node's
   RSS-polling memory monitor stays as the soft/graceful layer on top)
 - cpu.weight  <- proportional share from the worker's CPU request

Everything is best-effort and degrades to a no-op when the root isn't
writable (containers without cgroup delegation, non-root runs): isolation
is a hardening layer, never a boot requirement. The root is injectable so
tests run against a fake hierarchy in a tmpdir.
"""

from __future__ import annotations

import logging
import os
import shutil
from typing import Optional

logger = logging.getLogger("ray_tpu.cgroup")

DEFAULT_ROOT = "/sys/fs/cgroup"


def _write(path: str, value: str) -> bool:
    try:
        with open(path, "w") as f:
            f.write(value)
        return True
    except OSError:
        return False


class CgroupManager:
    """One per node daemon; owns the node slice and its worker leaves."""

    def __init__(self, session: str, root: str = DEFAULT_ROOT):
        self.root = root
        self.slice_dir = os.path.join(root, f"rtpu-{session}")
        self.enabled = False
        # v2 detection: the unified hierarchy exposes cgroup.controllers
        # at its root (v1 mounts do not)
        if not os.path.exists(os.path.join(root, "cgroup.controllers")):
            logger.debug("cgroup v2 root %s not present; isolation off",
                         root)
            return
        try:
            os.makedirs(self.slice_dir, exist_ok=True)
        except OSError:
            logger.debug("cgroup root %s not writable; isolation off", root)
            return
        # enable the controllers we use for the children of the slice;
        # partial success is fine (e.g. cpu missing under some delegations)
        _write(os.path.join(self.slice_dir, "cgroup.subtree_control"),
               "+memory +cpu")
        self.enabled = True

    # -- worker lifecycle --

    def create_worker_group(self, worker_hex: str,
                            memory_bytes: int = 0,
                            num_cpus: float = 0.0) -> Optional[str]:
        """Create the leaf and set bounds; returns its path (None = off)."""
        if not self.enabled:
            return None
        leaf = os.path.join(self.slice_dir, f"w-{worker_hex[:16]}")
        try:
            os.makedirs(leaf, exist_ok=True)
        except OSError:
            return None
        if memory_bytes > 0:
            _write(os.path.join(leaf, "memory.max"), str(int(memory_bytes)))
            # contain the kill to the worker: without this the kernel may
            # pick any process in the group's subtree
            _write(os.path.join(leaf, "memory.oom.group"), "1")
        if num_cpus > 0:
            # cpu.weight is proportional (default 100, range 1-10000):
            # scale so a 1-CPU worker keeps the default share
            weight = max(1, min(10000, int(100 * num_cpus)))
            _write(os.path.join(leaf, "cpu.weight"), str(weight))
        return leaf

    def attach(self, leaf: Optional[str], pid: int) -> bool:
        """Move a spawned worker into its leaf (post-fork attach, like the
        reference's AddProcessToCgroup)."""
        if not leaf:
            return False
        return _write(os.path.join(leaf, "cgroup.procs"), str(pid))

    def remove_worker_group(self, leaf: Optional[str]) -> None:
        if not leaf:
            return
        try:
            os.rmdir(leaf)  # cgroup dirs remove via rmdir once empty
        except OSError:
            pass

    def memory_events(self, leaf: Optional[str]) -> dict:
        """Parse memory.events (oom_kill count etc.) for death-cause
        reporting — lets the node answer `worker_fate` with 'oom' when the
        KERNEL did the killing, not just our RSS poller."""
        if not leaf:
            return {}
        try:
            with open(os.path.join(leaf, "memory.events")) as f:
                return {k: int(v) for k, v in
                        (line.split() for line in f if line.strip())}
        except (OSError, ValueError):
            return {}

    def shutdown(self) -> None:
        if not self.enabled:
            return
        try:
            for d in os.listdir(self.slice_dir):
                p = os.path.join(self.slice_dir, d)
                if os.path.isdir(p):
                    try:
                        os.rmdir(p)
                    except OSError:
                        pass
            os.rmdir(self.slice_dir)
        except OSError:
            # leaves with live pids can't be removed; leave for reboot
            shutil.rmtree(self.slice_dir, ignore_errors=True)
