"""Worker process — task execution loop + actor mode.

Role-equivalent to the reference's worker-side CoreWorker (reference:
src/ray/core_worker/core_worker.cc:3230 ExecuteTask, :3804 HandlePushTask;
ordered actor queues in transport/task_receiver.h:51): a leased worker
receives pushed tasks directly from the submitting owner over RPC, executes
them serially (or on `max_concurrency` threads for threaded actors), and
replies with results — small values inline, large values sealed into the
node's shm store with the location reported back to the owner.

The worker also runs the full client runtime (ClusterBackend), so task code
can itself submit tasks, create actors, and put/get objects (nested
remote calls — reference: workers are full CoreWorkers too).
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.core import config as config_mod
from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import TaskCancelledError, TaskError
from ray_tpu.runtime import wire
from ray_tpu.runtime.protocol import (_COMBINED_DONE, DEFERRED, RpcClient,
                                      RpcError)
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import trace_context


class _LogShipper:
    """Forwards worker stdout/stderr to the submitting owner process.

    Role-equivalent to the reference's log monitor -> GCS pubsub -> driver
    print pipeline (reference: python/ray/_private/log_monitor.py,
    worker.py:1970 prints with the (pid=...) prefix) — redesigned as a
    direct worker->owner push: output produced WHILE a task runs is
    attributed to that task's submitter via a contextvar, so prints land
    on the process that called .remote(), not a global driver.
    """

    MAX_BUFFER = 10_000  # lines; overflow drops the OLDEST, keeps the tail

    def __init__(self, backend):
        self.backend = backend
        # contextvar, not a thread-local: async actor methods run as
        # interleaved coroutines on ONE loop thread, and the context
        # captured at dispatch (run_coroutine_threadsafe copies the
        # submitting thread's context into the Task) keeps each
        # coroutine's prints attributed to ITS caller
        import contextvars
        self._owner_var = contextvars.ContextVar("rtpu_log_owner",
                                                 default=None)
        self._lock = threading.Lock()
        import collections as _collections
        self._buf: "_collections.deque" = _collections.deque()
        self._last_owner: Optional[bytes] = None
        self._dropped = 0
        threading.Thread(target=self._flush_loop, daemon=True,
                         name="log-ship").start()

    # -- attribution --

    def set_owner(self, owner: Optional[bytes]) -> None:
        self._owner_var.set(owner)
        if owner:
            self._last_owner = owner

    def current_owner(self) -> Optional[bytes]:
        # off-task output (background threads) goes to the most recent
        # submitter — better than losing it
        return self._owner_var.get() or self._last_owner

    # -- production --

    def emit(self, stream: str, text: str) -> None:
        owner = self.current_owner()
        if owner is None or not text:
            return
        with self._lock:
            if len(self._buf) >= self.MAX_BUFFER:
                # keep the newest output: the tail (the error) is the
                # diagnostically valuable part of a runaway burst
                self._buf.popleft()
                self._dropped += 1
            self._buf.append((owner, stream, text))

    def _flush_loop(self) -> None:
        while True:
            time.sleep(0.2)
            self.flush()

    def flush(self) -> None:
        import collections as _collections
        with self._lock:
            batch, self._buf = list(self._buf), _collections.deque()
            dropped, self._dropped = self._dropped, 0
        if not batch:
            if dropped:
                # the buffer drained between overflow and flush: carry
                # the count to the next non-empty flush so the "...N
                # lines dropped" notice is never itself dropped
                with self._lock:
                    self._dropped += dropped
            return
        by_owner: Dict[bytes, list] = {}
        for owner, stream, text in batch:
            by_owner.setdefault(owner, []).append((stream, text))
        if dropped:
            by_owner.setdefault(batch[-1][0], []).append(
                ("stderr", f"... {dropped} log lines dropped (buffer full)"))
        me = self.backend.worker.worker_id.hex()[:8]
        pid = os.getpid()
        for owner, lines in by_owner.items():
            try:
                self.backend.object_plane.owner_client(
                    WorkerID(owner)).oneway("log_batch", {
                        "worker": me, "pid": pid, "lines": lines})
            except Exception:  # noqa: BLE001 — log loss must never kill
                pass


class _TeeStream:
    """File-like wrapper: writes through to the real stream (which the
    node daemon redirects into the durable worker-<id>.{out,err} files)
    AND ships complete lines to the log shipper (owner push) and the
    structured log plane (local file sink + head ring) — so output
    produced before the first task, when the shipper has no owner yet,
    is still captured instead of silently discarded."""

    def __init__(self, real, name: str,
                 shipper: Optional[_LogShipper] = None):
        self._real = real
        self._name = name
        self._shipper = shipper
        self._partial = ""

    def _emit(self, line: str) -> None:
        if self._shipper is not None:
            self._shipper.emit(self._name, line)
        if not line:
            return
        try:
            from ray_tpu.util import log_plane
            logger = log_plane.get_global()
            if logger is not None:
                # stderr is error severity: the LogStore's severity-
                # indexed rings keep it alive through debug floods, and
                # tracebacks feed the error-fingerprint/storm machinery
                logger.log("error" if self._name == "stderr" else "info",
                           line, stream=self._name)
        except Exception:  # noqa: BLE001 — log loss must never kill
            pass

    def write(self, text) -> int:
        n = self._real.write(text)
        self._partial += str(text)
        while "\n" in self._partial:
            line, self._partial = self._partial.split("\n", 1)
            self._emit(line)
        return n

    def flush(self) -> None:
        # a trailing partial line (print(..., end='') then flush, or
        # process exit) is emitted, not dropped: the last words before
        # a crash are exactly the ones written without a newline
        if self._partial:
            line, self._partial = self._partial, ""
            self._emit(line)
        self._real.flush()

    def __getattr__(self, attr):
        return getattr(self._real, attr)


class _BatchReplyCollector:
    """Accumulates the per-task replies of ONE push_task_batch frame and
    ships them back as a single combined reply when the last completes.

    This is the worker half of the combined-batch fast path: a 32-task
    frame costs one pickle.dumps + one transport frame in each direction
    instead of 32 (reference analogue: the raylet's batched
    PushTaskReply streaming, core_worker/transport/direct_actor_transport
    — redesigned here as symmetric batch frames)."""

    __slots__ = ("ctx", "n", "slots", "lock", "done")

    def __init__(self, ctx, n: int):
        self.ctx = ctx
        self.n = n
        self.slots: List[Any] = [None] * n
        self.lock = threading.Lock()
        self.done = 0

    def reply_at(self, i: int, value, error) -> None:
        with self.lock:
            if self.slots[i] is not None:
                return
            self.slots[i] = (value, error)
            self.done += 1
            flush = self.done == self.n
        if flush:
            self.ctx.reply(self.slots)


class _EagerReplyCollector:
    """Per-slot eager replies for a combined batch: each task's result is
    flushed on its own pre-allocated req_id the moment it completes, then
    a done marker closes the main req_id. Replaces the buffer-until-last
    behaviour of _BatchReplyCollector when the client sent slot ids —
    buffering deadlocked nested gets (task A in the batch blocked on a
    ref produced by task B in the SAME batch: B's reply was withheld
    until A finished, which never happened)."""

    __slots__ = ("ctx", "slot_ids", "lock", "replied", "done")

    def __init__(self, ctx, slot_ids):
        self.ctx = ctx
        self.slot_ids = slot_ids
        self.lock = threading.Lock()
        self.replied = [False] * len(slot_ids)
        self.done = 0

    def reply_at(self, i: int, value, error) -> None:
        with self.lock:
            if self.replied[i]:
                return
            self.replied[i] = True
            self.done += 1
            last = self.done == len(self.slot_ids)
        self.ctx.reply_to(self.slot_ids[i], value, error)
        if last:
            # marker is sent AFTER every slot reply on the same ordered
            # stream, so the client has fired all callbacks when it lands
            self.ctx.reply(_COMBINED_DONE)


class _SubCtx:
    """HandlerContext stand-in for one task inside a combined batch."""

    __slots__ = ("_coll", "_i", "peer", "replied")

    def __init__(self, coll: _BatchReplyCollector, i: int, peer):
        self._coll = coll
        self._i = i
        self.peer = peer
        self.replied = False

    def reply(self, value=None, error=None) -> None:
        if self.replied:
            return
        self.replied = True
        self._coll.reply_at(self._i, value, error)


class Executor:
    """Serial (or n-threaded, or asyncio-loop) execution of pushed tasks."""

    def __init__(self, backend, worker):
        self.backend = backend
        self.worker = worker
        self.queue: "queue.Queue" = queue.Queue()
        self.fn_cache: Dict[str, Any] = {}
        self.cancelled: set = set()
        self.actor_instance: Optional[Any] = None
        self.actor_id: Optional[bytes] = None
        # async actors: all methods run on this event loop (reference:
        # fiber-based async execution, core_worker/transport/fiber.h role —
        # here a plain asyncio loop thread + semaphore)
        self._aio_loop = None
        self._aio_sem = None
        # packages async-actor replies (serialize + shm copy + socket write)
        # off the event-loop thread so one large result can't stall every
        # interleaved coroutine
        from concurrent.futures import ThreadPoolExecutor
        self._reply_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="reply")
        self._threads: List[threading.Thread] = []
        # concurrency groups (reference: ConcurrencyGroupManager,
        # core_worker/transport/concurrency_group_manager.h): each group
        # gets its own queue + thread lane; methods route by name so
        # control-plane probes never queue behind busy handler lanes.
        self._group_queues: Dict[str, "queue.Queue"] = {}
        self._method_groups: Dict[str, str] = {}
        self.log_shipper: Optional[_LogShipper] = None
        self._start_threads(1)

    def _start_threads(self, n: int, q: Optional["queue.Queue"] = None,
                       tag: str = "exec") -> None:
        q = q if q is not None else self.queue
        # exact-tag match (name is "<tag>-<index>"): a prefix test would
        # over-count when one group's name prefixes another's ("a", "a-b")
        have = sum(1 for t in self._threads
                   if t.name.rsplit("-", 1)[0] == tag)
        for i in range(have, n):
            t = threading.Thread(target=self._loop, args=(q,), daemon=True,
                                 name=f"{tag}-{i}")
            t.start()
            self._threads.append(t)

    # ------------------------------------------------------------- handlers

    def handle_push_task(self, payload, ctx):
        group = self._method_groups.get(payload.get("method_name") or "")
        q = self._group_queues.get(group) if group else None
        (q if q is not None else self.queue).put((payload, ctx))
        return DEFERRED

    def handle_push_task_batch(self, payloads, ctx):
        """N tasks in one frame, ONE combined reply frame (see
        _BatchReplyCollector). Tasks still route individually through
        their concurrency-group queues, so ordering semantics match the
        per-task path exactly. Clients that pre-allocated per-slot reply
        ids (ctx.slot_ids) get each result flushed eagerly instead
        (_EagerReplyCollector); old-format frames keep the single
        combined reply."""
        slot_ids = getattr(ctx, "slot_ids", None)
        if slot_ids is not None and len(slot_ids) == len(payloads):
            coll = _EagerReplyCollector(ctx, slot_ids)
        else:
            coll = _BatchReplyCollector(ctx, len(payloads))
        for i, p in enumerate(payloads):
            group = self._method_groups.get(p.get("method_name") or "")
            q = self._group_queues.get(group) if group else None
            (q if q is not None else self.queue).put(
                (p, _SubCtx(coll, i, ctx.peer)))
        return DEFERRED

    def handle_cancel(self, payload, ctx):
        self.cancelled.add(payload["task_id"])
        return True

    def handle_dag_start_loop(self, payload, ctx):
        """Pre-launch a compiled-DAG execution loop on this actor
        (reference: compiled_dag_node.py do_exec_tasks at :188 — the
        actor-side half of aDAG): read the input shm ring, run the bound
        method on the live actor instance, write the output ring. The
        stop sentinel cascades: closing our input closes our output."""
        from ray_tpu.runtime.channel import ChannelClosed, ShmChannel
        store = self.backend.object_plane.store
        inc = ShmChannel(store, payload["in"], payload["capacity"])
        out = ShmChannel(store, payload["out"], payload["capacity"])
        method_name = payload["method"]

        def loop():
            while True:
                try:
                    tag, val = inc.get(timeout=None)
                except ChannelClosed:
                    out.close()
                    return
                except Exception:  # noqa: BLE001 — store torn down
                    return
                if tag == "e":  # upstream error: pass through untouched
                    out.put((tag, val))
                    continue
                try:
                    method = getattr(self.actor_instance, method_name)
                    out.put(("v", method(val)))
                except BaseException as e:  # noqa: BLE001
                    if isinstance(e, (SystemExit, KeyboardInterrupt)):
                        raise
                    try:
                        out.put(("e", e))
                    except Exception:  # unserializable exception: a dead
                        # loop would hang the whole pipeline — ship a
                        # stringified stand-in instead
                        out.put(("e", RuntimeError(
                            f"{type(e).__name__}: {e!r} "
                            f"(original not serializable)")))

        threading.Thread(target=loop, daemon=True, name="dag-loop").start()
        return self.backend.local_node_id

    def handle_become_actor(self, payload, ctx):
        # Ack immediately — construction runs async on the exec thread so an
        # arbitrarily slow __init__ can't trip the node->worker RPC deadline
        # (liveness is tracked via actor_ready/actor_failed to the head).
        self.queue.put((("__become_actor__", payload), None))
        return True

    # ------------------------------------------------------------ execution

    def _loop(self, q: "queue.Queue") -> None:
        while True:
            item, ctx = q.get()
            try:
                if isinstance(item, tuple) and item and \
                        item[0] == "__become_actor__":
                    self._become_actor(item[1], ctx)
                else:
                    self._execute(item, ctx)
            except BaseException as e:  # noqa: BLE001
                try:
                    if ctx is not None:
                        ctx.reply(None, error=e)
                except Exception:
                    pass

    def _resolve_function(self, key: str):
        fn = self.fn_cache.get(key)
        if fn is None:
            blob = self.backend.kv_get(key)
            if blob is None:
                raise TaskError("LookupError", f"function {key} not exported",
                                "<head kv miss>")
            fn = cloudpickle.loads(blob)
            self.fn_cache[key] = fn
        return fn

    def _resolve_args(self, wire_args: List[dict], kwargs_blob: bytes):
        args = []
        for a in wire_args:
            if "ref" in a:
                oid, owner = a["ref"]
                ref = ObjectRef(ObjectID(oid), WorkerID(owner))
                args.append(self.worker.get(ref))
            else:
                args.append(serialization.deserialize(a["inline"]))
        kwargs = serialization.deserialize(kwargs_blob)
        return args, kwargs

    def _become_actor(self, payload: dict, ctx) -> None:
        spec = pickle_loads(payload["spec_bytes"])
        self.actor_id = spec["actor_id"]
        num_restarts = payload.get("num_restarts", 0)
        try:
            cls = cloudpickle.loads(spec["cls_bytes"])
            args, kwargs = self._resolve_args(spec["args"], spec["kwargs"])
            self.actor_instance = cls(*args, **kwargs)
            # ALL extra lanes (default max_concurrency and groups) start
            # only AFTER construction: until then every call sits in the
            # default queue behind this __become_actor__ item, whose
            # single consumer is this thread — any extra consumer could
            # dequeue a method while __init__ is still in flight and see a
            # None instance.
            import asyncio
            import inspect
            # scan the whole MRO (dir), not vars(cls): inherited coroutine
            # methods must also flip the actor into async mode
            is_async = any(
                inspect.iscoroutinefunction(getattr(cls, n, None))
                or inspect.isasyncgenfunction(getattr(cls, n, None))
                for n in dir(cls))
            mc = spec.get("max_concurrency")
            if is_async:
                # async actor: every method runs on one event loop; the
                # semaphore bounds in-flight coroutines (reference default
                # 1000 for async actors)
                self._aio_loop = asyncio.new_event_loop()
                self._aio_sem = asyncio.Semaphore(mc if mc else 1000)
                threading.Thread(target=self._aio_loop.run_forever,
                                 daemon=True, name="actor-aio").start()
            elif mc and mc > 1:
                self._start_threads(mc)
            for gname, gn in (spec.get("concurrency_groups") or {}).items():
                gq: "queue.Queue" = queue.Queue()
                self._group_queues[gname] = gq
                self._start_threads(max(1, int(gn)), q=gq, tag=f"cg-{gname}")
            self._method_groups = dict(spec.get("method_groups") or {})
        except BaseException as e:  # noqa: BLE001
            tb = traceback.format_exc()
            try:
                self.backend.head.call("actor_failed", {
                    "actor_id": spec["actor_id"],
                    "num_restarts": num_restarts,
                    "reason": f"{type(e).__name__}: {e}\n{tb}"})
            except RpcError:
                pass
            return
        try:
            self.backend.head.call("actor_ready", {
                "actor_id": spec["actor_id"],
                "num_restarts": num_restarts,
                "address": self.backend.server.address})
        except RpcError:
            pass

    def _execute(self, payload: dict, ctx) -> None:
        task_id = payload["task_id"]
        if task_id in self.cancelled:
            ctx.reply({"results": None, "cancelled": True})
            return
        self.worker.current_task_id = TaskID(task_id)
        if self.log_shipper is not None:
            self.log_shipper.set_owner(payload.get("owner") or None)
        # restore the submitter's trace context as ambient for the task
        # body: nested .remote() calls stamp THIS span as their parent,
        # linking the cross-process chain into one trace. Contextvar, so
        # async-actor dispatch carries it into the coroutine (the loop
        # handoff snapshots this thread's context).
        trace_tok = trace_context.activate(
            payload.get("trace_id"), payload.get("span_id"))
        t_start = time.time()
        try:
            args, kwargs = self._resolve_args(payload["args"],
                                              payload["kwargs"])
            if payload.get("actor_id") is not None:
                if self.actor_instance is None:
                    raise RuntimeError("push to non-actor worker")
                method = getattr(self.actor_instance, payload["method_name"],
                                 None)
                if method is None:
                    raise AttributeError(
                        f"actor has no method {payload['method_name']!r}")
                if self._aio_loop is not None:
                    # async actor: hand off to the loop WITHOUT blocking
                    # this lane — that's what lets one replica interleave
                    # many in-flight requests
                    self._dispatch_async(method, args, kwargs, payload, ctx,
                                         t_start)
                    return
                result = method(*args, **kwargs)
            else:
                fn = self._resolve_function(payload["function_key"])
                result = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, (SystemExit, KeyboardInterrupt)):
                raise
            self._reply_error(payload, ctx, e, t_start)
            return
        finally:
            self.worker.current_task_id = None
            trace_context.deactivate(trace_tok)
        if payload.get("streaming"):
            self._stream_out(payload, ctx, result, t_start)
            return
        self._reply_ok(payload, ctx, result, t_start)

    # ----------------------------------------------------- reply packaging

    def _record_span(self, payload: dict, t_start: float, ok: bool) -> None:
        # task span -> event buffer (flushed by the telemetry thread;
        # reference: TaskEventBuffer state transitions)
        buf = getattr(self.backend, "event_buffer", None)
        if buf is None:
            return
        name = payload.get("name") or payload.get("method_name") or "task"
        span_id = payload.get("span_id", "")
        buf.record(
            name=name,
            task_id=TaskID(payload["task_id"]).hex()[:16],
            kind="actor_task" if payload.get("actor_id") else "task",
            start=t_start, end=time.time(), ok=ok,
            trace_id=payload.get("trace_id", ""),
            span_id=span_id,
            parent_span_id=payload.get("parent_span_id", ""))
        # scheduler-phase companion span: submit→start, a CHILD of the
        # execution span so a trace view separates queueing delay from
        # run time (reference: ray task-state timeline's
        # PENDING_NODE_ASSIGNMENT..RUNNING segments)
        submit_ts = payload.get("submit_ts")
        if submit_ts is None:
            return
        try:
            submit_ts = float(submit_ts)
        except (TypeError, ValueError):
            return
        import hashlib
        sched_sid = hashlib.sha256(
            f"sched:{span_id or payload['task_id']!r}".encode()
        ).hexdigest()[:16]
        buf.record(
            name=f"{name}::sched",
            task_id=TaskID(payload["task_id"]).hex()[:16],
            kind="sched",
            start=submit_ts, end=t_start, ok=True,
            trace_id=payload.get("trace_id", ""),
            span_id=sched_sid,
            parent_span_id=span_id,
            lease_ts=payload.get("lease_ts"))
        metrics_mod.submit_to_start_histogram().observe(
            max(0.0, t_start - submit_ts))

    def _reply_error(self, payload: dict, ctx, exc: BaseException,
                     t_start: float) -> None:
        # any reply releases the submitter's serialize-time arg pins, so
        # our queued add-borrower registrations for those args must reach
        # their owners first (transfer-before-release, borrower side)
        self.backend.flush_borrows()
        self._record_span(payload, t_start, ok=False)
        so = serialization.serialize_error(exc)
        n = max(1, payload["num_returns"])
        if payload.get("streaming"):
            ctx.reply({"streaming_count": 0,
                       "streaming_error": so.to_bytes()})
            return
        ctx.reply({"results": [{"inline": so.to_bytes(),
                                "is_error": True}] * n})

    def _reply_ok(self, payload: dict, ctx, result: Any,
                  t_start: float) -> None:
        self.backend.flush_borrows()  # see _reply_error: adds-before-reply
        num_returns = payload["num_returns"]
        if num_returns == 1:
            values = [result]
        else:
            if not isinstance(result, tuple) or len(result) != num_returns:
                self._reply_error(payload, ctx, ValueError(
                    f"declared num_returns={num_returns} but returned "
                    f"{type(result)}"), t_start)
                return
            values = list(result)
        self._record_span(payload, t_start, ok=True)
        cfg = config_mod.GlobalConfig
        results = []
        contained = []
        tid = TaskID(payload["task_id"])
        for i, v in enumerate(values):
            so = serialization.serialize(v)
            contained.extend(so.contained_refs)
            if so.total_bytes <= cfg.memory_store_threshold_bytes:
                results.append({"inline": so.to_bytes(), "is_error": False})
            else:
                oid = ObjectID.for_return(tid, i + 1)
                node = self.backend.object_plane.store_result_bytes(
                    oid, so.to_bytes(),
                    owner=(payload.get("owner") or b"").hex())
                results.append({"in_shm": node})
        # Transfer-before-release (owner-side): refs WE own riding in this
        # reply get the caller pre-registered as a borrower BEFORE the
        # serialize-time pins drop. Without this, releasing the pin races
        # the caller's add_borrower registration, and the loser's object is
        # freed while the caller holds a live ref (observed: the LAST ref
        # of a 20-ref list reply lost the race and get() hung on
        # "pending"). add_borrower is set-based, so the caller's own later
        # registration is idempotent (reference: reference_count.h borrower
        # bookkeeping — returned refs are charged to the caller up front).
        caller = payload.get("owner")
        for r in contained:
            if caller and r.owner_id() == self.worker.worker_id:
                self.worker.refcounter.add_borrower(r.id(), caller)
        ctx.reply({"results": results})
        for r in contained:
            self.worker.refcounter.on_serialized_ref_done(r.id())

    # ------------------------------------------------------------ streaming

    def _send_stream_item(self, owner_client, payload: dict, index: int,
                          value: Any) -> None:
        """Ship one yielded value to the owner (inline or via shm)."""
        cfg = config_mod.GlobalConfig
        oid = ObjectID.for_return(TaskID(payload["task_id"]), index)
        so = serialization.serialize(value)
        msg = {"task_id": payload["task_id"], "object_id": oid.binary(),
               "index": index}
        if so.total_bytes <= cfg.memory_store_threshold_bytes:
            msg["inline"] = so.to_bytes()
        else:
            # creator pin released: the owner's ref is the only keeper, and
            # streamed items are meant to be consumed-and-dropped
            msg["in_shm"] = self.backend.object_plane.store_result_bytes(
                oid, so.to_bytes(),
                owner=(payload.get("owner") or b"").hex())
        caller = payload.get("owner")
        for r in so.contained_refs:
            # same transfer-before-release as _reply_ok
            if caller and r.owner_id() == self.worker.worker_id:
                self.worker.refcounter.add_borrower(r.id(), caller)
        self.backend.flush_borrows()  # adds-before-ship for borrowed refs
        owner_client.oneway("stream_item", msg)
        for r in so.contained_refs:
            self.worker.refcounter.on_serialized_ref_done(r.id())

    def _stream_out(self, payload: dict, ctx, result: Any,
                    t_start: float) -> None:
        """Drain a generator task, shipping items as they are produced
        (reference: streaming generator protocol, _raylet.pyx:1391)."""
        owner = self.backend.object_plane.owner_client(
            WorkerID(payload["owner"]))
        i = 0
        try:
            for v in iter(result):
                i += 1
                self._send_stream_item(owner, payload, i, v)
        except BaseException as e:  # noqa: BLE001
            self._record_span(payload, t_start, ok=False)
            so = serialization.serialize_error(e)
            self.backend.flush_borrows()  # adds-before-reply
            ctx.reply({"streaming_count": i,
                       "streaming_error": so.to_bytes()})
            return
        self._record_span(payload, t_start, ok=True)
        self.backend.flush_borrows()  # see _reply_error: adds-before-reply
        ctx.reply({"streaming_count": i})

    # ---------------------------------------------------------- async actors

    def _dispatch_async(self, method, args, kwargs, payload: dict, ctx,
                        t_start: float) -> None:
        import asyncio
        import inspect

        streaming = bool(payload.get("streaming"))

        def _stream_reply(i: int, exc: Optional[BaseException]) -> None:
            """Reply for a streaming call, preserving the count of items
            already shipped so the consumer drains them before seeing the
            error (same contract as the sync _stream_out path)."""
            self.backend.flush_borrows()  # adds-before-reply
            if exc is None:
                self._record_span(payload, t_start, ok=True)
                ctx.reply({"streaming_count": i})
            else:
                self._record_span(payload, t_start, ok=False)
                so = serialization.serialize_error(exc)
                ctx.reply({"streaming_count": i,
                           "streaming_error": so.to_bytes()})

        async def run():
            async with self._aio_sem:
                if inspect.isasyncgenfunction(method):
                    if not streaming:
                        raise TypeError(
                            f"{payload['method_name']} is an async generator"
                            f" — call it with num_returns='streaming'")
                    owner = self.backend.object_plane.owner_client(
                        WorkerID(payload["owner"]))
                    i = 0
                    try:
                        async for v in method(*args, **kwargs):
                            i += 1
                            # blocking socket write; cheap enough on-loop
                            # for token-sized payloads
                            self._send_stream_item(owner, payload, i, v)
                    except BaseException as e:  # noqa: BLE001
                        _stream_reply(i, e)
                        return None
                    _stream_reply(i, None)
                    return None
                out = method(*args, **kwargs)
                if inspect.isawaitable(out):
                    out = await out
                if streaming:
                    owner = self.backend.object_plane.owner_client(
                        WorkerID(payload["owner"]))
                    i = 0
                    try:
                        for v in iter(out):
                            i += 1
                            self._send_stream_item(owner, payload, i, v)
                    except BaseException as e:  # noqa: BLE001
                        _stream_reply(i, e)
                        return None
                    _stream_reply(i, None)
                    return None
                return out

        fut = asyncio.run_coroutine_threadsafe(run(), self._aio_loop)

        def package(f):
            try:
                result = f.result()
            except BaseException as e:  # noqa: BLE001
                # streaming paths that started shipping replied already
                # (ctx.reply is once-only); this covers pre-iteration
                # failures and non-streaming errors
                self._reply_error(payload, ctx, e, t_start)
                return
            if streaming:
                return  # replied inside run() with the true item count
            self._reply_ok(payload, ctx, result, t_start)

        # done-callbacks run ON the loop thread; serializing a large result
        # there would stall every interleaved coroutine, so hand reply
        # packaging to the reply pool and keep the loop free
        fut.add_done_callback(
            lambda f: self._reply_pool.submit(package, f))


def pickle_loads(data: bytes):
    import pickle
    return pickle.loads(data)


def _dump_stacks() -> dict:
    """All thread stacks of this worker, formatted — the in-process
    analog of the reference's on-demand py-spy profiling
    (dashboard/modules/reporter/profile_manager.py:82): no external
    profiler binary exists in the image, but sys._current_frames gives
    the same "where is this worker stuck" answer."""
    import traceback
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in frames.items():
        # key by name AND ident: same-named threads (e.g. pooled client
        # readers) must not overwrite each other in the report
        key = f"{names.get(ident, 'thread')}-{ident}"
        stacks[key] = "".join(traceback.format_stack(frame))
    return {"pid": os.getpid(), "num_threads": len(stacks),
            "stacks": stacks}


def _profile_burst(p, ctx) -> dict:
    """Synchronous collapsed-stack burst of this worker's threads (the
    worker leg of 'profile --record'; runs on the RPC lane so the task
    thread under observation is never perturbed)."""
    from ray_tpu.util.stack_profiler import burst_capture
    p = p or {}
    return burst_capture(float(p.get("seconds", 2.0) or 2.0),
                         float(p.get("hz", 99.0) or 99.0))


def main() -> None:
    node_addr, head_addr, shm_name, worker_hex, cfg_json = sys.argv[1:6]
    config_mod.GlobalConfig.apply(json.loads(cfg_json))
    # per-worker RTPU_* env (e.g. a runtime_env's env_vars) wins over the
    # propagated cluster table — same precedence as the reference's RAY_*
    # per-process overrides (ray_config_def.h env lookup happens in-process)
    config_mod.GlobalConfig.apply_env_overrides()

    # runtime_env working_dir: the node daemon spawned us with cwd set to
    # the materialized package; make its modules importable like the
    # reference does (runtime_env/working_dir.py adds it to sys.path)
    _wd = os.environ.get("RTPU_WORKING_DIR")
    if _wd:
        sys.path.insert(0, _wd)

    # Die with the node daemon (reference: raylet owns worker lifetimes —
    # node death must kill its workers or "node failure" tests lie).
    try:
        import ctypes
        import signal
        PR_SET_PDEATHSIG = 1
        ctypes.CDLL("libc.so.6", use_errno=True).prctl(
            PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:
        pass

    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime.cluster_backend import ClusterBackend

    worker_id = WorkerID(bytes.fromhex(worker_hex))
    backend = ClusterBackend.connect_as_worker(
        global_worker, head_addr, shm_name, worker_id)
    executor = Executor(backend, global_worker)
    # structured log plane: records go to worker-<id>.log (same dir the
    # node daemon pointed our raw .out/.err streams at) and ride the
    # backend's telemetry flush to the head's LogStore
    from ray_tpu.util import log_plane
    try:
        log_plane.ensure_started(
            role="worker",
            node=os.environ.get("RTPU_NODE_ID", "")[:12],
            worker=worker_hex[:12],
            log_dir=log_plane.session_log_dir(
                os.environ.get("RTPU_SESSION", "")),
            filename=f"worker-{worker_hex[:12]}.log")
    except Exception:  # noqa: BLE001 — logging must never stop boot
        pass
    # XLA compile tracker: jax-free at this point (the seam only hooks
    # jax.monitoring once user code actually imports jax — re-checked
    # at every telemetry flush), so workers that never touch jax pay
    # one idle object
    try:
        from ray_tpu.util import compile_tracker
        compile_tracker.ensure_started(
            role="worker",
            node=os.environ.get("RTPU_NODE_ID", "")[:12],
            worker=worker_hex[:12])
    except Exception:  # noqa: BLE001 — tracking must never stop boot
        pass
    shipper = None
    if config_mod.GlobalConfig.log_to_driver:
        shipper = _LogShipper(backend)
        executor.log_shipper = shipper
    if shipper is not None or log_plane.get_global() is not None:
        sys.stdout = _TeeStream(sys.stdout, "stdout", shipper)
        sys.stderr = _TeeStream(sys.stderr, "stderr", shipper)
        # emit trailing partial lines on orderly exit (SIGKILL loses
        # them from the rings — the durable .out/.err still have them)
        import atexit
        atexit.register(sys.stderr.flush)
        atexit.register(sys.stdout.flush)
    backend.server.handlers.update({
        "push_task": executor.handle_push_task,
        "push_task_batch": executor.handle_push_task_batch,
        "become_actor": executor.handle_become_actor,
        "cancel_task": executor.handle_cancel,
        "dag_start_loop": executor.handle_dag_start_loop,
        "ping": lambda p, c: "pong",
        "dump_stacks": lambda p, c: _dump_stacks(),
        # on-demand burst capture (node daemon fans 'profiles_record'
        # here); samples THIS worker's task threads from the RPC lane
        "profile_burst": _profile_burst,
        "exit": lambda p, c: os._exit(0),
    })
    backend.server.inline_methods.add("push_task")
    backend.server.inline_methods.add("push_task_batch")

    node = RpcClient(node_addr, name="worker->node")
    node.call_retrying("worker_ready", {
        "worker_id": worker_id.binary(),
        "address": backend.server.address,
    })
    # park forever; the node daemon owns our lifetime
    threading.Event().wait()


if __name__ == "__main__":
    main()
