"""Per-node hardware sampler — CPU/RSS/cgroup/arena/TPU gauges.

Role-equivalent to the reference's per-node reporter agent poll loop
(reference: dashboard/modules/reporter/reporter_agent.py sampling psutil +
GPU stats on a period and shipping them to the metrics agent), served from
/proc directly: the node daemon runs one `HardwareSampler` on a ~2s period
and pushes each batch over the existing `telemetry_push` path; the head
lands the points in per-(node, metric) ring buffers (util/timeseries.py).

The procfs/cgroup roots are injectable so tests run against a faked tree;
the TPU probe NEVER imports jax (an import would claim the node's chips —
see accelerators/tpu.py:31): it only reads device memory_stats when some
other code in the process already initialized jax, which is true in TPU
workers and false in the node daemon and on CPU-only hosts.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

Sample = dict  # {"metric": str, "value": float, "tags": {str: str}}


def read_proc_stat_cpu(procfs: str = "/proc") -> Optional[tuple]:
    """(busy_ticks, total_ticks) from the aggregate cpu line."""
    try:
        with open(os.path.join(procfs, "stat")) as f:
            first = f.readline().split()
        if first[:1] != ["cpu"]:
            return None
        ticks = [int(x) for x in first[1:]]
        total = sum(ticks)
        idle = ticks[3] + (ticks[4] if len(ticks) > 4 else 0)  # idle+iowait
        return total - idle, total
    except (OSError, ValueError, IndexError):
        return None


def read_pid_cpu_ticks(pid: int, procfs: str = "/proc") -> Optional[int]:
    """utime+stime ticks for one process (fields 14/15 of /proc/pid/stat;
    comm is parenthesized and may contain spaces — split after ')')."""
    try:
        with open(os.path.join(procfs, str(pid), "stat")) as f:
            rest = f.read().rsplit(")", 1)[1].split()
        # rest[0] is field 3 (state) -> utime is rest[11], stime rest[12]
        return int(rest[11]) + int(rest[12])
    except (OSError, ValueError, IndexError):
        return None


def read_pid_rss(pid: int, procfs: str = "/proc") -> Optional[int]:
    """Resident bytes from /proc/pid/statm (total resident, the operator
    view — the OOM monitor's private-RSS variant subtracts shm views)."""
    try:
        with open(os.path.join(procfs, str(pid), "statm")) as f:
            fields = f.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return None


def read_meminfo(procfs: str = "/proc") -> Optional[tuple]:
    """(available, total) bytes."""
    try:
        fields = {}
        with open(os.path.join(procfs, "meminfo")) as f:
            for line in f:
                k, v = line.split(":", 1)
                fields[k] = int(v.strip().split()[0]) * 1024
        return fields["MemAvailable"], fields["MemTotal"]
    except (OSError, KeyError, ValueError):
        return None


def read_cgroup_cpu_usec(cg_dir: str) -> Optional[int]:
    try:
        with open(os.path.join(cg_dir, "cpu.stat")) as f:
            for line in f:
                k, _, v = line.partition(" ")
                if k == "usage_usec":
                    return int(v)
    except (OSError, ValueError):
        pass
    return None


def read_cgroup_memory_current(cg_dir: str) -> Optional[int]:
    try:
        with open(os.path.join(cg_dir, "memory.current")) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def read_cgroup_pressure(cg_dir: str, which: str = "cpu") -> Optional[float]:
    """avg10 of the `some` line of {cpu,memory,io}.pressure (PSI)."""
    try:
        with open(os.path.join(cg_dir, f"{which}.pressure")) as f:
            for line in f:
                if line.startswith("some"):
                    for part in line.split():
                        if part.startswith("avg10="):
                            return float(part[6:])
    except (OSError, ValueError):
        pass
    return None


def tpu_memory_samples() -> List[Sample]:
    """HBM used/limit per local TPU device — ONLY when jax is already
    live in this process (never imports it; importing here would claim
    the chips and is meaningless on CPU anyway)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    out: List[Sample] = []
    try:
        for i, dev in enumerate(jax.local_devices()):
            if getattr(dev, "platform", "") not in ("tpu", "gpu"):
                continue
            try:
                ms = dev.memory_stats() or {}
            except Exception:  # noqa: BLE001 — backend without stats
                continue
            used = ms.get("bytes_in_use")
            limit = ms.get("bytes_limit") or ms.get("bytes_reservable_limit")
            tags = {"device": str(i)}
            if used is not None:
                out.append({"metric": "tpu_hbm_used_bytes",
                            "value": float(used), "tags": tags})
            if limit is not None:
                out.append({"metric": "tpu_hbm_limit_bytes",
                            "value": float(limit), "tags": tags})
    except Exception:  # noqa: BLE001 — a probe must never break telemetry
        return out
    return out


class HardwareSampler:
    """Stateful delta-based sampler; one per node daemon.

    workers(): -> [{"worker_id": hex, "pid": int, "state": str}, ...]
    arena_stats(): -> ShmStore.stats() dict (or {}).
    """

    def __init__(self, procfs: str = "/proc",
                 cgroup_dir: Optional[str] = None,
                 workers: Optional[Callable[[], List[dict]]] = None,
                 arena_stats: Optional[Callable[[], dict]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.procfs = procfs
        self.cgroup_dir = cgroup_dir
        self._workers = workers or (lambda: [])
        self._arena_stats = arena_stats or (lambda: {})
        self._clock = clock
        self._ncpu = os.cpu_count() or 1
        try:
            self._hz = os.sysconf("SC_CLK_TCK")
        except (ValueError, OSError):
            self._hz = 100
        # previous readings for the delta-based percentages
        self._prev_node_cpu: Optional[tuple] = None          # (busy, total)
        self._prev_pid_ticks: Dict[int, tuple] = {}          # pid -> (t, ticks)
        self._prev_cg_usec: Optional[tuple] = None           # (t, usec)
        # probes that already logged a failure (warn once, not per period)
        self._warned_probes: set = set()

    # -- individual probes (each returns a list of samples) ---------------

    def _node_cpu(self) -> List[Sample]:
        cur = read_proc_stat_cpu(self.procfs)
        if cur is None:
            return []
        prev, self._prev_node_cpu = self._prev_node_cpu, cur
        if prev is None or cur[1] <= prev[1]:
            return []
        busy_d, total_d = cur[0] - prev[0], cur[1] - prev[1]
        pct = 100.0 * max(0, busy_d) / max(1, total_d)
        return [{"metric": "node_cpu_percent", "value": round(pct, 2),
                 "tags": {}}]

    def _node_mem(self) -> List[Sample]:
        mem = read_meminfo(self.procfs)
        if mem is None:
            return []
        available, total = mem
        return [
            {"metric": "node_mem_used_bytes",
             "value": float(total - available), "tags": {}},
            {"metric": "node_mem_total_bytes", "value": float(total),
             "tags": {}},
        ]

    def _worker_samples(self) -> List[Sample]:
        out: List[Sample] = []
        now = self._clock()
        live_pids = set()
        for w in self._workers():
            pid = w.get("pid")
            if pid is None:
                continue
            live_pids.add(pid)
            wid = str(w.get("worker_id", pid))[:12]
            tags = {"worker": wid, "state": str(w.get("state", ""))}
            rss = read_pid_rss(pid, self.procfs)
            if rss is not None:
                out.append({"metric": "worker_rss_bytes",
                            "value": float(rss), "tags": tags})
            ticks = read_pid_cpu_ticks(pid, self.procfs)
            if ticks is not None:
                prev = self._prev_pid_ticks.get(pid)
                self._prev_pid_ticks[pid] = (now, ticks)
                if prev is not None and now > prev[0] \
                        and ticks >= prev[1]:
                    # ticks < prev means the pid was REUSED between
                    # passes (counter restarted from ~0): drop the
                    # garbage delta and let the fresh baseline above
                    # seed the next pass. Clamp the emitted percentage
                    # to the host's physical ceiling — a tick-counter
                    # hiccup must never graph a 4000%-CPU worker.
                    pct = 100.0 * (ticks - prev[1]) / self._hz \
                        / (now - prev[0])
                    pct = min(max(0.0, pct), 100.0 * self._ncpu)
                    out.append({"metric": "worker_cpu_percent",
                                "value": round(pct, 2),
                                "tags": tags})
        # forget exited pids so the delta table doesn't grow with churn
        for pid in [p for p in self._prev_pid_ticks if p not in live_pids]:
            del self._prev_pid_ticks[pid]
        return out

    def _cgroup_samples(self) -> List[Sample]:
        if not self.cgroup_dir:
            return []
        out: List[Sample] = []
        now = self._clock()
        usec = read_cgroup_cpu_usec(self.cgroup_dir)
        if usec is not None:
            prev, self._prev_cg_usec = self._prev_cg_usec, (now, usec)
            if prev is not None and now > prev[0]:
                pct = (usec - prev[1]) / 1e4 / (now - prev[0])
                out.append({"metric": "cgroup_cpu_percent",
                            "value": round(max(0.0, pct), 2), "tags": {}})
        mem = read_cgroup_memory_current(self.cgroup_dir)
        if mem is not None:
            out.append({"metric": "cgroup_mem_current_bytes",
                        "value": float(mem), "tags": {}})
        for which in ("cpu", "memory"):
            avg10 = read_cgroup_pressure(self.cgroup_dir, which)
            if avg10 is not None:
                out.append({"metric": f"cgroup_{which}_pressure_avg10",
                            "value": avg10, "tags": {}})
        return out

    def _arena_samples(self) -> List[Sample]:
        try:
            st = self._arena_stats() or {}
        except Exception:  # noqa: BLE001 — store closing during shutdown
            return []
        out: List[Sample] = []
        for key, metric in (("bytes_used", "object_store_used_bytes"),
                            ("capacity", "object_store_capacity_bytes"),
                            ("num_objects", "object_store_num_objects"),
                            ("total_evicted", "object_store_evictions")):
            if key in st:
                out.append({"metric": metric, "value": float(st[key]),
                            "tags": {}})
        return out

    def sample(self) -> List[Sample]:
        """One sampling pass; each call emits the current gauge batch
        (CPU percentages need a prior pass to have a delta, so the very
        first call omits them).

        Probes are ISOLATED: one raising probe (e.g. tpu_memory_samples
        mid-backend-shutdown) loses only its own gauges for that pass,
        never the whole batch — and logs once, not once per period."""
        out: List[Sample] = []
        for name, probe in (("node_cpu", self._node_cpu),
                            ("node_mem", self._node_mem),
                            ("workers", self._worker_samples),
                            ("cgroup", self._cgroup_samples),
                            ("arena", self._arena_samples),
                            ("tpu", tpu_memory_samples)):
            try:
                out += probe()
            except Exception as e:  # noqa: BLE001 — probe fault boundary
                if name not in self._warned_probes:
                    self._warned_probes.add(name)
                    logger.warning(
                        "hardware probe %s failed (suppressing repeats "
                        "for this probe): %r", name, e)
        ts = time.time()
        for s in out:
            s.setdefault("ts", ts)
        return out
