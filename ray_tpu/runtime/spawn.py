"""Child-process environment construction shared by all daemon spawners."""

from __future__ import annotations

import os
from typing import Dict


def child_env(extra: Dict[str, str] | None = None) -> Dict[str, str]:
    """Env for spawned daemons/workers: make the ray_tpu package importable
    even when the parent added it via sys.path (not PYTHONPATH)."""
    import ray_tpu
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    if extra:
        env.update(extra)
    return env
