"""Head service — the global control plane (GCS role).

Role-equivalent to the reference's GcsServer (reference:
src/ray/gcs/gcs_server/gcs_server.h:89) with its managers collapsed into one
process: node membership (gcs_node_manager.h:45), actor directory + restart
orchestration (gcs_actor_manager.h:324, RestartActor at
gcs_actor_manager.cc:413), internal KV (gcs_kv_manager.h), health checks
(gcs_health_check_manager.h:45), and cluster-level scheduling decisions
(delegated to the C++ ClusterState, the role of
raylet/scheduling/cluster_resource_scheduler.h:44 — here centralized since
lease accounting lives on the head, not gossiped).

Leases: a client asks the head for (node, worker) to run a resource shape;
the head acquires resources, asks the node daemon to pop a worker from its
pool, and hands back the worker address. The client pushes tasks directly
to the worker (the reference's lease + direct PushTask design,
transport/normal_task_submitter.h:74) and releases the lease when idle.
"""

from __future__ import annotations

import collections
import json
import os
import pickle
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import config as config_mod
from ray_tpu.core._native import (POLICY_HYBRID, POLICY_NODE_AFFINITY,
                                  POLICY_SPREAD, ClusterState)
from ray_tpu.runtime import wire
from ray_tpu.runtime.protocol import ClientPool, RpcError, RpcServer

# actor states (reference: gcs.proto ActorTableData.ActorState)
PENDING = "PENDING"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"

_POLICY_BY_NAME = {
    "hybrid": POLICY_HYBRID,
    "spread": POLICY_SPREAD,
    "node_affinity": POLICY_NODE_AFFINITY,
}


def _bundle_fits(pg: dict, idx: int, resources: Dict[str, float]) -> bool:
    """Caller holds the head lock. True if `resources` fit what remains of
    bundle `idx` after current draws."""
    bundle = pg["bundles"][idx]
    used = pg.setdefault("used", [dict() for _ in pg["bundles"]])[idx]
    return all(used.get(k, 0.0) + v <= bundle.get(k, 0.0) + 1e-9
               for k, v in resources.items())


def _bundle_draw(pg: dict, idx: int, resources: Dict[str, float]) -> None:
    used = pg.setdefault("used", [dict() for _ in pg["bundles"]])[idx]
    for k, v in resources.items():
        used[k] = used.get(k, 0.0) + v


class _KvStore:
    """Head internal KV table (reference: GcsInternalKVManager,
    src/ray/gcs/gcs_server/gcs_kv_manager.h — a C++ KV service the Python
    layer also reads).

    Plain dict until the RPC server exists; once the native transport's
    listener fast-path is enabled the table LIVES inside the C event loop
    (src/transport.cc FastKV): client kv/ping frames are answered without
    entering Python at all, and this adapter becomes the head-side
    accessor over the same map. Values are pickled so str/bytes/objects
    round-trip identically through both paths.
    """

    def __init__(self):
        self._dict: Optional[Dict[str, Any]] = {}
        self._server = None
        self._mutations = 0  # dict-mode mutation counter (dirty tracking)
        # dict-mode check-and-set atomicity (handlers run on a thread
        # pool); the native store has its own C-side mutex
        self._dict_lock = threading.Lock()

    def attach_native(self, server, incarnation: int) -> bool:
        if not hasattr(server, "enable_kv_fastpath"):
            return False  # pure-Python transport fallback
        if not server.enable_kv_fastpath(incarnation):
            return False
        # migrate under the dict lock: the RPC server is ALREADY serving,
        # so a pickle-path kv_put can race this loop (reconnecting clients
        # land the moment the listener is up). Writers that grab the lock
        # after us see _server set and go native; writers that held it
        # before us finished their dict write before we copied.
        with self._dict_lock:
            for k, v in self._dict.items():  # migrate snapshot-restored keys
                server.kv_fast_put(k.encode(), pickle.dumps(v, protocol=5))
            self._server = server
            self._dict = None
        return True

    @property
    def native(self) -> bool:
        return self._server is not None

    def put(self, key: str, value: Any, overwrite: bool = True) -> bool:
        """Returns True when the key was newly created."""
        if self._server is None:
            with self._dict_lock:
                if self._server is None:  # not migrated mid-wait
                    exists = key in self._dict
                    if overwrite or not exists:
                        self._dict[key] = value
                        self._mutations += 1
                    return not exists
        return self._server.kv_fast_put(
            key.encode(), pickle.dumps(value, protocol=5), overwrite)

    def get(self, key: str) -> Any:
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    return self._dict.get(key)
        raw = self._server.kv_fast_get(key.encode())
        return None if raw is None else pickle.loads(raw)

    def delete(self, key: str) -> bool:
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    if key in self._dict:
                        del self._dict[key]
                        self._mutations += 1
                        return True
                    return False
        return self._server.kv_fast_del(key.encode())

    def keys(self, prefix: str = "") -> List[str]:
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    return [k for k in self._dict if k.startswith(prefix)]
        return [k.decode()
                for k in self._server.kv_fast_keys(prefix.encode())]

    def items(self) -> Dict[str, Any]:
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    return dict(self._dict)
        return {k.decode(): pickle.loads(v)
                for k, v in self._server.kv_fast_items().items()}

    def items_raw(self) -> Dict[str, bytes]:
        """Pickled-value form — what snapshots store: skips the
        loads-then-redump round trip over possibly-megabyte blobs."""
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    return {k: pickle.dumps(v, protocol=5)
                            for k, v in self._dict.items()}
        return {k.decode(): v
                for k, v in self._server.kv_fast_items().items()}

    def put_raw(self, key: str, raw: bytes) -> None:
        """Restore one snapshot entry (already-pickled value)."""
        if self._server is None:
            with self._dict_lock:
                if self._server is None:
                    self._dict[key] = pickle.loads(raw)
                    self._mutations += 1
                    return
        self._server.kv_fast_put(key.encode(), raw)

    def version(self) -> int:
        """Mutation counter — client fast-path writes bypass Python, so
        persistence polls this instead of relying on handler dirty bits."""
        if self._server is not None:
            return self._server.kv_fast_version()
        return self._mutations


class _NodeEntry:
    __slots__ = ("node_id", "address", "shm_name", "resources", "alive",
                 "last_seen", "missed")

    def __init__(self, node_id: str, address: str, shm_name: str,
                 resources: Dict[str, float]):
        self.node_id = node_id
        self.address = address
        self.shm_name = shm_name
        self.resources = resources
        self.alive = True
        self.last_seen = time.monotonic()
        self.missed = 0


class _ActorEntry:
    __slots__ = ("actor_id", "spec_bytes", "state", "address", "node_id",
                 "worker_id", "restarts_left", "max_task_retries", "reason",
                 "name_key", "resources", "owner_addr", "class_name",
                 "num_restarts", "pg", "lease_resources", "pg_drawn_bundle",
                 "runtime_env")

    def __init__(self, actor_id: bytes, spec_bytes: bytes, restarts_left: int,
                 max_task_retries: int, name_key: str,
                 resources: Dict[str, float], owner_addr: str,
                 class_name: str):
        self.actor_id = actor_id
        self.spec_bytes = spec_bytes
        self.state = PENDING
        self.address: Optional[str] = None
        self.node_id: Optional[str] = None
        self.worker_id: Optional[bytes] = None
        self.restarts_left = restarts_left
        self.max_task_retries = max_task_retries
        self.reason = ""
        self.name_key = name_key
        self.resources = resources
        self.owner_addr = owner_addr
        self.class_name = class_name
        self.num_restarts = 0
        self.pg = None  # (pg_id, bundle_index) when PG-scheduled
        # physical shape for the node lease (chip env etc.); differs from
        # `resources` for PG actors, whose cluster accounting lives in the
        # bundle reservation
        self.lease_resources = dict(resources)
        self.pg_drawn_bundle: Optional[int] = None
        self.runtime_env: Optional[dict] = None


class _LeaseEntry:
    __slots__ = ("lease_id", "node_id", "worker_id", "worker_addr",
                 "resources", "created", "peer", "pg_id", "bundle_index",
                 "fast_key")

    def __init__(self, lease_id: str, node_id: str, worker_id: bytes,
                 worker_addr: str, resources: Dict[str, float], peer,
                 pg_id: Optional[bytes] = None, bundle_index: int = -1,
                 fast_key: Optional[int] = None):
        self.lease_id = lease_id
        self.node_id = node_id
        self.worker_id = worker_id
        self.worker_addr = worker_addr
        self.resources = resources
        self.created = time.monotonic()
        self.peer = peer  # requesting connection; leases die with it
        self.pg_id = pg_id
        self.bundle_index = bundle_index
        # set for grants living in the native lease pool (transport.cc
        # FastLease): peer is None there — the C loop tracks the holder
        self.fast_key = fast_key


class Head:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session: str = "", persist_path: str = ""):
        self.session = session
        # Distinguishes head processes across restarts: node daemons compare
        # it on every liveness poll and re-register when it changes
        # (reference: GCS restart detection — raylets reconnect and actors
        # re-resolve, gcs_server/gcs_init_data.h + gcs_actor_manager.h:324).
        self.incarnation = os.urandom(4).hex()
        # Table durability (reference: GCS table persistence via Redis,
        # store_client/redis_store_client.h): KV + job counter + actor
        # directory + placement groups snapshot to disk; on restart the
        # tables are rebuilt and reconciled against re-registering nodes.
        # Leases are deliberately NOT persisted — they are bound to client
        # connections, and clients fall back to returning leased workers
        # directly to their node when the head forgot the lease.
        self._persist_path = persist_path
        self._persist_dirty = False
        self._persisted_kv_version = 0  # last KV version snapshotted
        # serializes snapshot WRITES (persist loop vs stop(): two threads
        # sharing one .tmp path would interleave into a torn pickle)
        self._persist_write_lock = threading.Lock()
        # prompt-flush signal: rare-but-important transitions (actor
        # ready/dead, PG created) kick the persist loop instead of waiting
        # out the 1s batch tick, narrowing the window a hard head kill can
        # lose a transition in (KV writes stay batched)
        self._persist_kick = threading.Event()
        # actor_ids/pg_ids restored from a snapshot, awaiting a node
        # re-registration that claims them; swept after the recovery grace
        self._recovering_actors: set = set()
        self._recovering_pgs: set = set()
        # restored actors that had no worker yet: re-placed at boot
        self._respawn_on_boot: list = []
        self.cluster = ClusterState()
        cfg = config_mod.GlobalConfig
        self.cluster.set_spread_threshold(cfg.scheduler_spread_threshold)
        self._lock = threading.RLock()
        self._nodes: Dict[str, _NodeEntry] = {}
        self._actors: Dict[bytes, _ActorEntry] = {}
        self._named: Dict[str, bytes] = {}  # "ns:name" -> actor_id
        self._actor_by_worker: Dict[bytes, bytes] = {}  # worker_id -> actor_id
        self._kv = _KvStore()
        self._pgs: Dict[bytes, dict] = {}  # PlacementGroupID bin -> info
        self._next_job = 0
        if self._persist_path:
            # restore BEFORE the RPC server exists: a client whose ping
            # succeeded must never read a miss on persisted keys or have
            # a fresh put clobbered by the stale snapshot applying late
            self._load_snapshot()
        self._leases: Dict[str, _LeaseEntry] = {}
        self._lease_counter = 0
        # telemetry (reference: GcsTaskManager events + metrics agent):
        # per-worker metric snapshots + bounded task-span ring buffer
        self._metrics: Dict[str, dict] = {}
        self._objects: Dict[str, dict] = {}  # worker -> object summary
        self._task_events: collections.deque = collections.deque(
            maxlen=cfg.event_buffer_size)
        # hardware time series (node samplers push via telemetry_push):
        # fixed rings per (node, metric, tags) — see util/timeseries.py
        from ray_tpu.util.timeseries import TimeSeriesStore
        self._timeseries = TimeSeriesStore(
            maxlen=cfg.timeseries_ring_points)
        # LLM request records (llm/request_log.py flight recorders ship
        # over telemetry_push): rid -> wire dict, bounded ring — the
        # backing store for `python -m ray_tpu requests` / /api/requests
        self._llm_requests: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._llm_requests_cap = max(2, cfg.llm_request_log_size)
        # structured cluster event journal (reference: GCS cluster-event
        # log surfaced by `ray list cluster-events`): node/worker/actor
        # transitions, spill overflows, lease failures, autoscaler moves —
        # sequenced at arrival, dumped via events_dump
        from ray_tpu.runtime.event_journal import ClusterEventJournal
        self.journal = ClusterEventJournal(
            capacity=cfg.cluster_event_journal_size)
        # cluster-wide sampling-profiler plane (util/stack_profiler.py):
        # every process's collapsed-stack exports ride telemetry_push into
        # per-process rings here, merged on read by profiles_dump — and the
        # head profiles ITSELF (the 1.7k-LoC Python policy the slow
        # control-plane rows blame needs frame-level evidence)
        from ray_tpu.util import stack_profiler as profiler_mod
        self._profiler_mod = profiler_mod
        self._profiles = profiler_mod.ProfileStore()
        try:
            profiler_mod.ensure_started()
        except Exception:  # noqa: BLE001 — profiling must never stop boot
            pass
        # structured log plane (util/log_plane.py): every process's log
        # ring rides telemetry_push into per-process rings here, served
        # by logs_dump — and the head logs ITSELF (snapshot warnings,
        # lifecycle diagnostics) into the same store + head.log
        from ray_tpu.util import log_plane as log_plane_mod
        self._log_plane_mod = log_plane_mod
        self._logs = log_plane_mod.LogStore()
        try:
            log_plane_mod.ensure_started(
                role="head",
                log_dir=log_plane_mod.session_log_dir(session),
                filename="head.log")
            log_plane_mod.get_logger().info(
                f"head started (session {session})")
        except Exception:  # noqa: BLE001 — logging must never stop boot
            pass
        # XLA compile observability plane (util/compile_tracker.py):
        # every jax-bearing process's compile-record ring rides
        # telemetry_push into per-process rings here, served by
        # compiles_dump. The head starts its own tracker for symmetry —
        # it never imports jax, so the listeners never hook
        from ray_tpu.util import compile_tracker as compile_mod
        self._compile_mod = compile_mod
        self._compiles = compile_mod.CompileStore()
        try:
            compile_mod.ensure_started(role="head")
        except Exception:  # noqa: BLE001 — tracking must never stop boot
            pass
        # unserviceable demand, deduped per (requester, shape): each
        # submitter polls its shape every ~0.2s, so per-poll appends would
        # over-count 25x per window (the autoscaler's demand signal;
        # reference: GcsAutoscalerStateManager pending-demand reporting)
        self._demand: Dict[tuple, dict] = {}
        self._node_clients = ClientPool(name="head->node")
        self._stopped = threading.Event()
        # general topic pub/sub + the head's own cluster-event feed on it
        # (reference: GCS pubsub node/actor channels, publisher.h:297)
        from ray_tpu.runtime.pubsub import PubsubBroker
        self.pubsub = PubsubBroker(epoch=self.incarnation)
        self.server = RpcServer({
            "register_node": self._h_register_node,
            "unregister_node": self._h_unregister_node,
            "list_nodes": self._h_list_nodes,
            "connect_driver": self._h_connect_driver,
            "kv_put": self._h_kv_put,
            "kv_get": self._h_kv_get,
            "kv_del": self._h_kv_del,
            "kv_keys": self._h_kv_keys,
            "request_lease": self._h_request_lease,
            "release_lease": self._h_release_lease,
            "create_actor": self._h_create_actor,
            "actor_ready": self._h_actor_ready,
            "actor_failed": self._h_actor_failed,
            "get_actor": self._h_get_actor,
            "get_actor_by_name": self._h_get_actor_by_name,
            "kill_actor": self._h_kill_actor,
            "worker_died": self._h_worker_died,
            "create_placement_group": self._h_create_pg,
            "remove_placement_group": self._h_remove_pg,
            "get_placement_group": self._h_get_pg,
            "cluster_resources": self._h_cluster_resources,
            "available_resources": self._h_available_resources,
            "state_dump": self._h_state_dump,
            "telemetry_push": self._h_telemetry_push,
            "metrics_dump": self._h_metrics_dump,
            "timeline_dump": self._h_timeline_dump,
            "timeseries_dump": self._h_timeseries_dump,
            "requests_dump": self._h_requests_dump,
            "events_dump": self._h_events_dump,
            "objects_dump": self._h_objects_dump,
            "profiles_dump": self._h_profiles_dump,
            "logs_dump": self._h_logs_dump,
            "compiles_dump": self._h_compiles_dump,
            "profiles_record": self._h_profiles_record,
            "journal_record": self._h_journal_record,
            "autoscaler_state": self._h_autoscaler_state,
            "pubsub_publish": lambda p, c: self.pubsub.publish(
                p["topic"], p["message"]),
            "pubsub_poll": lambda p, c: self.pubsub.poll(
                p["cursors"], p.get("timeout_s", 2.0)),
            "pubsub_topics": lambda p, c: self.pubsub.topics(),
            "ping": lambda p, c: {"pong": True,
                                  "incarnation": self.incarnation},
        }, host=host, port=port, max_workers=32, name="head")
        # Native kv/ping service: with the C++ transport, kv_put/kv_get/
        # kv_del/ping fast-frames are answered inside the event loop — the
        # head's Python never runs for them (SURVEY §2.2 native control
        # plane; the Python handlers above remain for pickle-path clients
        # and both views share one table).
        self._kv.attach_native(self.server, int(self.incarnation, 16))
        # a crashed client can't release its leases; reclaim them when its
        # connection drops (reference: raylet returns leased workers when
        # the owner dies — lease lifetime is bound to the owner)
        self.server.on_disconnect = self._on_client_disconnect
        # Native lease pool (verdict: "serve lease grant/release as native
        # fast frames with Python keeping only placement policy"): Python
        # pre-stocks ready grants per resource-shape sig; FOP_LEASE_ACQ/REL
        # are then served inside the C loop. Python keeps placement
        # (stocking), reclamation, and drain policy.
        self._fast_lease_on = (
            config_mod.GlobalConfig.fast_lease_pool_target > 0
            and self._kv.native  # fast frames route into the C loop
            and hasattr(self.server, "lease_stock")
            and hasattr(self.server, "on_disconnect_conn"))
        self._restock_wants: Dict[int, dict] = {}  # sig -> resources/want
        self._stocked_sigs: set = set()  # every sig EVER stocked (drain set)
        self._restock_kick = threading.Event()
        self._fast_hits_seen = 0
        self._fast_idle_since = time.monotonic()
        # Python-path pool consumption (the pool-first branch in
        # _h_request_lease) — folded into the idle-drain activity check:
        # C-loop `hits` alone misses a steady Python-path consumer and
        # drains a pool that is actually hot.
        self._py_unstocks = 0
        if self._fast_lease_on:
            self.server.on_disconnect_conn = self._on_conn_fastlease_reclaim
            threading.Thread(target=self._restock_loop, daemon=True,
                             name="head-fastlease").start()
        self.address = self.server.address
        if self._persist_path:
            self._persist_thread = threading.Thread(
                target=self._persist_loop, daemon=True, name="head-persist")
            self._persist_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="head-health")
        self._health_thread.start()
        if self._recovering_actors or self._recovering_pgs:
            threading.Thread(target=self._recovery_grace_loop, daemon=True,
                             name="head-recovery").start()
        for entry in self._respawn_on_boot:
            self._spawn_actor(entry)
        self._respawn_on_boot = []

    # ----------------------------------------------------- table durability

    #: _ActorEntry fields snapshotted verbatim (placement fields are
    #: deliberately excluded: node_id/worker_id/address are reconciled
    #: against re-registering nodes, never trusted from disk)
    _ACTOR_PERSIST_FIELDS = ("spec_bytes", "state", "restarts_left",
                             "max_task_retries", "reason", "name_key",
                             "resources", "owner_addr", "class_name",
                             "num_restarts", "pg", "lease_resources",
                             "runtime_env")

    def _load_snapshot(self) -> None:
        if not os.path.exists(self._persist_path):
            return  # fresh cluster: nothing to restore
        try:
            with open(self._persist_path, "rb") as f:
                data = pickle.load(f)
        except Exception as e:  # noqa: BLE001 — unreadable/torn snapshot
            from ray_tpu.util import log_plane
            log_plane.get_logger().warning(
                f"discarding unreadable head snapshot "
                f"{self._persist_path}: {e!r}")
            return
        with self._lock:
            for k, raw in data.get("kv_raw", {}).items():
                self._kv.put_raw(k, raw)
            for k, v in data.get("kv", {}).items():  # legacy snapshots
                self._kv.put(k, v)
            self._next_job = max(self._next_job, data.get("next_job", 0))
            for rec in data.get("actors", ()):
                entry = _ActorEntry(rec["actor_id"], rec["spec_bytes"],
                                    rec["restarts_left"],
                                    rec["max_task_retries"], rec["name_key"],
                                    rec["resources"], rec["owner_addr"],
                                    rec["class_name"])
                for f in self._ACTOR_PERSIST_FIELDS:
                    if f in rec:
                        setattr(entry, f, rec[f])
                if entry.state != DEAD:
                    if rec.get("had_worker"):
                        # was live when the snapshot landed: hold in
                        # RESTARTING until its node re-registers and claims
                        # the still-running worker, or the grace expires
                        entry.state = RESTARTING
                        self._recovering_actors.add(entry.actor_id)
                    else:
                        # never had a worker (placement was in flight and
                        # died with the old head): place it fresh instead
                        # of burning a restart in the lost-worker path.
                        # Bump the fencing epoch WITHOUT consuming a
                        # restart: if a stale snapshot hid a worker that
                        # did start, its actor_ready carries the old
                        # num_restarts and is rejected, and re-registration
                        # kills it as unclaimed.
                        entry.state = PENDING
                        entry.num_restarts += 1
                        self._respawn_on_boot.append(entry)
                self._actors[entry.actor_id] = entry
            self._named.update(data.get("named", {}))
            for pg_id, pg in data.get("pgs", {}).items():
                pg = dict(pg)
                # lease draws died with their clients; actor draws are
                # re-established on reconcile
                pg["used"] = [dict() for _ in pg["bundles"]]
                if pg["state"] == "CREATED":
                    # keep the node mapping provisionally; bundles are
                    # re-acquired per node as nodes return (grace sweep
                    # reschedules pgs whose nodes never come back)
                    pg["_acq"] = set()
                    self._recovering_pgs.add(pg_id)
                self._pgs[pg_id] = pg

    def _save_snapshot(self) -> None:
        with self._persist_write_lock:
            with self._lock:
                # KV dirtiness comes from the table's mutation counter
                # (native fast-path writes never run Python handlers).
                # Checked HERE, not only in the poll loop, so stop()'s
                # final snapshot can't miss writes newer than the loop's
                # last 1s tick.
                v = self._kv.version()
                if v != self._persisted_kv_version:
                    self._persisted_kv_version = v
                    self._persist_dirty = True
                if not self._persist_dirty:
                    return
                actors = []
                for aid, e in self._actors.items():
                    rec = {"actor_id": aid,
                           "had_worker": e.worker_id is not None}
                    for f in self._ACTOR_PERSIST_FIELDS:
                        rec[f] = getattr(e, f)
                    actors.append(rec)
                pgs = {}
                for pid, pg in self._pgs.items():
                    pgs[pid] = {k: pg[k] for k in
                                ("bundles", "nodes", "state", "strategy",
                                 "name")}
                snap = {"kv_raw": self._kv.items_raw(),
                        "next_job": self._next_job,
                        "actors": actors, "named": dict(self._named),
                        "pgs": pgs}
                self._persist_dirty = False
            try:
                tmp = self._persist_path + ".tmp"
                os.makedirs(os.path.dirname(self._persist_path) or ".",
                            exist_ok=True)
                with open(tmp, "wb") as f:
                    pickle.dump(snap, f)
                os.replace(tmp, self._persist_path)
            except Exception:
                # failed write must not discard the dirty state: re-mark
                # so the loop retries once the disk recovers
                with self._lock:
                    self._persist_dirty = True
                raise

    def _persist_loop(self) -> None:
        while not self._stopped.is_set():
            self._persist_kick.wait(timeout=1.0)
            self._persist_kick.clear()
            if self._stopped.is_set():
                return  # stop() takes the final snapshot itself
            try:
                self._save_snapshot()
            except Exception:  # noqa: BLE001
                pass

    # ------------------------------------------------------ restart recovery

    def _recovery_grace_loop(self) -> None:
        """After a restart, wait for nodes to re-register and claim the
        restored actors/PGs; whatever is still unclaimed when the grace
        expires is treated as lost (actors take the normal restart path,
        PGs go back to PENDING and reschedule)."""
        grace = config_mod.GlobalConfig.head_recovery_grace_s
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and not self._stopped.is_set():
            with self._lock:
                if not self._recovering_actors and not self._recovering_pgs:
                    return
            time.sleep(0.1)
        displaced: List[tuple] = []  # (actor_id, node_addr, worker_id)
        with self._lock:
            lost_actors = [aid for aid in self._recovering_actors
                           if aid in self._actors]
            self._recovering_actors.clear()
            lost_pgs = list(self._recovering_pgs)
            self._recovering_pgs.clear()
            for pg_id in lost_pgs:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    continue
                # release what partial re-acquisition happened, then let
                # the scheduler place the whole group fresh
                for idx in pg.pop("_acq", ()):
                    node_id = pg["nodes"][idx]
                    if node_id in self._nodes and self._nodes[node_id].alive:
                        self.cluster.release(node_id, pg["bundles"][idx])
                pg["state"] = "PENDING"
                pg["nodes"] = None
                pg["used"] = [dict() for _ in pg["bundles"]]
                self._persist_dirty = True
                # actors already reconciled into this group are now running
                # outside any reservation: displace them so the restart
                # path re-places them once the group reschedules
                for aid, e in self._actors.items():
                    if e.pg is not None and e.pg[0] == pg_id and \
                            e.state == ALIVE and e.worker_id is not None:
                        node = self._nodes.get(e.node_id)
                        displaced.append(
                            (aid, node.address if node is not None and
                             node.alive else None, e.worker_id))
        for aid, node_addr, worker_id in displaced:
            if node_addr is not None:
                try:
                    self._node_clients.get(node_addr).call(
                        "kill_worker", {"worker_id": worker_id})
                except RpcError:
                    pass
            self._on_actor_worker_lost(
                aid, "placement group rescheduled after head restart")
        for aid in lost_actors:
            self._on_actor_worker_lost(
                aid, "worker lost across head restart")
        if lost_pgs:
            self._try_schedule_pgs()

    # ------------------------------------------------------------- membership

    def _h_register_node(self, p, ctx):
        """Admit (or re-admit) a node. A re-registration after a head
        restart carries the node's still-running actor workers; the head
        claims them for the restored actor entries and tells the node to
        kill workers whose actors it no longer knows (reference: raylet
        reconnect after GCS restart — gcs_init_data.h rebuild + actor
        re-resolution, gcs_actor_manager.h:324)."""
        node_id = p["node_id"]
        kill: List[bytes] = []
        with self._lock:
            known = self._nodes.get(node_id)
            new_node = known is None or not known.alive
            if new_node:
                entry = _NodeEntry(node_id, p["address"], p["shm_name"],
                                   p["resources"])
                self._nodes[node_id] = entry
                self.cluster.add_node(node_id, p["resources"])
            else:
                # idempotent re-register (e.g. a transient network blip on
                # the node side, same head incarnation): refresh liveness
                known.address = p["address"]
                known.last_seen = time.monotonic()
                known.missed = 0
            # re-acquire bundle reservations for recovering PGs mapped here
            for pg_id in list(self._recovering_pgs):
                pg = self._pgs.get(pg_id)
                if pg is None or pg.get("nodes") is None:
                    self._recovering_pgs.discard(pg_id)
                    continue
                for idx, nid in enumerate(pg["nodes"]):
                    if nid == node_id and idx not in pg["_acq"]:
                        if self.cluster.acquire(node_id, pg["bundles"][idx]):
                            pg["_acq"].add(idx)
                if len(pg["_acq"]) == len(pg["bundles"]):
                    pg.pop("_acq", None)
                    self._recovering_pgs.discard(pg_id)
            # claim reported actor workers for restored actor entries
            for aw in p.get("actor_workers", ()):
                aid = aw.get("actor_id")
                entry2 = self._actors.get(aid) if aid is not None else None
                if entry2 is not None and \
                        entry2.worker_id == aw["worker_id"] and \
                        entry2.state != DEAD:
                    # idempotent re-claim: a repeated re-registration (first
                    # reply lost on the node side) must not disown workers
                    # the previous attempt already reconciled
                    entry2.address = aw["address"]
                    entry2.node_id = node_id
                    continue
                if entry2 is None or entry2.state == DEAD or \
                        aid not in self._recovering_actors:
                    kill.append(aw["worker_id"])
                    continue
                entry2.state = ALIVE
                entry2.node_id = node_id
                entry2.worker_id = aw["worker_id"]
                entry2.address = aw["address"]
                self._actor_by_worker[aw["worker_id"]] = aid
                if entry2.pg is None:
                    self.cluster.acquire(node_id, entry2.resources)
                self._recovering_actors.discard(aid)
                self._persist_dirty = True
        if new_node:
            self.pubsub.publish("cluster_events", {
                "event": "node_added", "node_id": node_id,
                "address": p["address"], "ts": time.time()})
            self.journal.record("node_register", node_id=node_id,
                                address=p["address"],
                                resources=dict(p["resources"]))
        return {"session": self.session, "incarnation": self.incarnation,
                "kill": kill}

    def _h_unregister_node(self, p, ctx):
        self._mark_node_dead(p["node_id"], "unregistered")
        return True

    def _h_list_nodes(self, p, ctx):
        with self._lock:
            return [{"node_id": n.node_id, "address": n.address,
                     "shm_name": n.shm_name, "resources": n.resources,
                     "alive": n.alive}
                    for n in self._nodes.values()]

    def _h_connect_driver(self, p, ctx):
        with self._lock:
            self._next_job += 1
            job = self._next_job
            self._persist_dirty = True
        return {"job_id": job, "session": self.session,
                "nodes": self._h_list_nodes(None, None)}

    # --------------------------------------------------------------------- kv

    def _h_kv_put(self, p, ctx):
        # pickle-path clients (and the pure-Python transport); native
        # clients hit the C fast path and never reach here. Both write
        # the same table (_KvStore); persistence dirtiness is tracked by
        # the kv version counter in _persist_loop, so no-op puts (the
        # overwrite=False dedup path every worker hits re-exporting the
        # same function blobs) don't force snapshot rewrites.
        return self._kv.put(p["key"], p["value"], p.get("overwrite", True))

    def _h_kv_get(self, p, ctx):
        return self._kv.get(p["key"])

    def _h_kv_del(self, p, ctx):
        # dirtiness via the version counter, as in _h_kv_put
        return self._kv.delete(p["key"])

    def _h_kv_keys(self, p, ctx):
        return self._kv.keys(p.get("prefix", ""))

    # ----------------------------------------------------------------- leases

    def _schedule_and_acquire(self, resources: Dict[str, float],
                              policy: str = "hybrid",
                              affinity_node: str = "",
                              soft: bool = False,
                              _drain_on_busy: bool = True) -> Optional[str]:
        for attempt in (0, 1):
            with self._lock:
                node_id = self.cluster.schedule(
                    resources, _POLICY_BY_NAME.get(policy, POLICY_HYBRID),
                    affinity_node=affinity_node, soft=soft)
                if node_id is not None:
                    if not self.cluster.acquire(node_id, resources):
                        node_id = None
                if node_id is not None:
                    return node_id
            # busy: pooled fast-lease grants may be holding the capacity —
            # drain them (opportunistic pool, never allowed to starve real
            # demand past one round-trip) and retry once
            if attempt == 0 and _drain_on_busy and self._fast_lease_on:
                if self._drain_all_pools() == 0:
                    return None
            else:
                return None
        return None

    def _release(self, node_id: str, resources: Dict[str, float]) -> None:
        with self._lock:
            if node_id in self._nodes and self._nodes[node_id].alive:
                self.cluster.release(node_id, resources)

    def _record_sched_event(self, name: str, start: float) -> None:
        """Head-side scheduler-phase span, appended straight to the
        timeline deque (the head process has no telemetry flush loop —
        it IS the collector). Lets `python -m ray_tpu trace` / timeline
        consumers see where lease grants came from and what they cost."""
        self._task_events.append({
            "name": name, "task_id": "", "kind": "sched",
            "start": start, "end": time.time(), "ok": True,
            "worker": "head", "node": "head"})

    def _h_request_lease(self, p, ctx):
        """Grant (node, worker) for a resource shape; None if infeasible now.

        Reply: {lease_id, node_id, worker_id, worker_addr, shm_name} or
        {retry: True} when resources are busy, or {infeasible: True} when no
        node could ever satisfy the shape.

        With pg_id set, the lease comes from the bundle's reserved node and
        no extra resources are acquired — the PG already holds them
        (reference: PlacementGroupSchedulingStrategy +
        placement_group_resource_manager.h bundle accounting).
        """
        t_req = time.time()
        resources = p["resources"]
        pg_id = p.get("pg_id")
        if self._fastlease_eligible(p, pg_id):
            # Arm the native pool for this shape: the NEXT acquire for it
            # is served inside the C loop (this one proceeds via Python).
            # Depth is DEMAND-BOUNDED by the submitter's pending hint: an
            # isolated task (pending=1) stocks nothing, a burst stocks up
            # to the target — unconditional deep stocking caused a
            # worker-spawn storm that starved small hosts.
            want = min(config_mod.GlobalConfig.fast_lease_pool_target,
                       max(0, int(p.get("pending", 1)) - 1))
            sig = wire.lease_sig(resources)
            if want > 0:
                with self._lock:
                    cur = self._restock_wants.get(sig)
                    self._restock_wants[sig] = {
                        "resources": dict(resources),
                        "want": max(want, cur["want"] if cur else 0)}
                self._restock_kick.set()
            # Pool-first: a Python-path request for a pooled shape serves
            # straight from the pool. Without this, concurrent requester
            # threads race their own pool — the Python path sees the
            # capacity as busy, drain-on-busy rips grants out from under
            # sibling fast acquires, and restock churns (measured 28%
            # single-client regression).
            item = self.server.lease_unstock(sig)
            if item is not None:
                _lkey, blob = item
                try:
                    g = pickle.loads(blob)
                except Exception:  # noqa: BLE001
                    g = None
                live = False
                if g is not None:
                    with self._lock:
                        e = self._leases.get(g["lease_id"])
                        if e is not None:
                            # now an ordinary Python lease: bound to this
                            # peer for disconnect reclaim, out of the
                            # C-side tables
                            e.peer = ctx.peer if ctx is not None else None
                            e.fast_key = None
                            live = True
                if live:
                    self._py_unstocks += 1
                    self._record_sched_event("lease::pool", t_req)
                    return {k: g[k] for k in
                            ("lease_id", "node_id", "worker_id",
                             "worker_addr", "node_addr", "shm_name")}
                # Stale pooled grant: its _LeaseEntry was already released
                # (resources returned by _h_release_lease) — handing it
                # out would point the client at a worker the node may
                # have reclaimed, and release of the reissued lease_id
                # would be a no-op double-spend. Discard and fall through
                # to ordinary scheduling.
        if pg_id is not None:
            return self._pg_lease(p, pg_id, ctx)
        node_id = self._schedule_and_acquire(
            resources, policy=p.get("policy", "hybrid"),
            affinity_node=p.get("affinity_node", ""),
            soft=p.get("soft", False))
        if node_id is not None:
            with self._lock:  # shape satisfied: retire its demand entry
                self._demand.pop(
                    (str(ctx.peer), tuple(sorted(resources.items()))),
                    None)
        if node_id is None:
            # distinguish busy from impossible: try against total capacity
            with self._lock:
                feasible = any(
                    all(n.resources.get(k, 0.0) >= v
                        for k, v in resources.items())
                    for n in self._nodes.values() if n.alive)
                key = (str(ctx.peer), tuple(sorted(resources.items())))
                self._demand[key] = {
                    "ts": time.time(), "resources": dict(resources),
                    "count": max(1, int(p.get("pending", 1)))}
            return {"infeasible": not feasible, "retry": feasible}
        node = self._nodes[node_id]
        try:
            grant = self._node_clients.get(node.address).call(
                "lease_worker", {"resources": resources,
                                 "runtime_env": p.get("runtime_env")})
        except RpcError as e:
            self._release(node_id, resources)
            self._mark_node_dead(node_id, f"lease rpc failed: {e}")
            return {"retry": True}
        except Exception as e:  # node-side bug: don't leak the acquisition
            self._release(node_id, resources)
            return {"infeasible": True, "reason": f"lease failed: {e}"}
        if grant is None:
            self._release(node_id, resources)
            return {"retry": True}
        if isinstance(grant, dict) and "invalid" in grant:
            self._release(node_id, resources)
            return {"infeasible": True, "reason": grant["invalid"]}
        with self._lock:
            self._lease_counter += 1
            lease_id = f"l{self.incarnation}.{self._lease_counter}"
            self._leases[lease_id] = _LeaseEntry(
                lease_id, node_id, grant["worker_id"], grant["worker_addr"],
                resources, ctx.peer if ctx is not None else None)
        self._record_sched_event("lease::grant", t_req)
        return {"lease_id": lease_id, "node_id": node_id,
                "worker_id": grant["worker_id"],
                "worker_addr": grant["worker_addr"],
                "node_addr": node.address,
                "shm_name": node.shm_name}

    def _pg_lease(self, p, pg_id: bytes, ctx=None):
        resources = p["resources"]
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return {"infeasible": True, "retry": False,
                        "reason": "placement group removed"}
            if pg["state"] != "CREATED":
                return {"retry": True}
            idx = p.get("bundle_index", -1)
            if idx >= len(pg["bundles"]):
                return {"infeasible": True,
                        "reason": f"bundle index {idx} out of range "
                                  f"({len(pg['bundles'])} bundles)"}
            # per-bundle usage accounting: a lease draws down its bundle's
            # reservation so concurrent tasks can't overrun into another
            # PG's chips (reference: placement_group_resource_manager.h)
            if idx < 0:
                idx = next((i for i in range(len(pg["bundles"]))
                            if _bundle_fits(pg, i, resources)), -1)
                if idx < 0:
                    return {"retry": True}
            elif not _bundle_fits(pg, idx, resources):
                return {"retry": True}
            _bundle_draw(pg, idx, resources)
            node_id = pg["nodes"][idx]
            node = self._nodes.get(node_id)
        if node is None or not node.alive:
            self._bundle_return(pg_id, idx, resources)
            return {"retry": True}
        try:
            grant = self._node_clients.get(node.address).call(
                "lease_worker", {"resources": resources,
                                 "runtime_env": p.get("runtime_env")})
        except RpcError as e:
            self._bundle_return(pg_id, idx, resources)
            self._mark_node_dead(node_id, f"lease rpc failed: {e}")
            return {"retry": True}
        except Exception as e:
            self._bundle_return(pg_id, idx, resources)
            return {"infeasible": True, "reason": f"lease failed: {e}"}
        if grant is None:
            self._bundle_return(pg_id, idx, resources)
            return {"retry": True}
        if isinstance(grant, dict) and "invalid" in grant:
            self._bundle_return(pg_id, idx, resources)
            return {"infeasible": True, "reason": grant["invalid"]}
        with self._lock:
            self._lease_counter += 1
            lease_id = f"l{self.incarnation}.{self._lease_counter}"
            # resources recorded for bundle return, not cluster release
            self._leases[lease_id] = _LeaseEntry(
                lease_id, node_id, grant["worker_id"], grant["worker_addr"],
                resources, ctx.peer if ctx is not None else None,
                pg_id=pg_id, bundle_index=idx)
        return {"lease_id": lease_id, "node_id": node_id,
                "worker_id": grant["worker_id"],
                "worker_addr": grant["worker_addr"],
                "node_addr": node.address,
                "shm_name": node.shm_name}

    def _bundle_return(self, pg_id: bytes, idx: int,
                       resources: Dict[str, float]) -> None:
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is not None and pg.get("used"):
                used = pg["used"][idx]
                for k, v in resources.items():
                    used[k] = max(0.0, used.get(k, 0.0) - v)

    def _on_client_disconnect(self, peer) -> None:
        with self._lock:
            stale = [l.lease_id for l in self._leases.values()
                     if l.peer == peer]
        if not stale:
            return

        # off-thread: this callback runs on the transport dispatcher and
        # _h_release_lease makes a blocking return_worker call per lease
        def _reclaim():
            for lease_id in stale:
                self._h_release_lease({"lease_id": lease_id}, None)

        threading.Thread(target=_reclaim, daemon=True,
                         name="lease-reclaim").start()

    def _h_release_lease(self, p, ctx):
        with self._lock:
            lease = self._leases.pop(p["lease_id"], None)
        if lease is None:
            return False
        if lease.fast_key is not None and self._fast_lease_on:
            # a Python-path release of a pooled/held fast grant (corpse
            # detected by the client, head restart fallback): make sure the
            # C loop can't re-grant it
            self.server.lease_invalidate(lease.fast_key)
        if lease.pg_id is not None:
            self._bundle_return(lease.pg_id, lease.bundle_index,
                                lease.resources)
        else:
            self._release(lease.node_id, lease.resources)
        node = self._nodes.get(lease.node_id)
        if node is not None and node.alive:
            try:
                self._node_clients.get(node.address).call(
                    "return_worker", {"worker_id": lease.worker_id})
            except RpcError:
                pass
        return True

    # ------------------------------------------------- native lease pool

    def _fastlease_eligible(self, p, pg_id) -> bool:
        return (self._fast_lease_on and pg_id is None
                and not p.get("runtime_env") and not p.get("affinity_node")
                and p.get("policy", "hybrid") == "hybrid"
                and not p.get("soft"))

    def _restock_loop(self) -> None:
        """Placement policy half of the native lease pool: keep each hot
        shape's pool stocked to target depth so FOP_LEASE_ACQ hits in C.
        Stocking is strictly opportunistic — any request that finds the
        cluster busy drains every pool first (_drain_all_pools), so pooled
        grants can only ever cost one retry round-trip of latency."""
        while not self._stopped.is_set():
            self._restock_kick.wait(timeout=1.0)
            self._restock_kick.clear()
            with self._lock:
                wants = dict(self._restock_wants)
            for sig, entry in wants.items():
                while (not self._stopped.is_set()
                       and self.server.lease_depth(sig) < entry["want"]):
                    with self._lock:
                        # a drain may have disarmed this sig since the
                        # snapshot — stocking past it would orphan grants
                        if sig not in self._restock_wants:
                            break
                    if not self._stock_one(sig, entry["resources"]):
                        break

    def _stock_one(self, sig: int, resources: Dict[str, float]) -> bool:
        node_id = self._schedule_and_acquire(resources, _drain_on_busy=False)
        if node_id is None:
            return False
        with self._lock:
            node = self._nodes.get(node_id)
        if node is None:
            self._release(node_id, resources)
            return False
        try:
            grant = self._node_clients.get(node.address).call(
                "lease_worker", {"resources": resources,
                                 "runtime_env": None})
        except RpcError:
            self._release(node_id, resources)
            self.journal.record("lease_grant_failed", node_id=node_id,
                                resources=dict(resources),
                                reason="lease rpc failed (pool stock)")
            self._mark_node_dead(node_id, "lease rpc failed (pool stock)")
            return False
        except Exception as e:  # noqa: BLE001
            self._release(node_id, resources)
            self.journal.record("lease_grant_failed", node_id=node_id,
                                resources=dict(resources), reason=repr(e))
            return False
        if not isinstance(grant, dict) or "worker_id" not in grant:
            self._release(node_id, resources)
            return False
        with self._lock:
            self._lease_counter += 1
            n = self._lease_counter
            lease_id = f"l{self.incarnation}.{n}"
            self._leases[lease_id] = _LeaseEntry(
                lease_id, node_id, grant["worker_id"], grant["worker_addr"],
                dict(resources), None, fast_key=n)
        blob = pickle.dumps({
            "lease_id": lease_id, "node_id": node_id,
            "worker_id": grant["worker_id"],
            "worker_addr": grant["worker_addr"],
            "node_addr": node.address, "shm_name": node.shm_name,
            "fast_key": n}, protocol=5)
        if not self.server.lease_stock(sig, n, blob):
            self._h_release_lease({"lease_id": lease_id}, None)
            return False
        with self._lock:
            self._stocked_sigs.add(sig)
        return True

    def _drain_all_pools(self) -> int:
        """Return every POOLED (un-held) fast grant to the cluster and stop
        restocking until fresh eligible demand re-arms it."""
        with self._lock:
            # drain every sig that EVER stocked, not just currently-armed
            # ones: a restock racing a previous drain can deposit grants
            # after the wants were cleared, and wants-only draining would
            # orphan them (they held a node's capacity forever)
            sigs = set(self._restock_wants) | set(self._stocked_sigs)
            self._restock_wants.clear()  # re-armed by fresh eligible demand
        n = 0
        for sig in sigs:
            while True:
                item = self.server.lease_unstock(sig)
                if item is None:
                    break
                _lkey, blob = item
                try:
                    g = pickle.loads(blob)
                except Exception:  # noqa: BLE001
                    continue
                self._h_release_lease({"lease_id": g["lease_id"]}, None)
                n += 1
        return n

    def _on_conn_fastlease_reclaim(self, conn_id: int, peer) -> None:
        """A connection died holding native-granted leases: release them
        (role of the peer-based reclaim in _on_client_disconnect, driven by
        the C-side holder table instead of Python lease entries)."""
        items = self.server.lease_reclaim_conn(conn_id)
        if not items:
            return

        def _reclaim():
            for _lkey, _sig, blob in items:
                try:
                    g = pickle.loads(blob)
                except Exception:  # noqa: BLE001
                    continue
                self._h_release_lease({"lease_id": g["lease_id"]}, None)

        threading.Thread(target=_reclaim, daemon=True,
                         name="fastlease-reclaim").start()

    # ----------------------------------------------------------------- actors

    def _h_create_actor(self, p, ctx):
        """Register + schedule an actor. Reply immediately; creation is async.

        (Reference: GcsActorManager::RegisterActor/CreateActor,
        gcs_actor_manager.cc:389,475 — the client gets an immediate ack and
        discovers liveness through get_actor polling.)
        """
        actor_id: bytes = p["actor_id"]
        entry = _ActorEntry(
            actor_id, p["spec_bytes"], p["max_restarts"],
            p["max_task_retries"], p.get("name_key", ""),
            p["resources"], p.get("owner_addr", ""), p.get("class_name", ""))
        entry.runtime_env = p.get("runtime_env")
        if p.get("pg_id") is not None:
            # bundle reservations cover the cluster accounting; the node
            # lease still carries the physical shape (lease_resources) so
            # TPU actors get chip allocation + TPU_VISIBLE_CHIPS
            entry.pg = (p["pg_id"], p.get("bundle_index", -1))
            entry.resources = {}
        with self._lock:
            if entry.name_key:
                if entry.name_key in self._named:
                    raise ValueError(
                        f"named actor {entry.name_key!r} already exists")
                self._named[entry.name_key] = actor_id
            self._actors[actor_id] = entry
            self._persist_dirty = True
        self._persist_kick.set()
        self._spawn_actor(entry)
        return True

    def _spawn_actor(self, entry: _ActorEntry) -> None:
        """Try to place the actor; retries in a background thread if busy."""

        def _try_place():
            deadline = time.monotonic() + config_mod.GlobalConfig.rpc_call_timeout_s
            while not self._stopped.is_set():
                with self._lock:
                    if entry.state == DEAD:
                        return  # killed while pending placement
                if entry.pg is not None:
                    node_id = self._pg_actor_node(entry)
                    if node_id is None:
                        time.sleep(0.02)
                        continue
                else:
                    node_id = self._schedule_and_acquire(entry.resources)
                if node_id is not None:
                    node = self._nodes[node_id]
                    try:
                        grant = self._node_clients.get(node.address).call(
                            "lease_worker",
                            {"resources": entry.resources,
                             "runtime_env": entry.runtime_env})
                    except RpcError:
                        self._release(node_id, entry.resources)
                        self._mark_node_dead(node_id, "actor lease rpc failed")
                        continue
                    if grant is None:
                        self._release(node_id, entry.resources)
                        time.sleep(0.05)
                        continue
                    if isinstance(grant, dict) and "invalid" in grant:
                        # unsatisfiable lease (bad TPU shape, runtime_env
                        # materialization failure): surface as creation
                        # failure, don't spin forever
                        self._release(node_id, entry.resources)
                        with self._lock:
                            if entry.state != DEAD:
                                entry.state = DEAD
                                entry.reason = grant["invalid"]
                                self._persist_dirty = True
                        self._persist_kick.set()
                        return
                    with self._lock:
                        if entry.state == DEAD:  # killed during the lease
                            self._release(node_id, entry.resources)
                            grant_dead = True
                        else:
                            grant_dead = False
                            entry.node_id = node_id
                            entry.worker_id = grant["worker_id"]
                            self._actor_by_worker[grant["worker_id"]] = \
                                entry.actor_id
                    if grant_dead:
                        try:
                            self._node_clients.get(node.address).call(
                                "return_worker",
                                {"worker_id": grant["worker_id"]})
                        except RpcError:
                            pass
                        return
                    try:
                        self._node_clients.get(node.address).call(
                            "start_actor", {
                                "worker_id": grant["worker_id"],
                                "actor_id": entry.actor_id,
                                "spec_bytes": entry.spec_bytes,
                                "head_addr": self.address,
                                "num_restarts": entry.num_restarts,
                            })
                    except RpcError as e:
                        self._on_actor_worker_lost(entry.actor_id,
                                                   f"start_actor failed: {e}")
                    return
                # infeasible forever?
                with self._lock:
                    feasible = any(
                        all(n.resources.get(k, 0.0) >= v
                            for k, v in entry.resources.items())
                        for n in self._nodes.values() if n.alive)
                if not feasible and time.monotonic() > deadline:
                    with self._lock:
                        entry.state = DEAD
                        entry.reason = (
                            f"infeasible resources {entry.resources}")
                    return
                time.sleep(0.02)

        threading.Thread(target=_try_place, daemon=True,
                         name="head-actor-place").start()

    def _pg_actor_node(self, entry: _ActorEntry) -> Optional[str]:
        """Bundle's node for a PG-scheduled actor; None while the PG is
        pending. Marks the actor DEAD if its PG was removed."""
        pg_id, idx = entry.pg
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                entry.state = DEAD
                entry.reason = "placement group removed"
                return None
            if pg["state"] != "CREATED":
                return None
            node_id = pg["nodes"][idx if idx >= 0 else 0]
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return None
            return node_id

    def _h_actor_ready(self, p, ctx):
        with self._lock:
            entry = self._actors.get(p["actor_id"])
            if entry is None:
                return False
            # Restart fencing: a stale incarnation (e.g. a slow __init__
            # finishing after the head already declared the worker lost and
            # restarted elsewhere) must not flip state back to ALIVE.
            if p.get("num_restarts", 0) != entry.num_restarts or \
                    entry.state == DEAD:
                return False
            entry.state = ALIVE
            entry.address = p["address"]
            self._persist_dirty = True
        self._persist_kick.set()
        return True

    def _h_actor_failed(self, p, ctx):
        """Actor constructor raised — not a crash; no restart (reference
        semantics: creation errors surface to the caller)."""
        with self._lock:
            entry = self._actors.get(p["actor_id"])
            if entry is None:
                return False
            if p.get("num_restarts", 0) != entry.num_restarts or \
                    entry.state == DEAD:
                return False
            entry.state = DEAD
            entry.reason = p.get("reason", "creation failed")
            self._persist_dirty = True
            self._persist_kick.set()
            node = self._nodes.get(entry.node_id) if entry.node_id else None
            worker_id = entry.worker_id
            self._cleanup_actor_placement(entry)
        # the worker process held partial constructor state — reclaim the
        # pool slot by killing it (its death event no-ops: actor is DEAD)
        if node is not None and worker_id is not None and node.alive:
            try:
                self._node_clients.get(node.address).call(
                    "kill_worker", {"worker_id": worker_id})
            except RpcError:
                pass
        return True

    def _cleanup_actor_placement(self, entry: _ActorEntry) -> None:
        """Release resources + pool bookkeeping after an actor leaves a node.

        Caller must hold self._lock.
        """
        if entry.worker_id is not None:
            self._actor_by_worker.pop(entry.worker_id, None)
        if entry.node_id is not None and entry.node_id in self._nodes:
            if self._nodes[entry.node_id].alive:
                self.cluster.release(entry.node_id, entry.resources)
        entry.node_id = None
        entry.worker_id = None
        entry.address = None

    def _h_get_actor(self, p, ctx):
        with self._lock:
            entry = self._actors.get(p["actor_id"])
            if entry is None:
                return None
            return {"state": entry.state, "address": entry.address,
                    "reason": entry.reason,
                    "max_task_retries": entry.max_task_retries,
                    "num_restarts": entry.num_restarts}

    def _h_get_actor_by_name(self, p, ctx):
        key = f"{p['namespace']}:{p['name']}"
        with self._lock:
            actor_id = self._named.get(key)
            if actor_id is None:
                return None
            entry = self._actors[actor_id]
            return {"actor_id": actor_id, "class_name": entry.class_name,
                    "state": entry.state,
                    "max_task_retries": entry.max_task_retries}

    def _h_kill_actor(self, p, ctx):
        actor_id = p["actor_id"]
        with self._lock:
            entry = self._actors.get(actor_id)
            if entry is None:
                return False
            if p.get("no_restart", True):
                entry.restarts_left = 0
                self._persist_dirty = True
            node = self._nodes.get(entry.node_id) if entry.node_id else None
            worker_id = entry.worker_id
            if worker_id is None and entry.state in (PENDING, RESTARTING) \
                    and p.get("no_restart", True):
                # not placed yet: mark dead now so the in-flight placement
                # loop aborts instead of starting a killed actor
                entry.state = DEAD
                entry.reason = "killed before start"
                self._recovering_actors.discard(actor_id)
        if node is not None and worker_id is not None:
            try:
                self._node_clients.get(node.address).call(
                    "kill_worker", {"worker_id": worker_id})
            except RpcError:
                pass
        return True

    # --------------------------------------------------- death + restart path

    def _h_worker_died(self, p, ctx):
        """Node daemon reports a worker process exit (reference: raylet
        worker death -> GcsActorManager::OnWorkerDead).

        Journals the death with its exit cause under a trace id (ambient,
        or freshly minted) that any follow-on actor-restart event shares,
        so `events` shows the causal chain and `trace` can cross-link it.
        """
        from ray_tpu.util.trace_context import current, new_trace_id
        ctx_t = current()
        trace_id = ctx_t[0] if ctx_t else new_trace_id()
        reason = p.get("reason", "worker died")
        wid = p.get("worker_id") or b""
        # crash forensics: the node daemon tails the dead worker's stderr
        # file + structured log file and sends the dying words along —
        # bounded here again so a hostile report can't bloat the journal
        tails = {}
        for k in ("stderr_tail", "log_tail"):
            v = p.get(k)
            if v:
                tails[k] = [str(ln)[:500] for ln in list(v)[-50:]]
        self.journal.record(
            "worker_death", trace_id=trace_id,
            worker_id=wid.hex() if isinstance(wid, bytes) else str(wid),
            node_id=p.get("node_id", ""), exit_cause=reason, **tails)
        self._on_actor_worker_lost(
            None, reason, worker_id=p["worker_id"], trace_id=trace_id)
        return True

    def _on_actor_worker_lost(self, actor_id: Optional[bytes], reason: str,
                              worker_id: Optional[bytes] = None,
                              trace_id: str = "") -> None:
        with self._lock:
            if actor_id is None and worker_id is not None:
                actor_id = self._actor_by_worker.get(worker_id)
            if actor_id is None:
                return  # plain task worker; owners detect via connection loss
            entry = self._actors.get(actor_id)
            if entry is None or entry.state == DEAD:
                return
            self._cleanup_actor_placement(entry)
            if entry.restarts_left != 0:
                if entry.restarts_left > 0:
                    entry.restarts_left -= 1
                entry.state = RESTARTING
                entry.num_restarts += 1
                restart = True
            else:
                entry.state = DEAD
                entry.reason = reason
                restart = False
            self._persist_dirty = True
        self._persist_kick.set()
        self.pubsub.publish("cluster_events", {
            "event": "actor_restarting" if restart else "actor_dead",
            "actor_id": actor_id.hex(), "reason": reason,
            "ts": time.time()})
        self.journal.record(
            "actor_restarting" if restart else "actor_dead",
            trace_id=trace_id, actor_id=actor_id.hex(), reason=reason,
            restarts_left=entry.restarts_left)
        if restart:
            self._spawn_actor(entry)

    def _mark_node_dead(self, node_id: str, reason: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            self.cluster.remove_node(node_id)
            dead_actor_ids = [aid for aid, e in self._actors.items()
                              if e.node_id == node_id and
                              e.state in (ALIVE, PENDING, RESTARTING)]
        self._node_clients.invalidate(node.address)
        if self._fast_lease_on:
            # fast grants on the dead node are garbage: pull them out of
            # the C pool/held tables so they can't be (re-)granted. The
            # node's resource accounting is already gone (remove_node), so
            # just drop the entries.
            with self._lock:
                dead_fast = [l for l in self._leases.values()
                             if l.node_id == node_id
                             and l.fast_key is not None]
            for l in dead_fast:
                self.server.lease_invalidate(l.fast_key)
                with self._lock:
                    self._leases.pop(l.lease_id, None)
        self.pubsub.publish("cluster_events", {
            "event": "node_dead", "node_id": node_id, "reason": reason,
            "ts": time.time()})
        self.journal.record("node_dead", node_id=node_id, reason=reason)
        for aid in dead_actor_ids:
            self._on_actor_worker_lost(aid, f"node {node_id} died: {reason}")

    def _health_loop(self) -> None:
        cfg = config_mod.GlobalConfig
        period = cfg.health_check_period_ms / 1000.0
        max_missed = max(1, int(cfg.health_check_timeout_ms /
                                cfg.health_check_period_ms))
        while not self._stopped.wait(period):
            with self._lock:
                nodes = [n for n in self._nodes.values() if n.alive]
            for n in nodes:
                try:
                    self._node_clients.get(n.address).call(
                        "ping", timeout=period * 2)
                    n.missed = 0
                    n.last_seen = time.monotonic()
                except RpcError:
                    n.missed += 1
                    if n.missed >= max_missed:
                        self._mark_node_dead(n.node_id, "health check failed")
            # periodic retry of pending placement groups: resources freed
            # by finished leases/actors may now fit a queued reservation
            self._try_schedule_pgs()
            # idle decay of the native lease pool: no acquires for a full
            # drain window -> hand the pooled capacity back
            if self._fast_lease_on:
                stats = self.server.lease_stats()
                if stats is not None:
                    # activity = C-loop pool hits PLUS Python-path pool
                    # consumption (pool-first in _h_request_lease): either
                    # one proves the pool is earning its keep. Counting
                    # only `hits` drained pools under pure Python-path
                    # load — a false idle.
                    activity = stats["hits"] + self._py_unstocks
                    if activity != self._fast_hits_seen:
                        self._fast_hits_seen = activity
                        self._fast_idle_since = time.monotonic()
                    elif (stats["pooled"] > 0
                          and time.monotonic()
                          - getattr(self, "_fast_idle_since", 0.0)
                          > config_mod.GlobalConfig.fast_lease_idle_drain_s):
                        self._drain_all_pools()

    # ------------------------------------------------------- placement groups

    def _h_create_pg(self, p, ctx):
        """Register a PG; reservation is atomic and retried until feasible
        (reference: GcsPlacementGroupManager pending queue,
        gcs_placement_group_manager.h:228). Clients poll get_placement_group
        for CREATED."""
        with self._lock:
            self._pgs[p["pg_id"]] = {
                "bundles": p["bundles"], "nodes": None, "state": "PENDING",
                "strategy": p["strategy"], "name": p.get("name", "")}
            self._persist_dirty = True
        self._try_schedule_pgs()
        return True

    def _try_schedule_pgs(self) -> None:
        """Attempt atomic reservation of every pending PG (called on create
        and periodically from the health loop so freed resources are
        picked up)."""
        for attempt in (0, 1):
            pending = False
            with self._lock:
                for pg in self._pgs.values():
                    if pg["state"] != "PENDING":
                        continue
                    nodes = self.cluster.schedule_bundles(pg["bundles"],
                                                          pg["strategy"])
                    if nodes is not None:
                        pg["nodes"] = nodes
                        pg["state"] = "CREATED"
                        self._persist_dirty = True
                        self._persist_kick.set()
                    else:
                        pending = True
            # a reservation that can't fit may be blocked by pooled
            # fast-lease grants: drain them and retry once (the pool is
            # opportunistic — real demand always wins)
            if not (attempt == 0 and pending and self._fast_lease_on
                    and self._drain_all_pools() > 0):
                return

    def _h_remove_pg(self, p, ctx):
        with self._lock:
            pg = self._pgs.pop(p["pg_id"], None)
            if pg is None:
                return False
            self._persist_dirty = True
            recovering = p["pg_id"] in self._recovering_pgs
            self._recovering_pgs.discard(p["pg_id"])
            if pg["state"] == "CREATED":
                acq = pg.pop("_acq", None)
                for idx, (node_id, bundle) in enumerate(
                        zip(pg["nodes"], pg["bundles"])):
                    if recovering and (acq is None or idx not in acq):
                        # post-restart: this bundle was never re-acquired —
                        # releasing it would overcommit the node
                        continue
                    if node_id in self._nodes and self._nodes[node_id].alive:
                        self.cluster.release(node_id, bundle)
        self._try_schedule_pgs()
        return True

    def _h_get_pg(self, p, ctx):
        with self._lock:
            pg = self._pgs.get(p["pg_id"])
            if pg is None:
                return None
            return dict(pg)

    # ------------------------------------------------------------------ state

    def _h_cluster_resources(self, p, ctx):
        with self._lock:
            total: Dict[str, float] = {}
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    def _pooled_fast_keys(self) -> set:
        """fast_keys of grants sitting UN-HELD in the C pool — their
        resources are reclaimable in one drain, so capacity reports treat
        them as free (without this, pooled grants masked freed capacity
        from the elastic-train grow monitor and the autoscaler)."""
        if not self._fast_lease_on:
            return set()
        try:
            return set(self.server.lease_pooled_keys())
        except Exception:  # noqa: BLE001
            return set()

    def _h_available_resources(self, p, ctx):
        total = self._h_cluster_resources(p, ctx)
        pooled = self._pooled_fast_keys()
        with self._lock:
            for lease in self._leases.values():
                if lease.fast_key is not None and lease.fast_key in pooled:
                    continue  # grantable pool cache counts as available
                for k, v in lease.resources.items():
                    total[k] = total.get(k, 0.0) - v
            for e in self._actors.values():
                if e.state in (ALIVE, PENDING, RESTARTING) and e.node_id:
                    for k, v in e.resources.items():
                        total[k] = total.get(k, 0.0) - v
        return total

    #: snapshots older than this are from dead/departed workers: drop
    #: them from aggregation and prune the map (bounds growth under
    #: worker churn; ~12 missed export periods at the default 5s)
    METRICS_STALE_S = 60.0

    def _h_telemetry_push(self, p, ctx):
        with self._lock:
            if p.get("metrics"):
                self._metrics[p["worker"]] = {
                    "ts": time.time(), "snap": p["metrics"]}
            if p.get("objects") is not None:
                # per-owner object summary for `list objects` (reference:
                # the state API's object listing aggregates owner-side
                # ref tables — ownership model: owners are authoritative)
                self._objects[p["worker"]] = {
                    "ts": time.time(), "node": p.get("node", ""),
                    "role": p.get("role", ""), "snap": p["objects"]}
            for e in p.get("events", ()):
                e["worker"] = p["worker"][:12]
                e["node"] = p.get("node", "")
                self._task_events.append(e)
            for r in p.get("llm_requests", ()):
                rid = r.get("rid")
                if not rid:
                    continue
                # live snapshots re-ship every flush and overwrite; a
                # landed FINISHED record is final — never let a stale
                # in-flight snapshot (reordered flush) roll it back
                cur = self._llm_requests.get(rid)
                if cur is not None and cur.get("done") \
                        and not r.get("done"):
                    continue
                r["worker"] = p["worker"][:12]
                r["node"] = p.get("node", "")
                self._llm_requests[rid] = r
                self._llm_requests.move_to_end(rid)
                while len(self._llm_requests) > self._llm_requests_cap:
                    self._llm_requests.popitem(last=False)
        if p.get("samples"):
            # hardware gauges -> ring buffers (own lock; outside _lock so
            # a big batch never stalls lease/actor RPCs)
            self._timeseries.ingest(p.get("node") or p["worker"],
                                    p["samples"])
        if p.get("profiles"):
            # collapsed-stack windows -> per-process profile rings (own
            # lock, outside _lock for the same reason)
            self._profiles.ingest(
                p["worker"], p["profiles"], role=p.get("role", ""),
                node=(p.get("node") or "")[:12], worker=p["worker"][:12])
        if p.get("logs"):
            # structured log windows -> per-process severity rings (own
            # lock, outside _lock; seq assigned at arrival is the
            # logs_dump follow cursor)
            self._logs.ingest(
                p["worker"], p["logs"], role=p.get("role", ""),
                node=(p.get("node") or "")[:12], worker=p["worker"][:12])
        if p.get("compiles"):
            # XLA compile windows -> per-process rings (own lock,
            # outside _lock; seq assigned at arrival is the
            # compiles_dump follow cursor)
            self._compiles.ingest(
                p["worker"], p["compiles"], role=p.get("role", ""),
                node=(p.get("node") or "")[:12], worker=p["worker"][:12])
        for ev in p.get("journal", ()):
            # worker-originated cluster events (spill overflows): the
            # journal assigns seq/ts at arrival so ordering is the head's
            if isinstance(ev, dict) and ev.get("type"):
                ev = dict(ev)
                etype = ev.pop("type")
                trace_id = ev.pop("trace_id", "")
                ev.setdefault("worker", p["worker"][:12])
                self.journal.record(etype, trace_id=trace_id, **ev)
        return True

    def _h_events_dump(self, p, ctx):
        """Cluster event journal dump (filters: after_seq cursor for
        --follow, exact type, newest-N limit)."""
        p = p or {}
        return self.journal.dump(
            after_seq=int(p.get("after_seq", 0) or 0),
            type=p.get("type", ""),
            limit=int(p.get("limit", 0) or 0))

    def _h_journal_record(self, p, ctx):
        """Out-of-band journal append for trusted controllers (the
        autoscaler records its scaling decisions through this)."""
        p = dict(p or {})
        etype = p.pop("type", "") or "event"
        trace_id = p.pop("trace_id", "")
        return self.journal.record(etype, trace_id=trace_id, **p)["seq"]

    # ------------------------------------------------------------ profiles

    @staticmethod
    def _proc_row(key, role, node, worker, export):
        e = export or {}
        return {"key": key, "role": role, "node": node, "worker": worker,
                "pid": e.get("pid"), "ts": e.get("ts"),
                "samples": int(e.get("samples") or 0),
                "dropped": int(e.get("dropped") or 0),
                "window_s": float(e.get("window_s") or 0.0),
                "stacks": e.get("stacks") or {}}

    def _h_profiles_dump(self, p, ctx):
        """Merged per-process collapsed-stack profiles from the
        ProfileStore (filters: role/node/worker substring, top-N
        stacks per process)."""
        p = p or {}
        try:
            # the head drains its OWN continuous profile at read time —
            # unlike workers/nodes it has no telemetry flush to ride
            export = self._profiler_mod.drain_export()
            if export:
                self._profiles.ingest("head", export, role="head")
        except Exception:  # noqa: BLE001 — profiling never fails a dump
            pass
        return self._profiles.dump(
            role=p.get("role", ""), node=p.get("node", ""),
            worker=p.get("worker", ""), top=int(p.get("top", 0) or 0))

    def _h_logs_dump(self, p, ctx):
        """Merged structured log records from the LogStore (filters:
        role/node/worker substring, severity floor, since-ts, msg regex,
        trace/request-id substring; after_seq cursor for --follow —
        same shape as events_dump)."""
        p = p or {}
        try:
            # the head drains its OWN ring (and staged storm events) at
            # read time — unlike workers/nodes it has no telemetry
            # flush to ride (same contract as _h_profiles_dump)
            export = self._log_plane_mod.drain_export()
            if export:
                self._logs.ingest("head", export, role="head")
            for ev in self._log_plane_mod.drain_journal_events():
                etype = ev.pop("type", "") or "log_error_storm"
                self.journal.record(etype, **ev)
        except Exception:  # noqa: BLE001 — logging never fails a dump
            pass
        return self._logs.dump(
            after_seq=int(p.get("after_seq", 0) or 0),
            role=p.get("role", ""), node=p.get("node", ""),
            worker=p.get("worker", ""), level=p.get("level", ""),
            since=float(p.get("since", 0.0) or 0.0),
            grep=p.get("grep", ""), trace=p.get("trace", ""),
            request=p.get("request", ""),
            limit=int(p.get("limit", 0) or 0))

    def _h_compiles_dump(self, p, ctx):
        """Merged XLA compile records from the CompileStore (filters:
        role/node/worker/callable substring, recompiles-only;
        after_seq cursor for --watch; optional per-callable
        aggregation for --by-callable — same cursor contract as
        logs_dump)."""
        p = p or {}
        try:
            # the head drains its OWN tracker (and staged storm events)
            # at read time — unlike workers/nodes it has no telemetry
            # flush to ride (same contract as _h_logs_dump). Inert in
            # practice: the head never imports jax.
            export = self._compile_mod.drain_export()
            if export:
                self._compiles.ingest("head", export, role="head")
            for ev in self._compile_mod.drain_journal_events():
                etype = ev.pop("type", "") or "compile_storm"
                self.journal.record(etype, **ev)
        except Exception:  # noqa: BLE001 — tracking never fails a dump
            pass
        return self._compiles.dump(
            after_seq=int(p.get("after_seq", 0) or 0),
            role=p.get("role", ""), node=p.get("node", ""),
            worker=p.get("worker", ""),
            callable=p.get("callable", ""),
            recompiles_only=bool(p.get("recompiles_only")),
            limit=int(p.get("limit", 0) or 0),
            by_callable=bool(p.get("by_callable")))

    def _h_profiles_record(self, p, ctx):
        """On-demand burst capture fanned out cluster-wide ('profile
        --record S --hz N'): the head bursts itself while every selected
        node daemon bursts itself and its workers in parallel. Returns
        merged per-process rows in the profiles_dump shape, bypassing
        the store (a burst is a one-shot answer, not history)."""
        p = p or {}
        seconds = max(0.1, min(float(p.get("seconds", 2.0) or 2.0), 30.0))
        hz = float(p.get("hz", 99.0) or 99.0)
        role = p.get("role", "")
        node_f = p.get("node", "")
        worker_f = p.get("worker", "")
        with self._lock:
            nodes = [(n.node_id, n.address)
                     for n in self._nodes.values() if n.alive]
        futs = []
        if role in ("", "node", "worker"):
            payload = {"seconds": seconds, "hz": hz, "worker": worker_f,
                       "include_self": role in ("", "node")
                       and not worker_f,
                       "include_workers": role in ("", "worker")}
            for node_id, addr in nodes:
                if node_f and not node_id.startswith(node_f):
                    continue
                try:
                    futs.append(self._node_clients.get(addr).call_async(
                        "profile_burst", payload))
                except Exception:  # noqa: BLE001 — node dying mid-record
                    pass
        procs = []
        if role in ("", "head") and not node_f and not worker_f:
            from ray_tpu.util.stack_profiler import burst_capture
            procs.append(self._proc_row(
                "head", "head", "", "", burst_capture(seconds, hz)))
        for fut in futs:
            try:
                reply = fut.result(timeout=seconds + 15.0)
            except Exception:  # noqa: BLE001 — skip unreachable nodes
                continue
            for row in (reply or {}).get("procs", ()):
                procs.append(self._proc_row(
                    row.get("key", ""), row.get("role", ""),
                    row.get("node", ""), row.get("worker", ""),
                    row.get("export")))
        return {"procs": procs}

    def _h_objects_dump(self, p, ctx):
        """Aggregated object directory: every reporter's reconciled rows
        (stamped with node + reporter) plus per-node, per-role totals
        summed over ALL entries — exact against ShmStore ground truth
        even when per-reporter rows were truncated."""
        cutoff = time.time() - self.METRICS_STALE_S
        with self._lock:
            for w in [w for w, e in self._objects.items()
                      if e["ts"] < cutoff]:
                del self._objects[w]
            reporters = [(w, e) for w, e in self._objects.items()]
        rows: List[dict] = []
        totals: Dict[str, dict] = {}
        for w, e in reporters:
            for row in (e["snap"].get("dir") or ()):
                rows.append({"node": e["node"], "reporter": w[:12], **row})
            for role, t in (e["snap"].get("dir_totals") or {}).items():
                node_tot = totals.setdefault(e["node"], {})
                cur = node_tot.setdefault(
                    role, {"count": 0, "bytes": 0, "arena_bytes": 0})
                cur["count"] += t.get("count", 0)
                cur["bytes"] += t.get("bytes", 0)
                cur["arena_bytes"] += t.get("arena_bytes", 0)
        return {"rows": rows, "totals": totals}

    def _h_metrics_dump(self, p, ctx):
        from ray_tpu.util.metrics import aggregate
        cutoff = time.time() - self.METRICS_STALE_S
        with self._lock:
            for w in [w for w, e in self._metrics.items()
                      if e["ts"] < cutoff]:
                del self._metrics[w]
            per_worker = {w: dict(e["snap"])
                          for w, e in self._metrics.items()}
        agg = aggregate(per_worker)
        if p and p.get("raw"):
            # tuple keys intact — the Prometheus renderer needs tag
            # structure, and pickle-path callers carry tuples fine
            return agg
        # tuple tag keys -> joined strings for wire/json friendliness
        for m in agg.values():
            m["values"] = {"|".join(k) if isinstance(k, tuple) else str(k): v
                           for k, v in m["values"].items()}
        return agg

    def _h_timeseries_dump(self, p, ctx):
        """Hardware ring-buffer dump (filters: node prefix, exact metric,
        last N points per series; latest=True -> newest point only)."""
        p = p or {}
        if p.get("latest"):
            return self._timeseries.latest(
                max_age_s=p.get("max_age_s", 0.0))
        return self._timeseries.dump(node=p.get("node", ""),
                                     metric=p.get("metric", ""),
                                     last=int(p.get("last", 0) or 0))

    def _h_timeline_dump(self, p, ctx):
        with self._lock:
            return list(self._task_events)

    def _h_requests_dump(self, p, ctx):
        """LLM request records aggregated from engine flight recorders
        (filters: live=True -> in-flight only; request=<rid> -> one
        record; slowest=N -> N worst finished e2e latencies first)."""
        p = p or {}
        with self._lock:
            recs = list(self._llm_requests.values())
        rid = p.get("request")
        if rid:
            return [r for r in recs if r.get("rid") == rid]
        if p.get("live"):
            recs = [r for r in recs if not r.get("done")]
        n = int(p.get("slowest", 0) or 0)
        if n > 0:
            recs = sorted(recs, key=lambda r: r.get("e2e") or 0.0,
                          reverse=True)[:n]
        return recs

    def _h_autoscaler_state(self, p, ctx):
        """Demand + per-node busyness for the autoscaler reconciler
        (reference: gcs_autoscaler_state_manager.h cluster state reply)."""
        horizon = time.time() - p.get("demand_window_s", 10.0)
        pooled = self._pooled_fast_keys()
        with self._lock:
            for k in [k for k, d in self._demand.items()
                      if d["ts"] < horizon]:
                del self._demand[k]
            demand = []
            for d in self._demand.values():
                # one shape per pending task, capped (a deep queue should
                # not request more nodes than it can use at once)
                demand.extend([dict(d["resources"])] *
                              min(d["count"], 16))
            # Unplaced placement-group bundles are demand too — the TPU
            # gang path: a pending {TPU-{pod}-head: 1} bundle asks the
            # autoscaler for a whole slice (reference:
            # gcs_autoscaler_state_manager reports pending gang requests)
            for pg in self._pgs.values():
                if pg["state"] == "PENDING":
                    demand.extend(dict(b) for b in pg["bundles"])
            busy_nodes = set()
            for lease in self._leases.values():
                if lease.fast_key is not None and lease.fast_key in pooled:
                    continue  # pooled cache must not block idle drain
                busy_nodes.add(lease.node_id)
            for e in self._actors.values():
                if e.state in (ALIVE, PENDING, RESTARTING) and e.node_id:
                    busy_nodes.add(e.node_id)
            # a CREATED placement group is a live reservation: its nodes
            # must never be idle-drained out from under it
            for pg in self._pgs.values():
                if pg["state"] == "CREATED":
                    busy_nodes.update(pg.get("nodes") or ())
            nodes = [{"node_id": n.node_id, "alive": n.alive,
                      "address": n.address,
                      "resources": n.resources,
                      "busy": n.node_id in busy_nodes}
                     for n in self._nodes.values()]
        return {"demand": demand, "nodes": nodes}

    def _h_state_dump(self, p, ctx):
        cutoff = time.time() - self.METRICS_STALE_S
        with self._lock:
            for w in [w for w, e in self._objects.items()
                      if e["ts"] < cutoff]:
                del self._objects[w]
            objects = [
                {"owner": w[:12], "node": e["node"], "role": e["role"],
                 **{k: v for k, v in e["snap"].items()
                    if k not in ("dir", "dir_totals")}}
                for w, e in self._objects.items()]
            # flattened per-object directory rows (the `ray memory` /
            # state.list_objects() surface; full totals via objects_dump)
            objects_dir = [
                {"node": e["node"], "reporter": w[:12], **row}
                for w, e in self._objects.items()
                for row in (e["snap"].get("dir") or ())]
            tasks = list(self._task_events)[-int(p.get("task_limit", 200)
                                                if p else 200):]
            return {
                "tasks": tasks,
                "objects": objects,
                "objects_dir": objects_dir,
                "events": self.journal.stats(),
                "nodes": [{"node_id": n.node_id, "address": n.address,
                           "alive": n.alive, "resources": n.resources}
                          for n in self._nodes.values()],
                "actors": [{"actor_id": aid.hex(), "class": e.class_name,
                            "state": e.state, "node_id": e.node_id,
                            "name": e.name_key, "restarts": e.num_restarts,
                            "reason": e.reason}
                           for aid, e in self._actors.items()],
                "leases": len(self._leases),
                "fast_lease": (self.server.lease_stats()
                               if self._fast_lease_on else None),
                "placement_groups": [
                    {"pg_id": pid.hex(), "strategy": pg["strategy"],
                     "nodes": pg["nodes"], "name": pg["name"]}
                    for pid, pg in self._pgs.items()],
            }

    def stop(self) -> None:
        self._stopped.set()
        if self._persist_path:
            try:
                self._save_snapshot()
            except Exception:  # noqa: BLE001
                pass
        self.server.stop()
        self._node_clients.close_all()


def main() -> None:
    """Entrypoint: ``python -m ray_tpu.runtime.head <port> <session>``."""
    import signal

    port = int(sys.argv[1])
    session = sys.argv[2]
    if len(sys.argv) > 3:
        config_mod.GlobalConfig.apply(json.loads(sys.argv[3]))
    persist = sys.argv[4] if len(sys.argv) > 4 else ""
    head = Head(port=port, session=session, persist_path=persist)
    stop = threading.Event()

    def _term(*_):
        head.stop()
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    sys.stdout.write(f"RTPU_HEAD_READY {head.address}\n")
    sys.stdout.flush()
    try:
        while not stop.wait(3600):
            pass
    except KeyboardInterrupt:
        head.stop()


if __name__ == "__main__":
    main()
