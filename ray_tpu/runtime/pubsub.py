"""Topic pub/sub broker hosted by the head.

Role-equivalent to the reference's GCS-side pub/sub (reference:
src/ray/pubsub/publisher.h:297 `Publisher`, subscriber.h long-poll
protocol): publishers push messages to named topics; subscribers
LONG-POLL with per-topic cursors and are woken as soon as anything new
arrives. The reference dedicates this machinery to internal channels
(object eviction, ref removal, logs, errors); here the same broker also
backs a user-facing topic API (`ray_tpu.util.pubsub`) and the head's
cluster-event feed.

Design notes:
- Per-topic ring buffers (drop-oldest) bound memory under slow or dead
  subscribers — a cursor that fell off the ring resumes at the oldest
  retained message and the gap is reported, mirroring the reference's
  max-buffer publisher semantics.
- Cursors live with the SUBSCRIBER (client-side), not the broker, so the
  broker holds no per-subscriber state to leak when clients vanish; the
  long-poll wait is the only per-call state.
- Poll replies carry the broker ``epoch`` (the head incarnation): after
  a head restart sequence numbers restart at zero, and a subscriber
  holding old-incarnation cursors would otherwise stall silently (high
  stale cursor) or skip messages (low stale cursor). Epoch change tells
  the client to reset cursors.
- Blocking waits are capped by a slot semaphore: the broker shares the
  head's RPC thread pool, and unbounded 2s parks could pin every handler
  thread (the head-pool starvation hazard cluster_backend.py documents).
  Polls past the cap degrade to an immediate scan; the client just
  re-polls.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Tuple

#: server-side cap on one long-poll wait; clients re-poll in a loop, so
#: this bounds how long a poll occupies an RPC worker thread
MAX_POLL_WAIT_S = 2.0
#: at most this many polls may BLOCK concurrently (excess polls return
#: their scan immediately); keeps long-polls from starving the head pool
MAX_BLOCKED_POLLS = 8
DEFAULT_BUFFER = 1000


class PubsubBroker:
    def __init__(self, max_buffer: int = DEFAULT_BUFFER, epoch: int = 0):
        self._max = max_buffer
        self.epoch = epoch
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._wait_slots = threading.BoundedSemaphore(MAX_BLOCKED_POLLS)
        # topic -> deque[(seq, message)]; seq is 1-based and per-topic
        self._topics: Dict[str, collections.deque] = {}
        self._seq: Dict[str, int] = {}

    def publish(self, topic: str, message: Any) -> int:
        """Append to the topic ring; returns the message's sequence no."""
        with self._cv:
            buf = self._topics.get(topic)
            if buf is None:
                buf = collections.deque(maxlen=self._max)
                self._topics[topic] = buf
            seq = self._seq.get(topic, 0) + 1
            self._seq[topic] = seq
            buf.append((seq, message))
            self._cv.notify_all()
            return seq

    def _scan(self, cursors: Dict[str, int]) -> Dict[str, Any]:
        """Collect news per topic. Caller holds the lock. The per-topic
        seq check makes no-op wakeups O(topics) dict lookups, not
        O(ring) rescans (publish notify_all wakes every waiter)."""
        out: Dict[str, Any] = {}
        for topic, cursor in cursors.items():
            if self._seq.get(topic, 0) <= cursor:
                continue
            buf = self._topics.get(topic)
            if not buf:
                continue
            oldest = buf[0][0]
            dropped = max(0, oldest - int(cursor) - 1)
            msgs = [m for s, m in buf if s > cursor]
            if msgs or dropped:
                out[topic] = {"messages": msgs,
                              "cursor": self._seq[topic],
                              "dropped": dropped}
        return out

    def poll(self, cursors: Dict[str, int],
             timeout_s: float) -> Dict[str, Any]:
        """Messages with seq > cursor for each subscribed topic, blocking
        up to ``timeout_s`` (clamped) until at least one arrives.

        Returns {"epoch": E, "topics": {topic: {"messages": [...],
        "cursor": int, "dropped": n}}} — topics empty on timeout."""
        deadline = time.monotonic() + max(0.0, min(timeout_s,
                                                   MAX_POLL_WAIT_S))
        may_block = self._wait_slots.acquire(blocking=False)
        try:
            with self._cv:
                while True:
                    out = self._scan(cursors)
                    if out:
                        return {"epoch": self.epoch, "topics": out}
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not may_block:
                        return {"epoch": self.epoch, "topics": {}}
                    self._cv.wait(timeout=remaining)
        finally:
            if may_block:
                self._wait_slots.release()

    def topics(self) -> Dict[str, Any]:
        with self._lock:
            return {"epoch": self.epoch,
                    "topics": [(t, self._seq.get(t, 0))
                               for t in self._topics]}
