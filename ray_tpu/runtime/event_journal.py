"""Cluster event journal — structured, ordered control-plane history.

Role-equivalent to the reference's GCS-side cluster-event log (reference:
src/ray/gcs/gcs_server/gcs_task_manager buffering task/worker failure
events, surfaced as `ray list cluster-events` via python/ray/util/state):
every significant cluster transition — node register/death, worker death
with its exit cause, actor restart/evict, object spill overflow, FastLease
grant failure, autoscaler decisions — lands here as ONE structured record.

Two properties the debugging workflows lean on:

* **Monotonic order.** ``seq`` is assigned under the journal lock at head
  arrival, so a dump is totally ordered even when events originate on
  different nodes (worker-side spill events ride ``telemetry_push`` and are
  sequenced when they land, like the reference's GCS arrival order). A
  follow cursor (``after_seq``) therefore never skips or repeats.
* **Trace cross-links.** Events are stamped with the ambient trace id when
  one exists (or the id the reporter carried), so `python -m ray_tpu trace`
  and the journal can be joined on ``trace_id`` — e.g. a worker-death event
  and the actor-restart it caused share one id.

The ring is bounded (``cluster_event_journal_size``); ``stats()`` reports
both the total ever recorded and the kept window so consumers can tell
when history has been evicted.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List

from ray_tpu.util import trace_context


class ClusterEventJournal:
    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(2, int(capacity)))
        self._seq = 0

    def record(self, type: str, trace_id: str = "",
               **fields: Any) -> Dict[str, Any]:
        """Append one event and return the stored record.

        ``seq``/``ts`` are assigned under the lock (head arrival time), so
        dumps are gap-free and monotonic; ``fields`` cannot override them.
        An empty ``trace_id`` picks up the ambient trace if one is active
        at the recording site.
        """
        if not trace_id:
            ctx = trace_context.current()
            if ctx is not None:
                trace_id = ctx[0]
        ev: Dict[str, Any] = {
            k: v for k, v in fields.items()
            if v is not None and k not in ("seq", "ts")}
        ev["type"] = str(type)
        ev["trace_id"] = trace_id
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            ev["ts"] = time.time()
            self._ring.append(ev)
        return ev

    def dump(self, after_seq: int = 0, type: str = "",
             limit: int = 0) -> List[Dict[str, Any]]:
        """Events with seq > after_seq, oldest first, optionally filtered
        by exact type; ``limit`` keeps the NEWEST n of the selection (the
        tail is what a bounded `events` render wants)."""
        with self._lock:
            out = [dict(e) for e in self._ring
                   if e["seq"] > after_seq and (not type or e["type"] == type)]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"recorded": self._seq, "kept": len(self._ring)}
