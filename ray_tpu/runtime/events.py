"""Task event buffer — per-task state transitions for the timeline.

Role-equivalent to the reference's TaskEventBuffer → GcsTaskManager path
(reference: src/ray/core_worker/task_event_buffer.h batching to
gcs_task_manager.h:88, surfaced as the dashboard timeline and
`ray timeline`): workers buffer (task, start, end) spans and the telemetry
thread flushes them to the head alongside metric snapshots; the CLI
exports Chrome-trace JSON.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List


class TaskEventBuffer:
    MAX_BUFFER = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = collections.deque()
        self._dropped = 0

    def record(self, *, name: str, task_id: str, kind: str,
               start: float, end: float, ok: bool, **extra: Any) -> None:
        """Record one span. ``extra`` carries optional fields — notably
        the trace context trio (trace_id/span_id/parent_span_id) the OTLP
        exporter links spans by; falsy values are dropped so old-format
        events keep their exact seed shape.

        The buffer is a ring: at MAX_BUFFER the OLDEST span is evicted so
        a busy flush interval keeps its newest events (refusing the new
        span instead would freeze the timeline at the interval's first
        4096 spans); the ``__dropped__`` meta marker reports the exact
        eviction count."""
        with self._lock:
            if len(self._events) >= self.MAX_BUFFER:
                self._events.popleft()
                self._dropped += 1
            e = {"name": name, "task_id": task_id, "kind": kind,
                 "start": start, "end": end, "ok": ok}
            for k, v in extra.items():
                if v:
                    e[k] = v
            self._events.append(e)

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = list(self._events), collections.deque()
            if self._dropped:
                out.append({"name": "__dropped__", "task_id": "",
                            "kind": "meta", "start": time.time(),
                            "end": time.time(), "ok": False,
                            "dropped": self._dropped})
                self._dropped = 0
            return out


def to_chrome_trace(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome-trace 'X' (complete) events; load in chrome://tracing or
    Perfetto (reference: `ray timeline` output format)."""
    trace = []
    for e in events:
        trace.append({
            "name": e["name"],
            "cat": e.get("kind", "task"),
            "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": max(e["end"] - e["start"], 0.0) * 1e6,
            "pid": e.get("node", "node"),
            "tid": e.get("worker", "worker"),
            "args": {"task_id": e.get("task_id", ""), "ok": e.get("ok")},
        })
    return trace
