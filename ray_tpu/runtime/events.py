"""Task event buffer — per-task state transitions for the timeline.

Role-equivalent to the reference's TaskEventBuffer → GcsTaskManager path
(reference: src/ray/core_worker/task_event_buffer.h batching to
gcs_task_manager.h:88, surfaced as the dashboard timeline and
`ray timeline`): workers buffer (task, start, end) spans and the telemetry
thread flushes them to the head alongside metric snapshots; the CLI
exports Chrome-trace JSON.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List


class TaskEventBuffer:
    MAX_BUFFER = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = collections.deque()
        self._dropped = 0

    def record(self, *, name: str, task_id: str, kind: str,
               start: float, end: float, ok: bool, **extra: Any) -> None:
        """Record one span. ``extra`` carries optional fields — notably
        the trace context trio (trace_id/span_id/parent_span_id) the OTLP
        exporter links spans by; falsy values are dropped so old-format
        events keep their exact seed shape.

        The buffer is a ring: at MAX_BUFFER the OLDEST span is evicted so
        a busy flush interval keeps its newest events (refusing the new
        span instead would freeze the timeline at the interval's first
        4096 spans); the ``__dropped__`` meta marker reports the exact
        eviction count."""
        with self._lock:
            if len(self._events) >= self.MAX_BUFFER:
                self._events.popleft()
                self._dropped += 1
            e = {"name": name, "task_id": task_id, "kind": kind,
                 "start": start, "end": end, "ok": ok}
            for k, v in extra.items():
                if v:
                    e[k] = v
            self._events.append(e)

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = list(self._events), collections.deque()
            if self._dropped:
                out.append({"name": "__dropped__", "task_id": "",
                            "kind": "meta", "start": time.time(),
                            "end": time.time(), "ok": False,
                            "dropped": self._dropped})
                self._dropped = 0
            return out


def to_chrome_trace(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome-trace 'X' (complete) events; load in chrome://tracing or
    Perfetto (reference: `ray timeline` output format)."""
    trace = []
    for e in events:
        trace.append({
            "name": e["name"],
            "cat": e.get("kind", "task"),
            "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": max(e["end"] - e["start"], 0.0) * 1e6,
            "pid": e.get("node", "node"),
            "tid": e.get("worker", "worker"),
            "args": {"task_id": e.get("task_id", ""), "ok": e.get("ok")},
        })
    return trace


# ----------------------------------------------------------------------
# multi-plane Perfetto export — every observability plane as a named
# lane on ONE wall clock. All source timestamps are already epoch
# seconds (task spans carry start/end, compile records ts+duration,
# request records a t0_wall anchor plus relative offsets, journal
# entries ts), so interleaving is pure bookkeeping: stable integer
# pids/tids with 'M'-phase process_name/thread_name metadata.

_LANE_SPANS = 1        # task/actor/scheduler spans (pid per node)
_LANE_TRAIN = 2001     # train step + phase spans
_LANE_REQUESTS = 2002  # LLM request token timelines
_LANE_COMPILES = 2003  # XLA compile events
_LANE_JOURNAL = 2004   # cluster journal markers (instants)

_TRAIN_KINDS = ("train_step", "train_phase")


class _Tids:
    """Stable small thread ids per lane with thread_name metadata."""

    def __init__(self, trace: List[Dict[str, Any]], pid: int):
        self.trace = trace
        self.pid = pid
        self._ids: Dict[str, int] = {}

    def get(self, name: str) -> int:
        tid = self._ids.get(name)
        if tid is None:
            tid = len(self._ids) + 1
            self._ids[name] = tid
            self.trace.append({"ph": "M", "pid": self.pid, "tid": tid,
                               "name": "thread_name",
                               "args": {"name": name or "?"}})
        return tid


def _lane(trace: List[Dict[str, Any]], pid: int, name: str) -> _Tids:
    trace.append({"ph": "M", "pid": pid, "name": "process_name",
                  "args": {"name": name}})
    return _Tids(trace, pid)


def to_perfetto(events: List[Dict[str, Any]],
                compiles: List[Dict[str, Any]] = None,
                requests: List[Dict[str, Any]] = None,
                journal: List[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One Perfetto/Chrome trace interleaving every plane: task-span
    trees (one pid per node), train step/phase times, LLM request token
    timelines (queue wait, first token, decode window), XLA compile
    events (one tid per process, recompiles carrying their signature
    diff), and cluster-journal markers as global instants. Returns the
    JSON-object trace format (``{"traceEvents": [...]}``) — the answer
    to "what was the whole cluster doing during this stall" in a single
    ``trace --perfetto out.json`` file."""
    trace: List[Dict[str, Any]] = []
    node_pids: Dict[str, int] = {}
    node_tids: Dict[str, _Tids] = {}
    train = _lane(trace, _LANE_TRAIN, "train: steps + phases")

    for e in events or []:
        if e.get("name") == "__dropped__":
            continue
        kind = e.get("kind", "task")
        start = float(e.get("start") or 0.0)
        dur = max(float(e.get("end") or 0.0) - start, 0.0)
        ev = {"name": e.get("name", "?"), "cat": kind, "ph": "X",
              "ts": start * 1e6, "dur": dur * 1e6,
              "args": {"task_id": e.get("task_id", ""),
                       "ok": e.get("ok")}}
        if e.get("trace_id"):
            ev["args"]["trace_id"] = e["trace_id"]
        if kind in _TRAIN_KINDS:
            ev["pid"] = _LANE_TRAIN
            ev["tid"] = train.get(
                "phases" if kind == "train_phase" else "steps")
        else:
            node = str(e.get("node", "") or "node")[:12]
            pid = node_pids.get(node)
            if pid is None:
                pid = _LANE_SPANS + len(node_pids)
                node_pids[node] = pid
                node_tids[node] = _lane(trace, pid,
                                        f"spans: node {node}")
            ev["pid"] = pid
            ev["tid"] = node_tids[node].get(
                str(e.get("worker", "") or "worker")[:12])
        trace.append(ev)

    if requests:
        lane = _lane(trace, _LANE_REQUESTS, "llm: requests")
        for r in requests:
            if not isinstance(r, dict) or not r.get("t0_wall"):
                continue
            t0 = float(r["t0_wall"])
            rid = str(r.get("rid", "?"))
            tid = lane.get(f"req {rid[:12]}")
            admits = r.get("admits") or []
            ttft = r.get("ttft")
            e2e = r.get("e2e") or r.get("age") or ttft or 0.0
            trace.append({
                "name": f"request {rid[:12]}", "cat": "llm_request",
                "ph": "X", "ts": t0 * 1e6,
                "dur": max(float(e2e), 0.0) * 1e6,
                "pid": _LANE_REQUESTS, "tid": tid,
                "args": {"trace_id": r.get("trace_id", ""),
                         "prompt_tokens": r.get("prompt_tokens"),
                         "generated": r.get("n_generated"),
                         "finish": r.get("finish_reason", ""),
                         "worker": r.get("worker", "")}})
            if admits:
                trace.append({
                    "name": "queue_wait", "cat": "llm_request",
                    "ph": "X", "ts": t0 * 1e6,
                    "dur": max(float(admits[0][0]), 0.0) * 1e6,
                    "pid": _LANE_REQUESTS, "tid": tid, "args": {}})
            if ttft is not None:
                trace.append({
                    "name": "first_token", "cat": "llm_request",
                    "ph": "i", "s": "t",
                    "ts": (t0 + float(ttft)) * 1e6,
                    "pid": _LANE_REQUESTS, "tid": tid, "args": {}})

    if compiles:
        lane = _lane(trace, _LANE_COMPILES, "xla: compiles")
        for c in compiles:
            if not isinstance(c, dict):
                continue
            end = float(c.get("ts") or 0.0)
            dur = float(c.get("duration_s") or
                        c.get("measured_s") or 0.0)
            proc = str(c.get("worker", "") or c.get("pid", "") or "?")
            name = c.get("name") or "<unattributed>"
            if c.get("recompile"):
                name = f"RECOMPILE {name}"
            trace.append({
                "name": name, "cat": "xla_compile", "ph": "X",
                "ts": max(end - dur, 0.0) * 1e6, "dur": dur * 1e6,
                "pid": _LANE_COMPILES, "tid": lane.get(str(proc)[:12]),
                "args": {"signature": c.get("signature"),
                         "diff": c.get("diff"),
                         "fingerprint": c.get("fingerprint", ""),
                         "kind": c.get("kind", ""),
                         "backend": c.get("backend", ""),
                         "trace_id": c.get("trace_id", "")}})

    if journal:
        lane = _lane(trace, _LANE_JOURNAL, "journal: cluster events")
        tid = lane.get("events")
        for j in journal:
            if not isinstance(j, dict) or not j.get("ts"):
                continue
            trace.append({
                "name": j.get("type", "event"), "cat": "journal",
                "ph": "i", "s": "g", "ts": float(j["ts"]) * 1e6,
                "pid": _LANE_JOURNAL, "tid": tid,
                "args": {k: v for k, v in j.items()
                         if k not in ("ts",) and
                         isinstance(v, (str, int, float, bool))}})

    return {"traceEvents": trace, "displayTimeUnit": "ms"}
