"""Framed RPC over TCP: request-id multiplexing, retries, chaos injection.

Role-equivalent to the reference's rpc layer (reference:
src/ray/rpc/grpc_server.h, grpc_client.h, retryable_grpc_client.h): typed
async calls over persistent connections. Design differences are deliberate:
instead of gRPC streams we frame pickled dicts over a TCP socket with a
request-id so many calls pipeline over one connection (the property that
makes lease/push pipelining and 8k tasks/s possible in the reference);
replies may be deferred by the handler (actor queues reply on completion).

Chaos injection mirrors reference src/ray/rpc/rpc_chaos.h:23
(RAY_testing_rpc_failure): config `testing_rpc_failure="method=N[,m=N]"`
fails the first N client calls of that method with RpcError so retry paths
are testable without real network faults.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

from ray_tpu.core import config as config_mod

_FRAME = struct.Struct("<QQ")  # (request_id, payload_len); id 0 = oneway

# request ids with the high bit set are replies
_REPLY_BIT = 1 << 63


class RpcError(Exception):
    """Transport-level failure (connect refused, peer died, chaos)."""


class ChaosInjectedError(RpcError):
    pass


class FastPathUnavailable(RpcError):
    """The peer answered a binary fast frame via its Python path — the
    fast path is deterministically absent there; callers should drop to
    the pickle path immediately instead of retrying the fast frame."""


def _chaos_table() -> Dict[str, int]:
    raw = config_mod.GlobalConfig.testing_rpc_failure
    table: Dict[str, int] = {}
    if raw:
        for part in raw.split(","):
            if "=" in part:
                k, v = part.split("=", 1)
                table[k.strip()] = int(v)
    return table


class _ChaosState:
    """Per-process count of injected failures, keyed by method."""

    def __init__(self):
        self._lock = threading.Lock()
        self._injected: Dict[str, int] = {}

    def should_fail(self, method: str) -> bool:
        budget = _chaos_table().get(method)
        if not budget:
            return False
        with self._lock:
            used = self._injected.get(method, 0)
            if used >= budget:
                return False
            self._injected[method] = used + 1
            return True


_chaos = _ChaosState()


def reset_chaos() -> None:
    global _chaos
    _chaos = _ChaosState()


def _chaos_should_fail(method: str) -> bool:
    """Current-table chaos check (shared with the native transport)."""
    return _chaos.should_fail(method)


# ---------------------------------------------------------------------------
# framing helpers

def _send_frame(sock: socket.socket, req_id: int, payload: bytes,
                lock: threading.Lock) -> None:
    header = _FRAME.pack(req_id, len(payload))
    with lock:
        sock.sendall(header + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


#: Frames above this are treated as stream corruption. Large objects move
#: as pipelined read_chunk frames (object_transfer_chunk_bytes each), so
#: legitimate frames stay small; the cap's job is catching desynced
#: headers, whose lengths are effectively random u64s:
#: P(random < 1 TiB) = 2^40/2^64 ≈ 6e-8, so 1 TiB keeps nearly all the
#: protection without ever rejecting real traffic.
_MAX_FRAME_BYTES = 1 << 40


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    header = _recv_exact(sock, _FRAME.size)
    req_id, length = _FRAME.unpack(header)
    if length > _MAX_FRAME_BYTES:
        raise ConnectionError(
            f"frame length {length} exceeds protocol maximum "
            f"({_MAX_FRAME_BYTES}); treating as stream corruption")
    return req_id, _recv_exact(sock, length)


# ---------------------------------------------------------------------------
# server

class HandlerContext:
    """Passed to every handler; allows deferred replies and peer identity."""

    __slots__ = ("_conn", "_req_id", "peer", "replied", "slot_ids")

    def __init__(self, conn: "_ServerConn", req_id: int):
        self._conn = conn
        self._req_id = req_id
        self.peer = conn.peer
        self.replied = False
        # combined frames with pre-allocated per-slot reply ids (eager
        # per-task replies — see call_combined_cb); None on plain requests
        self.slot_ids = None

    def reply(self, value: Any = None, error: Optional[BaseException] = None) -> None:
        if self.replied:
            return
        self.replied = True
        self._conn.send_reply(self._req_id, value, error)

    def reply_to(self, req_id: int, value: Any = None,
                 error: Optional[BaseException] = None) -> None:
        """Reply to one pre-allocated slot id of a combined frame (the
        caller registered a pending entry per slot). Unlike reply(),
        callable many times — once per distinct slot."""
        self._conn.send_reply(req_id, value, error)


DEFERRED = object()  # handler sentinel: "I'll call ctx.reply() later"

#: final main-request reply of an eagerly-flushed combined call: every
#: slot already got its own reply frame; this closes the exchange
_COMBINED_DONE = "__combined_done__"


class _ServerConn:
    def __init__(self, server: "RpcServer", sock: socket.socket, peer):
        self.server = server
        self.sock = sock
        self.peer = peer
        self.wlock = threading.Lock()
        self.alive = True

    def send_reply(self, req_id: int, value: Any, error: Optional[BaseException]) -> None:
        if req_id == 0:  # oneway — no reply expected
            return
        try:
            payload = pickle.dumps((value, error), protocol=5)
        except Exception as e:  # unpicklable result
            payload = pickle.dumps((None, RpcError(f"unpicklable reply: {e!r}")),
                                   protocol=5)
        try:
            _send_frame(self.sock, req_id | _REPLY_BIT, payload, self.wlock)
        except OSError:
            self.alive = False

    def close(self):
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RpcServer:
    """Threaded RPC server. Handlers: dict method -> fn(payload, ctx).

    A handler returns a value (replied immediately), raises (error reply),
    or returns DEFERRED and calls ctx.reply() later from any thread.
    """

    def __init__(self, handlers: Dict[str, Callable[[Any, HandlerContext], Any]],
                 host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, name: str = "rpc",
                 inline_methods: Optional[set] = None):
        self.handlers = dict(handlers)
        # Methods run inline on the connection reader thread instead of the
        # pool: preserves per-connection arrival order (actor task queues —
        # reference: ActorSchedulingQueue seq ordering). Must be fast and
        # non-blocking (enqueue + DEFERRED).
        self.inline_methods = set(inline_methods or ())
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.host, self.port = self._sock.getsockname()
        self.address = f"{self.host}:{self.port}"
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix=f"{name}-h")
        self._conns: list[_ServerConn] = []
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self.on_disconnect: Optional[Callable[[Any], None]] = None
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"{name}-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                sock, peer = self._sock.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _ServerConn(self, sock, peer)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn: _ServerConn) -> None:
        try:
            while not self._stopped.is_set():
                req_id, payload = _recv_frame(conn.sock)
                if self.inline_methods:
                    # decode once on the reader thread; inline methods run
                    # here (per-connection FIFO), the rest go to the pool
                    # with the already-decoded message
                    try:
                        msg = pickle.loads(payload)
                    except BaseException as e:  # noqa: BLE001
                        HandlerContext(conn, req_id).reply(
                            None, error=RpcError(f"bad request: {e!r}"))
                        continue
                    if msg[0] in self.inline_methods or msg[0] == "__batch__":
                        # batches route per-item below; unpacking them here
                        # keeps inline items in per-connection arrival order
                        self._dispatch_decoded(conn, req_id, msg)
                    else:
                        self._pool.submit(
                            self._dispatch_decoded, conn, req_id, msg)
                else:
                    self._pool.submit(self._dispatch, conn, req_id, payload)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.alive = False
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            if self.on_disconnect is not None:
                try:
                    self.on_disconnect(conn.peer)
                except Exception:
                    pass

    def _dispatch(self, conn: _ServerConn, req_id: int, payload: bytes) -> None:
        ctx = HandlerContext(conn, req_id)
        try:
            msg = pickle.loads(payload)
        except BaseException as e:  # noqa: BLE001
            ctx.reply(None, error=RpcError(f"bad request: {e!r}"))
            return
        self._dispatch_decoded(conn, req_id, msg, ctx)

    def _dispatch_decoded(self, conn: _ServerConn, req_id: int, msg,
                          ctx: Optional[HandlerContext] = None) -> None:
        if msg[0] == "__batch__":
            # batched frame: [(req_id, method, body), ...] — dispatch each
            # as an individual request; replies flow per inner id. Items
            # honor inline_methods individually.
            for rid, m, body in msg[1]:
                if m in self.inline_methods:
                    self._dispatch_decoded(conn, rid, (m, body))
                else:
                    self._pool.submit(self._dispatch_decoded, conn, rid,
                                      (m, body))
            return
        if ctx is None:
            ctx = HandlerContext(conn, req_id)
        try:
            # frames are (method, body) or (method, body, slot_ids) — the
            # 3rd element carries pre-allocated per-slot reply ids of an
            # eager combined call; old 2-tuple frames stay accepted
            method, body = msg[0], msg[1]
            if len(msg) > 2 and msg[2]:
                ctx.slot_ids = list(msg[2])
            handler = self.handlers.get(method)
            if handler is None:
                raise RpcError(f"no handler for {method!r}")
            result = handler(body, ctx)
            if result is DEFERRED:
                return
            ctx.reply(result)
        except BaseException as e:  # noqa: BLE001
            ctx.reply(None, error=e)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        self._pool.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# client

class RpcClient:
    """Persistent-connection client with request multiplexing and retries.

    One reader thread resolves reply futures; callers block on their own
    future, so arbitrarily many calls pipeline over the single connection
    (the async-gRPC property the reference relies on).
    """

    def __init__(self, address: str, name: str = "client"):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._name = name
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._pending: Dict[int, Future] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._closed = False

    # -- connection management --

    def _connect(self) -> socket.socket:
        with self._conn_lock:
            if self._sock is not None:
                return self._sock
            if self._closed:
                raise RpcError("client closed")
            cfg = config_mod.GlobalConfig
            sock = socket.create_connection(
                (self._host, self._port), timeout=cfg.rpc_connect_timeout_s)
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
            threading.Thread(target=self._reader_loop, args=(sock,),
                             daemon=True, name=f"{self._name}-rd").start()
            return sock

    @staticmethod
    def _complete(entry, value, error: Optional[BaseException]) -> None:
        """Resolve a pending entry: a Future or a callback(value, error)."""
        if isinstance(entry, Future):
            if entry.done():
                return
            if error is not None:
                entry.set_exception(error)
            else:
                entry.set_result(value)
        else:
            try:
                entry(value, error)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()

    def _reader_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                req_id, payload = _recv_frame(sock)
                req_id &= ~_REPLY_BIT
                with self._pending_lock:
                    entry = self._pending.pop(req_id, None)
                if entry is None:
                    continue
                try:
                    value, error = pickle.loads(payload)
                except BaseException as e:  # noqa: BLE001
                    self._complete(entry, None, RpcError(f"bad reply: {e!r}"))
                    continue
                self._complete(entry, value, error)
        except (ConnectionError, OSError):
            pass
        finally:
            self._fail_all(RpcError(f"connection to {self.address} lost"))

    def _fail_all(self, exc: Exception) -> None:
        with self._conn_lock:
            self._sock = None
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for entry in pending:
            self._complete(entry, None, exc)

    # -- calls --

    def call_async(self, method: str, payload: Any = None) -> Future:
        fut: Future = Future()
        if _chaos.should_fail(method):
            fut.set_exception(ChaosInjectedError(f"chaos: {method}"))
            return fut
        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        with self._id_lock:
            self._next_id += 1
            req_id = self._next_id
        fut._rtpu_req_id = req_id  # lets call() reap on timeout
        with self._pending_lock:
            self._pending[req_id] = fut
        try:
            sock = self._connect()
            data = pickle.dumps((method, payload), protocol=5)
            _send_frame(sock, req_id, data, self._wlock)
        except BaseException as e:  # noqa: BLE001
            with self._pending_lock:
                self._pending.pop(req_id, None)
            if not fut.done():
                fut.set_exception(
                    e if isinstance(e, RpcError) else RpcError(repr(e)))
        return fut

    def call_combined_cb(self, method: str, payloads: list,
                         callback) -> None:
        """One request frame carrying N sub-payloads, with a pre-allocated
        reply id per slot shipped alongside (3rd frame element). An eager
        peer replies per slot the moment that slot finishes — so a slot
        whose result a batchmate depends on is never withheld behind
        unfinished batchmates — then closes with _COMBINED_DONE on the
        main id. A peer that instead replies once with a list of N
        (value, error) pairs (old single-reply servers, plain handlers)
        is equally accepted. Either way callback(i, value, error) fires
        exactly once per slot. Same contract as the native transport's
        call_combined_cb."""
        n = len(payloads)
        lock = threading.Lock()
        done = [False] * n

        def fire(i, value, error):
            with lock:
                if done[i]:
                    return
                done[i] = True
            callback(i, value, error)

        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        with self._id_lock:
            slot_ids = []
            for _ in range(n):
                self._next_id += 1
                slot_ids.append(self._next_id)
            self._next_id += 1
            req_id = self._next_id

        def fanout(value, error):
            # main-request reply: drop the slot entries first so a peer
            # that answered with one combined list (or an error) doesn't
            # leak N pending entries
            with self._pending_lock:
                for rid in slot_ids:
                    self._pending.pop(rid, None)
            if error is None:
                if isinstance(value, list) and len(value) == n:
                    for i, (v, e) in enumerate(value):
                        fire(i, v, e)
                    return
                if value == _COMBINED_DONE:
                    # all slots should have their own replies by now (the
                    # marker is sent last on the same ordered connection);
                    # any still-unfired slot means the peer lost one
                    error = RpcError(
                        f"combined call {method}: peer finished without "
                        f"replying to every slot")
                else:
                    error = RpcError(
                        f"malformed combined reply for {method}: "
                        f"expected list of {n}, got {type(value).__name__}")
            for i in range(n):
                fire(i, None, error)

        with self._pending_lock:
            for i, rid in enumerate(slot_ids):
                self._pending[rid] = (lambda v, e, i=i: fire(i, v, e))
            self._pending[req_id] = fanout
        try:
            if _chaos.should_fail(method):
                raise ChaosInjectedError(f"chaos: {method}")
            sock = self._connect()
            data = pickle.dumps((method, payloads, slot_ids), protocol=5)
            _send_frame(sock, req_id, data, self._wlock)
        except BaseException as e:  # noqa: BLE001
            with self._pending_lock:
                entry = self._pending.pop(req_id, None)
                for rid in slot_ids:
                    self._pending.pop(rid, None)
            if entry is not None:
                err = e if isinstance(e, RpcError) else RpcError(repr(e))
                for i in range(n):
                    fire(i, None, err)

    def call_batch_cb(self, method: str, payloads: list,
                      callback) -> list:
        """Send many requests of one method in a single frame.

        callback(index, value, error) fires once per request on the reader
        thread (must not block). Returns the request ids. Same contract as
        the native transport's call_batch_cb.
        """
        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        items = []
        ids = []
        with self._pending_lock:
            for i, p in enumerate(payloads):
                with self._id_lock:
                    self._next_id += 1
                    req_id = self._next_id
                ids.append(req_id)
                self._pending[req_id] = (lambda v, e, i=i: callback(i, v, e))
                items.append((req_id, method, p))
        try:
            if _chaos.should_fail(method):
                raise ChaosInjectedError(f"chaos: {method}")
            sock = self._connect()
            data = pickle.dumps(("__batch__", items), protocol=5)
            _send_frame(sock, 0, data, self._wlock)
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, RpcError) else RpcError(repr(e))
            with self._pending_lock:
                entries = [self._pending.pop(rid, None) for rid in ids]
            for entry in entries:
                if entry is not None:
                    self._complete(entry, None, err)
        return ids

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        cfg = config_mod.GlobalConfig
        if timeout is None:
            timeout = cfg.rpc_call_timeout_s
        fut = self.call_async(method, payload)
        try:
            return fut.result(timeout=timeout)
        except TimeoutError:
            # drop the abandoned future so pending entries don't accumulate
            # against a peer that never replies
            req_id = getattr(fut, "_rtpu_req_id", None)
            if req_id is not None:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
            raise RpcError(f"call {method} to {self.address} timed out "
                           f"after {timeout}s") from None

    def call_retrying(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        """Retry with exponential backoff on transport failures only.

        Mirrors reference retryable_grpc_client.h: application exceptions
        pass through; RpcError (connect/chaos/conn-lost) retries.
        """
        cfg = config_mod.GlobalConfig
        attempts = max(1, cfg.rpc_retry_max_attempts)
        delay = cfg.rpc_retry_base_ms / 1000.0
        last: Optional[Exception] = None
        for i in range(attempts):
            try:
                return self.call(method, payload, timeout=timeout)
            except RpcError as e:
                last = e
                if i + 1 < attempts:
                    time.sleep(delay)
                    delay = min(delay * 2, 5.0)
        raise last  # type: ignore[misc]

    def oneway(self, method: str, payload: Any = None) -> bool:
        """Fire-and-forget (no reply frame will come back).

        Returns True if the frame was handed to the transport — a False
        means the send definitely failed, so callers with cleanup-critical
        oneways (object deletes) can queue a retry."""
        if _chaos.should_fail(method):
            return True
        try:
            sock = self._connect()
            data = pickle.dumps((method, payload), protocol=5)
            _send_frame(sock, 0, data, self._wlock)
            return True
        except BaseException:  # noqa: BLE001
            return False

    def close(self) -> None:
        self._closed = True
        with self._conn_lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            # shutdown BEFORE close: close() alone doesn't wake our reader
            # thread blocked in recv, and the kernel socket (and its FIN to
            # the peer) is held open until that recv returns
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._fail_all(RpcError("client closed"))


class ClientPool:
    """Address -> RpcClient cache (one persistent connection per peer)."""

    def __init__(self, name: str = "pool"):
        self._name = name
        self._clients: Dict[str, "RpcClient"] = {}
        self._lock = threading.Lock()

    def get(self, address: str) -> "RpcClient":
        with self._lock:
            c = self._clients.get(address)
            if c is None:
                # late global lookup: resolves to the transport selected at
                # module bottom (native by default, pure-Python fallback)
                c = RpcClient(address, name=self._name)
                self._clients[address] = c
            return c

    def invalidate(self, address: str) -> None:
        with self._lock:
            c = self._clients.pop(address, None)
        if c is not None:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            clients = list(self._clients.values())
            self._clients.clear()
        for c in clients:
            c.close()


# ---------------------------------------------------------------------------
# transport selection
#
# The pure-Python classes above are the reference implementation and the
# fallback; by default both RPC roles are served by the native C++ epoll
# transport (src/transport.cc via protocol_native.py — the SURVEY §2.2
# "native transport" requirement). Both speak the identical wire format, so
# mixed clusters work. Set RTPU_NATIVE_TRANSPORT=0 to force pure Python
# (used by the bench A/B and as an escape hatch).

PyRpcServer = RpcServer
PyRpcClient = RpcClient

NATIVE_TRANSPORT = False
_native_import_error: Optional[BaseException] = None
if os.environ.get("RTPU_NATIVE_TRANSPORT", "1") != "0":
    try:
        from ray_tpu.runtime import protocol_native as _protocol_native
        RpcServer = _protocol_native.RpcServer  # type: ignore[misc]
        RpcClient = _protocol_native.RpcClient  # type: ignore[misc]
        NATIVE_TRANSPORT = True
    except Exception as _e:  # noqa: BLE001 — keep the Python fallback
        _native_import_error = _e
