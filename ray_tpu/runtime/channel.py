"""Shm channel rings: fixed-capacity SPSC queues over the node's shm store.

Role-equivalent to the reference's compiled-graph channels (reference:
python/ray/experimental/channel/ — mutable plasma buffers + semaphores
moving aDAG intermediates without tasks). Redesigned over this runtime's
existing arena (core/_native ShmStore): a channel is a ring of `capacity`
slot object-ids; the writer creates+seals slot (seq % capacity), the
reader polls contains(), reads, and DELETES the slot — deletion is the
backpressure signal that frees the slot for lap seq+capacity. Same-node
processes share the arena, so a hop costs serialize + two native store
calls + one poll, no RPC and no scheduler.

Polling is adaptive: a short spin (native contains() is ~1µs) catches the
common in-flight case, then exponential sleep up to 1ms bounds idle CPU
on small hosts.
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Optional

from ray_tpu.core import serialization


class ChannelClosed(Exception):
    """The peer tore the channel down (sentinel received)."""


_STOP = b"\x00rtpu-channel-stop"


def _slot_id(name: str, slot: int) -> bytes:
    return hashlib.sha256(f"rtpu-chan:{name}:{slot}".encode()).digest()[:28]


class ShmChannel:
    """Single-producer single-consumer ring; one side writes, one reads.

    Both ends attach by (name, capacity) against the SAME node store —
    create one end with `writer=True` in the producing process and
    `writer=False` in the consuming process.
    """

    def __init__(self, store, name: str, capacity: int = 8):
        self.store = store
        self.name = name
        self.capacity = capacity
        self._seq = 0  # next slot to write (writer) / read (reader)

    # ------------------------------------------------------------- writer

    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        payload = serialization.serialize(value).to_bytes()
        self.put_bytes(payload, timeout)

    def put_bytes(self, payload: bytes, timeout: Optional[float] = None
                  ) -> None:
        slot = _slot_id(self.name, self._seq % self.capacity)
        self._wait(lambda: not self.store.contains(slot), timeout,
                   "channel full (reader gone?)")
        self._write(slot, payload)

    def try_put(self, value: Any) -> bool:
        """Non-blocking put; False when the ring slot is still occupied
        (lets a single-threaded producer interleave result draining
        instead of deadlocking on a full pipeline)."""
        slot = _slot_id(self.name, self._seq % self.capacity)
        if self.store.contains(slot):
            return False
        self._write(slot, serialization.serialize(value).to_bytes())
        return True

    def _write(self, slot: bytes, payload: bytes) -> None:
        self.store.put(slot, payload)
        # drop the creator pin: the reader's delete must actually reclaim
        # the slot, or the ring jams on the first lap
        self.store.release(slot)
        self._seq += 1

    def close(self, timeout: Optional[float] = 5.0) -> bool:
        """Send the stop sentinel; the reader raises ChannelClosed.
        Returns False when the ring stayed full past the timeout (the
        sentinel was NOT sent — caller must unjam and retry, or the
        reader loop lives forever)."""
        try:
            self.put_bytes(_STOP, timeout)
            return True
        except TimeoutError:
            return False

    # ------------------------------------------------------------- reader

    def get(self, timeout: Optional[float] = None) -> Any:
        slot = _slot_id(self.name, self._seq % self.capacity)
        self._wait(lambda: self.store.contains(slot), timeout,
                   "channel empty (writer gone?)")
        view = self.store.get(slot)
        try:
            payload = bytes(view)
        finally:
            self.store.release(slot)
        self.store.delete(slot)  # frees the slot: writer backpressure
        self._seq += 1
        if payload == _STOP:
            raise ChannelClosed(self.name)
        return serialization.deserialize(payload)

    # ------------------------------------------------------------- common

    def drain(self) -> None:
        """Best-effort slot cleanup (teardown after a dead peer)."""
        for i in range(self.capacity):
            self.store.delete(_slot_id(self.name, i))

    @staticmethod
    def _wait(ready, timeout: Optional[float], what: str) -> None:
        # spin first: the native contains() costs ~1µs and in-flight hops
        # resolve in tens of µs; then back off to bound idle CPU
        for _ in range(200):
            if ready():
                return
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 50e-6
        while True:
            if ready():
                return
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(what)
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)
