"""Per-node daemon — worker pool, leases, shm store host (raylet role).

Role-equivalent to the reference's raylet (reference:
src/ray/raylet/node_manager.h:118 — lease protocol at :554; worker pool at
src/ray/raylet/worker_pool.h:224): owns the node's shared-memory object
store, spawns/monitors worker processes, grants leased workers to the head,
and serves cross-node object reads (role of the object manager's push/pull,
src/ray/object_manager/object_manager.h — collapsed into a read RPC since
every peer reaches us over TCP directly).

Worker death is detected by a waiter thread per child process (reference:
raylet worker death via process waits) and reported to the head so actor
restart logic runs (gcs_actor_manager.cc:413).
"""

from __future__ import annotations

import collections
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import config as config_mod
from ray_tpu.core._native import ShmStore
from ray_tpu.core.ids import NodeID, WorkerID
from ray_tpu.runtime.protocol import ClientPool, RpcError, RpcServer
from ray_tpu.util import metrics as metrics_mod


def _proc_dead(proc) -> bool:
    """True when the child is dead, including dead-but-unreaped: Popen
    poll() returns None while another thread (our per-worker waitpid
    thread) holds the internal wait lock, so zombies need the /proc
    state check."""
    if proc.poll() is not None:
        return True
    try:
        with open(f"/proc/{proc.pid}/stat") as f:
            # field 3 is the state letter; comm (field 2) may contain
            # spaces but is parenthesized — split after the last ')'
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state in ("Z", "X", "x")
    except (OSError, IndexError):
        return True  # no /proc entry: reaped and gone


class _WorkerEntry:
    __slots__ = ("worker_id", "proc", "address", "ready", "state", "actor_id",
                 "chips", "env_key", "idle_since", "cgroup_leaf",
                 "out_path", "err_path", "log_path")

    def __init__(self, worker_id: bytes, proc: subprocess.Popen,
                 env_key: str = ""):
        self.worker_id = worker_id
        self.proc = proc
        self.cgroup_leaf: Optional[str] = None
        # durable per-worker stream/log files in the session log dir
        # (None when the log plane is disabled: streams are inherited)
        self.out_path: Optional[str] = None
        self.err_path: Optional[str] = None
        self.log_path: Optional[str] = None
        self.address: Optional[str] = None
        self.ready = threading.Event()
        self.state = "starting"  # starting | idle | leased | actor | dead
        self.actor_id: Optional[bytes] = None
        self.chips: Optional[list] = None  # TPU chip ids owned (single-use)
        # runtime-env signature this worker was spawned under; workers only
        # serve leases of their own environment (reference: WorkerPool keys
        # workers by runtime_env hash, worker_pool.h:224)
        self.env_key = env_key
        self.idle_since: Optional[float] = None


class NodeDaemon:
    def __init__(self, head_addr: str, session: str,
                 resources: Dict[str, float],
                 object_store_bytes: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 node_id: Optional[str] = None):
        cfg = config_mod.GlobalConfig
        self.head_addr = head_addr
        self.session = session
        # launcher-assigned id lets the autoscaler match a registration to
        # the exact launch it came from (adoption by identity, not order)
        self.node_id = node_id or NodeID.from_random().hex()
        self.resources = dict(resources)
        # TPU hosts advertise chip + gang resources (env-detected only —
        # a jax probe here would claim the chips; see accelerators/tpu.py)
        from ray_tpu.accelerators.tpu import (ChipAllocator,
                                              TPUAcceleratorManager)
        if "TPU" not in self.resources:
            tpu_info = TPUAcceleratorManager.detect()
            if tpu_info is not None:
                self.resources.update(
                    TPUAcceleratorManager.node_resources(tpu_info))
        n_chips = int(self.resources.get("TPU", 0))
        self.chips = ChipAllocator(n_chips) if n_chips > 0 else None
        self.shm_name = f"/rtpu_{session[:8]}_{self.node_id[:8]}"
        self.store = ShmStore.create(
            self.shm_name,
            object_store_bytes or cfg.object_store_memory_bytes,
            cfg.object_store_max_objects)
        self._lock = threading.RLock()
        # serve-side object-plane accounting: bytes shipped to remote
        # pullers + spill restores served from disk; the hardware sampler
        # loop pushes these to the head alongside its gauge samples
        self._m_pull_out_bytes = \
            metrics_mod.object_store_pull_out_bytes_counter()
        self._m_spill_restore_total = \
            metrics_mod.object_store_spill_restore_total_counter()
        self._m_spill_restore_bytes = \
            metrics_mod.object_store_spill_restore_bytes_counter()
        self._workers: Dict[bytes, _WorkerEntry] = {}
        # env_key -> FIFO of idle worker ids ('' = default environment)
        self._idle: Dict[str, List[bytes]] = {}
        self._spawn_reserved = 0  # in-flight spawns counted against the cap
        self._clients = ClientPool(name="node")
        self._stopped = threading.Event()
        self.server = RpcServer({
            "lease_worker": self._h_lease_worker,
            "return_worker": self._h_return_worker,
            "start_actor": self._h_start_actor,
            "kill_worker": self._h_kill_worker,
            "worker_ready": self._h_worker_ready,
            "read_object": self._h_read_object,
            "object_info": self._h_object_info,
            "read_chunk": self._h_read_chunk,
            "delete_object": self._h_delete_object,
            "store_stats": lambda p, c: self.store.stats(),
            "node_stats": self._h_node_stats,
            "profile_worker": self._h_profile_worker,
            "profile_burst": self._h_profile_burst,
            "list_workers": self._h_list_workers,
            "worker_fate": self._h_worker_fate,
            "ping": lambda p, c: "pong",
            "shutdown": self._h_shutdown,
        }, host=host, port=port, max_workers=32, name="node")
        self.address = self.server.address
        # worker deaths the head hasn't acknowledged yet (it may be down
        # mid-restart); flushed by the head-watch loop after reconnect
        self._dead_unreported: List[dict] = []
        self._head_incarnation: Optional[str] = None
        self._register_with_head(retrying=True)
        # watch the head for restarts: a new incarnation means fresh head
        # tables — re-register and hand over our still-running actor
        # workers for reconciliation (reference: raylet reconnect to a
        # restarted GCS, gcs_server/gcs_init_data.h rebuild path)
        threading.Thread(target=self._head_watch_loop, daemon=True,
                         name="node-head-watch").start()
        # reap idle workers past worker_idle_timeout_s (reference:
        # WorkerPool idle eviction, worker_pool.h:224)
        threading.Thread(target=self._idle_reap_loop, daemon=True,
                         name="node-idle-reap").start()
        # why a worker was killed (e.g. "oom"), kept for submitters that
        # see only a dropped connection and need the real cause
        self._fates: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        # cgroup-v2 worker isolation (best-effort; no-op without a
        # writable unified hierarchy — see runtime/cgroup.py)
        self.cgroups = None
        if cfg.worker_cgroup:
            from ray_tpu.runtime.cgroup import CgroupManager
            self.cgroups = CgroupManager(session, root=cfg.cgroup_root)
            if not self.cgroups.enabled:
                self.cgroups = None
        if cfg.memory_monitor_refresh_ms > 0:
            # memory monitor + OOM worker killing (reference:
            # common/memory_monitor.h:52 polling + retriable-FIFO victim
            # policy, raylet/worker_killing_policy_retriable_fifo.h)
            threading.Thread(target=self._memory_monitor_loop, daemon=True,
                             name="node-mem-monitor").start()
        if cfg.hw_sampler_period_s > 0:
            # hardware telemetry: cpu%/RSS/cgroup/arena samples -> head
            # ring buffers (reference: reporter_agent.py poll loop)
            threading.Thread(target=self._hw_sampler_loop, daemon=True,
                             name="node-hw-sampler").start()
        # continuous wall-clock stack sampler; exports ride the hardware
        # sampler's telemetry_push into the head's ProfileStore
        try:
            from ray_tpu.util import stack_profiler
            stack_profiler.ensure_started()
        except Exception:  # noqa: BLE001 — profiling never stops boot
            pass
        # structured log plane: the daemon's own diagnostics (OOM kills,
        # spawn failures) go to node-<id>.log + the head's LogStore, and
        # _log_dir is where spawned workers' .out/.err streams land —
        # the durable half of crash forensics
        self._log_dir: Optional[str] = None
        try:
            from ray_tpu.util import log_plane
            if log_plane.ensure_started(
                    role="node", node=self.node_id[:12],
                    log_dir=log_plane.session_log_dir(session),
                    filename=f"node-{self.node_id[:12]}.log") is not None:
                self._log_dir = log_plane.session_log_dir(session)
                os.makedirs(self._log_dir, exist_ok=True)
                log_plane.get_logger().info(
                    f"node daemon started (session {session})")
        except Exception:  # noqa: BLE001 — logging never stops boot
            pass
        for _ in range(cfg.worker_pool_prestart):
            self._spawn_worker()

    # ------------------------------------------------------ head liveness

    def _register_with_head(self, retrying: bool = False) -> None:
        with self._lock:
            actor_workers = [
                {"worker_id": w.worker_id, "actor_id": w.actor_id,
                 "address": w.address}
                for w in self._workers.values()
                if w.state == "actor" and w.actor_id is not None
                and w.address is not None]
        payload = {
            "node_id": self.node_id, "address": self.address,
            "shm_name": self.shm_name, "resources": self.resources,
            "actor_workers": actor_workers,
        }
        client = self._clients.get(self.head_addr)
        reply = (client.call_retrying if retrying else client.call)(
            "register_node", payload)
        self._head_incarnation = (reply or {}).get("incarnation")
        # workers whose actors the (restarted) head disowned: reap them so
        # the pool doesn't leak orphans serving nobody
        for wid in (reply or {}).get("kill", ()):
            self._h_kill_worker({"worker_id": wid}, None)

    def _head_watch_loop(self) -> None:
        period = config_mod.GlobalConfig.node_head_watch_period_s
        client = self._clients.get(self.head_addr)
        while not self._stopped.wait(period):
            try:
                pong = client.call("ping", timeout=max(2.0, period * 4))
            except RpcError:
                continue  # head down/restarting: keep polling
            inc = pong.get("incarnation") if isinstance(pong, dict) else None
            try:
                if inc is not None and inc != self._head_incarnation:
                    self._register_with_head()
                self._flush_dead_reports()
            except RpcError:
                continue

    def _flush_dead_reports(self) -> None:
        with self._lock:
            pending, self._dead_unreported = self._dead_unreported, []
        for rep in pending:
            try:
                self._clients.get(self.head_addr).call("worker_died", rep)
            except RpcError:
                with self._lock:
                    self._dead_unreported.append(rep)

    # ------------------------------------------------------------ worker pool

    def _retire_locked(self, entry: "_WorkerEntry"):
        """Remove an idle worker from the pool books (caller holds the
        lock) and return its proc for termination outside the lock. The
        waiter thread's cleanup is idempotent against this removal."""
        entry.state = "stopping"
        self._workers.pop(entry.worker_id, None)
        pool = self._idle.get(entry.env_key, [])
        if entry.worker_id in pool:
            pool.remove(entry.worker_id)
        return entry.proc

    def _evict_one_idle_locked(self, exclude_env: str):
        """Free a pool slot by retiring the oldest idle worker of some
        OTHER environment (caller holds the lock). Without this, a pool
        full of idle default-env workers starves every runtime_env lease
        forever (the cap counts them but nothing reclaims them)."""
        for env_key, pool in self._idle.items():
            if env_key == exclude_env:
                continue
            while pool:
                entry = self._workers.get(pool[0])
                if entry is None or entry.state != "idle":
                    pool.pop(0)
                    continue
                return self._retire_locked(entry)
        return None

    def _idle_reap_loop(self) -> None:
        timeout_s = config_mod.GlobalConfig.worker_idle_timeout_s
        period = min(30.0, max(1.0, timeout_s / 4))
        while not self._stopped.wait(period):
            now = time.monotonic()
            procs = []
            with self._lock:
                for pool in self._idle.values():
                    for wid in list(pool):
                        entry = self._workers.get(wid)
                        if entry is None:
                            pool.remove(wid)
                            continue
                        if entry.state == "idle" and \
                                entry.idle_since is not None and \
                                now - entry.idle_since > timeout_s:
                            procs.append(self._retire_locked(entry))
            for proc in procs:
                try:
                    proc.terminate()
                except OSError:
                    pass

    def _spawn_worker(self, env_extra: Optional[Dict[str, str]] = None,
                      chips: Optional[list] = None,
                      env_key: str = "",
                      cwd: Optional[str] = None,
                      num_cpus: float = 0.0) -> _WorkerEntry:
        worker_id = WorkerID.from_random().binary()
        from ray_tpu.runtime.spawn import child_env
        extra = {"RTPU_SESSION": self.session,
                 "RTPU_NODE_ID": getattr(self, "node_id", "")}
        if env_extra:
            extra.update(env_extra)
        env = child_env(extra)
        cmd = [sys.executable, "-m", "ray_tpu.runtime.worker_main",
               self.address, self.head_addr, self.shm_name,
               worker_id.hex(), config_mod.GlobalConfig.to_json()]
        # durable raw streams: with the log plane on, the worker's
        # stdout/stderr land in worker-<id>.{out,err} so a SIGKILL'd
        # worker's dying words survive for the death-report tail
        # (reference: raylet redirects worker output into the session
        # log dir); without it, streams inherit as before
        out_path = err_path = log_path = None
        out_f = err_f = None
        log_dir = getattr(self, "_log_dir", None)
        if log_dir:
            wid12 = WorkerID(worker_id).hex()[:12]
            out_path = os.path.join(log_dir, f"worker-{wid12}.out")
            err_path = os.path.join(log_dir, f"worker-{wid12}.err")
            log_path = os.path.join(log_dir, f"worker-{wid12}.log")
            try:
                out_f = open(out_path, "ab")
                err_f = open(err_path, "ab")
            except OSError:
                out_f = err_f = None
                out_path = err_path = log_path = None
        try:
            proc = subprocess.Popen(
                cmd, env=env, cwd=cwd,
                stdout=out_f if out_f is not None else None,
                stderr=err_f if err_f is not None else None)
        finally:
            # child holds its own dups; parent copies must not leak
            for f in (out_f, err_f):
                if f is not None:
                    f.close()
        entry = _WorkerEntry(worker_id, proc, env_key=env_key)
        entry.out_path, entry.err_path = out_path, err_path
        entry.log_path = log_path
        if self.cgroups is not None:
            # post-fork attach (reference: cgroup_setup.h AddProcessToCgroup)
            # num_cpus is the lease's CPU request: it becomes the leaf's
            # cpu.weight, so a 2-CPU task outweighs a 0.5-CPU task under
            # contention (proportional, not a hard cap)
            entry.cgroup_leaf = self.cgroups.create_worker_group(
                WorkerID(worker_id).hex(),
                memory_bytes=config_mod.GlobalConfig
                .worker_memory_limit_bytes,
                num_cpus=num_cpus)
            self.cgroups.attach(entry.cgroup_leaf, proc.pid)
        entry.chips = chips
        with self._lock:
            self._workers[worker_id] = entry
            if chips is not None:
                self.chips.assigned[worker_id] = chips
        threading.Thread(target=self._wait_worker, args=(entry,),
                         daemon=True, name="node-waitpid").start()
        return entry

    def _wait_worker(self, entry: _WorkerEntry) -> None:
        entry.proc.wait()
        rc = entry.proc.returncode
        with self._lock:
            prev_state = entry.state
            entry.state = "dead"
            self._workers.pop(entry.worker_id, None)
            pool = self._idle.get(entry.env_key, [])
            if entry.worker_id in pool:
                pool.remove(entry.worker_id)
            if self.chips is not None:
                self.chips.release(entry.worker_id)
        entry.ready.set()
        if self.cgroups is not None:
            # kernel-enforced OOM (memory.max breach) leaves no trace in
            # our RSS poller — memory.events is the authoritative record
            ev = self.cgroups.memory_events(entry.cgroup_leaf)
            if ev.get("oom_kill", 0) > 0:
                self._record_fate(entry.worker_id, "oom")
            self.cgroups.remove_worker_group(entry.cgroup_leaf)
        if self._stopped.is_set() or prev_state == "stopping":
            return
        with self._lock:
            fate = self._fates.get(WorkerID(entry.worker_id).hex())
        report = {"worker_id": entry.worker_id, "node_id": self.node_id,
                  "reason": "oom-killed" if fate == "oom"
                            else f"exit code {rc}"}
        # crash forensics: attach the dead worker's dying words — the
        # tail of its raw stderr file plus the last structured-log lines
        # (both durable on THIS node's disk, so a SIGKILL loses nothing
        # the kernel already flushed) — for the worker_death journal
        tail_n = config_mod.GlobalConfig.log_death_tail_lines
        if tail_n > 0 and (entry.err_path or entry.log_path):
            from ray_tpu.util import log_plane
            stderr_tail = log_plane.tail_lines(entry.err_path, tail_n)
            if stderr_tail:
                report["stderr_tail"] = stderr_tail
            log_tail = []
            for raw in log_plane.tail_lines(entry.log_path, tail_n):
                try:
                    log_tail.append(
                        log_plane.format_record(json.loads(raw)))
                except (ValueError, TypeError):
                    log_tail.append(raw)
            if log_tail:
                report["log_tail"] = log_tail
        try:
            self._clients.get(self.head_addr).call("worker_died", report)
        except RpcError:
            # head unreachable (likely restarting): queue the report so an
            # actor death during head downtime still triggers its restart
            with self._lock:
                self._dead_unreported.append(report)

    # --------------------------------------------------------- memory monitor

    @staticmethod
    def _rss_bytes(pid: int) -> Optional[int]:
        """Private RSS (resident minus shared pages): zero-copy views of
        shm-store objects must not count against a worker's cap — they are
        the node's arena, not the worker's memory."""
        try:
            with open(f"/proc/{pid}/statm") as f:
                fields = f.read().split()
            resident, shared = int(fields[1]), int(fields[2])
            return max(0, resident - shared) * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError):
            return None

    @staticmethod
    def _node_memory() -> Optional[tuple]:
        """(available, total) bytes from /proc/meminfo."""
        try:
            fields = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    fields[k] = int(v.strip().split()[0]) * 1024
            return fields["MemAvailable"], fields["MemTotal"]
        except (OSError, KeyError, ValueError):
            return None

    def _record_fate(self, worker_id: bytes, reason: str) -> None:
        with self._lock:
            self._fates[WorkerID(worker_id).hex()] = reason
            while len(self._fates) > 256:
                self._fates.popitem(last=False)

    def _h_worker_fate(self, p, ctx):
        with self._lock:
            return self._fates.get(p["worker_id"])

    def _oom_kill(self, entry: "_WorkerEntry", why: str) -> None:
        self._record_fate(entry.worker_id, "oom")
        from ray_tpu.util import log_plane
        log_plane.get_logger().warning(
            f"MEMORY MONITOR: killing worker pid={entry.proc.pid} "
            f"({why})",
            worker=WorkerID(entry.worker_id).hex()[:12])
        try:
            entry.proc.kill()
        except OSError:
            pass

    def _memory_monitor_loop(self) -> None:
        cfg = config_mod.GlobalConfig
        period = cfg.memory_monitor_refresh_ms / 1000.0
        last_victim: Optional[bytes] = None
        victim_deadline = 0.0
        while not self._stopped.wait(period):
            limit = cfg.worker_memory_limit_bytes
            with self._lock:
                busy = [w for w in self._workers.values()
                        if w.state in ("leased", "actor")]
                fated = set(self._fates)
            # exclude workers already being killed: their RSS lingers
            # until the kernel reclaims, and re-selecting them (or their
            # neighbours) every tick is the cascade the grace below stops
            busy = [w for w in busy
                    if WorkerID(w.worker_id).hex() not in fated]
            # per-worker cap: deterministic, checked first
            if limit > 0:
                for w in busy:
                    rss = self._rss_bytes(w.proc.pid)
                    if rss is not None and rss > limit:
                        self._oom_kill(
                            w, f"rss {rss >> 20} MiB > limit "
                               f"{limit >> 20} MiB")
            # node-level pressure: ONE victim at a time, and no further
            # kills until the previous victim's process actually exited
            # (or a timeout passes) — /proc/meminfo lags SIGKILL reclaim
            # by several ticks, and killing on stale numbers wipes out
            # healthy workers (reference: MemoryMonitor waits for the
            # victim's death before re-evaluating)
            if last_victim is not None:
                with self._lock:
                    still_here = last_victim in self._workers
                if still_here and time.monotonic() < victim_deadline:
                    continue
                last_victim = None
            mem = self._node_memory()
            if mem is None:
                continue
            available, total = mem
            if total <= 0 or \
                    1.0 - available / total < cfg.memory_usage_threshold:
                continue
            # retriable-FIFO: newest leased (task) worker first, actors
            # only if no task worker exists (reference:
            # worker_killing_policy_retriable_fifo.h — retriable tasks
            # die before harder-to-restart work)
            victims = sorted((w for w in busy if w.state == "leased"),
                             key=lambda w: w.proc.pid, reverse=True) or \
                sorted((w for w in busy if w.state == "actor"),
                       key=lambda w: w.proc.pid, reverse=True)
            if victims:
                used_frac = 1.0 - available / total
                self._oom_kill(
                    victims[0],
                    f"node memory {used_frac:.0%} > "
                    f"{cfg.memory_usage_threshold:.0%}")
                last_victim = victims[0].worker_id
                victim_deadline = time.monotonic() + 10.0

    # --------------------------------------------------------- hw telemetry

    def _hw_sampler_loop(self) -> None:
        """Push one hardware-gauge batch per period over telemetry_push;
        the head lands each batch in its per-(node, metric) ring buffers
        (util/timeseries.py). Loss-tolerant by design: a down head just
        drops samples until it returns."""
        from ray_tpu.runtime.hw_sampler import HardwareSampler
        from ray_tpu.util import compile_tracker, log_plane, \
            stack_profiler
        period = config_mod.GlobalConfig.hw_sampler_period_s
        # the daemon itself never imports jax, so its tracker stays a
        # silent no-op — starting it anyway keeps the plane contract
        # uniform across processes (and live if that ever changes)
        try:
            compile_tracker.ensure_started(role="node",
                                           node=self.node_id[:12])
        except Exception:  # noqa: BLE001 — telemetry never stops boot
            pass

        def _worker_rows():
            with self._lock:
                return [{"worker_id": WorkerID(w.worker_id).hex(),
                         "pid": w.proc.pid, "state": w.state}
                        for w in self._workers.values()
                        if w.state != "dead"]

        sampler = HardwareSampler(
            cgroup_dir=self.cgroups.slice_dir
            if self.cgroups is not None else None,
            workers=_worker_rows,
            arena_stats=self.store.stats)
        while not self._stopped.wait(period):
            try:
                samples = sampler.sample()
                # the daemon's own collapsed-stack window rides the same
                # push (None when profiling is off or nothing sampled),
                # as do its structured-log window + staged storm events
                profiles = stack_profiler.drain_export()
                logs = log_plane.drain_export()
                journal = log_plane.drain_journal_events()
                compiles = compile_tracker.drain_export()
                journal = journal + \
                    compile_tracker.drain_journal_events()
                if samples or profiles or logs or journal or compiles:
                    # the metrics snapshot rides along so daemon-side
                    # counters (pull-out bytes, spill restores served)
                    # aggregate at the head like any worker's
                    self._clients.get(self.head_addr).oneway(
                        "telemetry_push", {
                            "worker": f"node:{self.node_id[:12]}",
                            "node": self.node_id, "role": "node",
                            "samples": samples, "profiles": profiles,
                            "logs": logs, "journal": journal,
                            "compiles": compiles,
                            "metrics": metrics_mod.snapshot()})
            except Exception:  # noqa: BLE001 — head down: keep sampling
                pass

    def _h_worker_ready(self, p, ctx):
        worker_id = p["worker_id"]
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None:
                return False
            entry.address = p["address"]
            # chip workers never join the generic idle pool — leasing one
            # for a CPU task would strand its chips
            if entry.state == "starting" and entry.chips is None:
                entry.state = "idle"
                entry.idle_since = time.monotonic()
                self._idle.setdefault(entry.env_key, []).append(worker_id)
        entry.ready.set()
        return True

    def _h_lease_worker(self, p, ctx):
        """Pop an idle worker (spawning if under the cap); None = busy.

        TPU leases get a dedicated single-use worker spawned with
        TPU_VISIBLE_CHIPS for its allocated chips (visibility must be set
        before the process's TPU runtime initializes — reference:
        accelerators/tpu.py:31); generic idle workers are never reused for
        chips and chip workers never return to the generic pool.
        """
        cfg = config_mod.GlobalConfig
        renv = p.get("runtime_env") or None
        try:
            env_key, env_extra, cwd = self._prepare_runtime_env(renv)
        except RpcError:
            # transient: the head (KV holding the package) is unreachable —
            # report "busy" so the lease is retried, never a permanent
            # failure that kills the task/actor
            return None
        except Exception as e:  # noqa: BLE001 — missing package, bad zip…
            # structured reply, not a typed exception: a raised error would
            # bypass the head's RpcError handling and leak the resources it
            # acquired for this lease (same contract as invalid TPU shapes)
            return {"invalid": f"runtime_env setup failed: {e}"}
        n_tpu = int(p.get("resources", {}).get("TPU", 0) or 0)
        n_cpu = float(p.get("resources", {}).get("CPU", 0) or 0.0)
        if n_tpu > 0 and self.chips is not None:
            return self._lease_tpu_worker(n_tpu, cfg, env_extra=env_extra,
                                          cwd=cwd, num_cpus=n_cpu)
        with self._lock:
            pool = self._idle.setdefault(env_key, [])
            while pool:
                wid = pool.pop(0)
                entry = self._workers.get(wid)
                if entry is not None and entry.state == "idle":
                    # Liveness gate: a worker that died while pooled must
                    # never be handed out — the native transport fails
                    # pushes to a corpse in microseconds, so re-leasing
                    # one can burn a task's whole retry budget before the
                    # waitpid loop reports the death. NOTE: poll() alone
                    # can read None for a dead-but-unreaped child (the
                    # _wait_worker thread holds the waitpid lock), hence
                    # the /proc zombie check.
                    if _proc_dead(entry.proc):
                        continue  # the waitpid loop reports the death
                    entry.state = "leased"
                    return {"worker_id": wid, "worker_addr": entry.address}
            # count in-flight spawns too — concurrent lease RPCs must not
            # overshoot the pool cap between check and spawn
            evict_proc = None
            if len(self._workers) + self._spawn_reserved >= cfg.worker_pool_max:
                evict_proc = self._evict_one_idle_locked(env_key)
                if evict_proc is None:
                    return None  # pool genuinely busy: retry later
            self._spawn_reserved += 1
        if evict_proc is not None:
            try:
                evict_proc.terminate()
            except OSError:
                pass
        try:
            entry = self._spawn_worker(env_extra=env_extra, env_key=env_key,
                                       cwd=cwd, num_cpus=n_cpu)
        finally:
            with self._lock:
                self._spawn_reserved -= 1
        if not entry.ready.wait(timeout=cfg.rpc_connect_timeout_s * 3):
            return None
        with self._lock:
            if entry.state in ("starting", "idle"):
                pool = self._idle.get(entry.env_key, [])
                if entry.worker_id in pool:
                    pool.remove(entry.worker_id)
                entry.state = "leased"
                return {"worker_id": entry.worker_id,
                        "worker_addr": entry.address}
        return None

    def _prepare_runtime_env(self, renv):
        """(env_key, spawn-env additions, cwd) for a lease's runtime env.
        Materializes the working_dir package into the node cache on first
        use (reference: per-node runtime-env agent)."""
        from ray_tpu.runtime import runtime_env as rtenv
        if not renv:
            return "", None, None
        env_key = rtenv.descriptor_key(renv)
        wd_path = None
        uri = renv.get("working_dir_uri")
        if uri:
            cache_root = os.path.join(
                config_mod.GlobalConfig.session_dir,
                f"rtenv_{self.session[:8]}")
            os.makedirs(cache_root, exist_ok=True)
            wd_path = rtenv.materialize(
                cache_root, uri,
                lambda k: self._clients.get(self.head_addr).call_retrying(
                    "kv_get", {"key": k}))
        return env_key, rtenv.worker_env(renv, wd_path), wd_path

    def _lease_tpu_worker(self, n_tpu: int, cfg, env_extra=None, cwd=None,
                          num_cpus: float = 0.0):
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager
        try:
            TPUAcceleratorManager.validate_chip_request(n_tpu)
        except ValueError as e:
            # structured reply, not an exception: an invalid shape must not
            # leak head-side acquisitions or crash client lease threads
            return {"invalid": str(e)}
        with self._lock:
            if len(self._workers) + self._spawn_reserved >= cfg.worker_pool_max:
                return None
            chips = self.chips.allocate(b"__reserving__", n_tpu)
            if chips is None:
                return None
            self.chips.assigned.pop(b"__reserving__", None)
            self._spawn_reserved += 1
        entry = None
        try:
            env = TPUAcceleratorManager.visibility_env(chips)
            if env_extra:
                env = {**env_extra, **env}
            entry = self._spawn_worker(env_extra=env, chips=chips, cwd=cwd,
                                       num_cpus=num_cpus)
        finally:
            with self._lock:
                self._spawn_reserved -= 1
                if entry is None:
                    # spawn raised after allocation — give the chips back
                    self.chips.release_chips(chips)
        if not entry.ready.wait(timeout=cfg.rpc_connect_timeout_s * 3):
            # stuck spawn: kill it so its chips free via _wait_worker
            # instead of the worker later joining the pool holding chips
            try:
                entry.proc.kill()
            except OSError:
                pass
            return None
        with self._lock:
            if entry.state in ("starting", "idle"):
                pool = self._idle.get(entry.env_key, [])
                if entry.worker_id in pool:
                    pool.remove(entry.worker_id)
                entry.state = "leased"
                return {"worker_id": entry.worker_id,
                        "worker_addr": entry.address}
        return None

    def _h_return_worker(self, p, ctx):
        with self._lock:
            entry = self._workers.get(p["worker_id"])
            if entry is None or entry.state == "dead":
                return False
            if _proc_dead(entry.proc):
                # returned a corpse (the usual reason a lease comes back
                # early): don't pool it — the waitpid loop reports it
                return False
            if entry.chips is not None:
                # chip workers are single-use: their TPU runtime already
                # initialized against specific chips — kill to free them
                entry.state = "stopping"
                proc = entry.proc
            else:
                entry.state = "idle"
                entry.idle_since = time.monotonic()
                pool = self._idle.setdefault(entry.env_key, [])
                if entry.worker_id not in pool:
                    pool.append(entry.worker_id)
                proc = None
        if proc is not None:
            try:
                proc.terminate()
            except OSError:
                pass
        return True

    def _h_start_actor(self, p, ctx):
        with self._lock:
            entry = self._workers.get(p["worker_id"])
        if entry is None or entry.address is None:
            raise RpcError("worker gone before actor start")
        with self._lock:
            entry.state = "actor"
            entry.actor_id = p.get("actor_id")
        self._clients.get(entry.address).call("become_actor", {
            "spec_bytes": p["spec_bytes"],
            "num_restarts": p.get("num_restarts", 0),
        })
        return True

    def _h_kill_worker(self, p, ctx):
        with self._lock:
            entry = self._workers.get(p["worker_id"])
        if entry is None:
            return False
        entry.proc.kill()
        return True

    def _h_list_workers(self, p, ctx):
        with self._lock:
            return [{"worker_id": w.worker_id.hex(), "state": w.state,
                     "address": w.address, "pid": w.proc.pid}
                    for w in self._workers.values()]

    def _h_node_stats(self, p, ctx):
        """psutil-style node report: cpu load, memory, disk, per-worker
        RSS — the reference's per-node reporter agent surface
        (dashboard/agent.py + reporter_agent.py), served straight from
        /proc instead of a separate agent process."""
        mem = self._node_memory()
        try:
            load1, load5, load15 = os.getloadavg()
        except OSError:
            load1 = load5 = load15 = None
        import shutil
        from ray_tpu.runtime.object_plane import spill_dir_for
        spill = spill_dir_for(config_mod.GlobalConfig.session_dir,
                              self.shm_name)
        try:
            du = shutil.disk_usage(spill if os.path.isdir(spill) else "/")
            disk = {"total": du.total, "used": du.used, "free": du.free}
        except OSError:
            disk = None
        with self._lock:
            workers = [{"worker_id": w.worker_id.hex(), "state": w.state,
                        "pid": w.proc.pid,
                        "rss": self._rss_bytes(w.proc.pid)}
                       for w in self._workers.values()]
        return {
            "node_id": self.node_id,
            "cpus": os.cpu_count(),
            "load_avg": [load1, load5, load15],
            "mem_available": mem[0] if mem else None,
            "mem_total": mem[1] if mem else None,
            "disk": disk,
            "store": self.store.stats(),
            "workers": workers,
        }

    def _h_profile_worker(self, p, ctx):
        """On-demand stack dump of one worker (reference: dashboard
        reporter's py-spy profile_manager role): forwards to the worker's
        dump_stacks RPC."""
        wid = p["worker_id"]
        if isinstance(wid, str):
            wid = bytes.fromhex(wid)
        with self._lock:
            w = self._workers.get(wid)
            addr = w.address if w is not None else None
        if addr is None:
            raise ValueError(f"no live worker {wid.hex()} on this node")
        return self._clients.get(addr).call("dump_stacks", timeout=10.0)

    def _h_profile_burst(self, p, ctx):
        """Burst-capture leg of `profiles_record`: this daemon bursts
        itself while every (filtered) live worker bursts in parallel;
        rows come back tagged with node/worker ids so the head can
        attribute frames without knowing our topology."""
        from ray_tpu.util.stack_profiler import burst_capture
        p = p or {}
        seconds = max(0.1, min(float(p.get("seconds", 2.0) or 2.0), 30.0))
        hz = float(p.get("hz", 99.0) or 99.0)
        worker_f = p.get("worker", "")
        node12 = self.node_id[:12]
        futs = []
        if p.get("include_workers", True):
            payload = {"seconds": seconds, "hz": hz}
            with self._lock:
                rows = [(WorkerID(w.worker_id).hex(), w.address)
                        for w in self._workers.values()
                        if w.state != "dead" and w.address]
            for wid, addr in rows:
                if worker_f and not wid.startswith(worker_f):
                    continue
                try:
                    futs.append((wid, self._clients.get(addr).call_async(
                        "profile_burst", payload)))
                except Exception:  # noqa: BLE001 — worker exiting
                    pass
        procs = []
        if p.get("include_self", True):
            procs.append({"key": f"node:{node12}", "role": "node",
                          "node": node12, "worker": "",
                          "export": burst_capture(seconds, hz)})
        for wid, fut in futs:
            try:
                export = fut.result(timeout=seconds + 10.0)
            except Exception:  # noqa: BLE001 — worker died mid-burst
                continue
            procs.append({"key": wid, "role": "worker", "node": node12,
                          "worker": wid[:12], "export": export})
        return {"procs": procs}

    # ----------------------------------------------------------- object plane

    def _h_read_object(self, p, ctx):
        """Serve an object's bytes in ONE frame (small objects only — the
        pull path switches to object_info/read_chunk above the chunk size;
        reference: ObjectManager::Push chunking, push_manager.h:30). Falls
        back to the node's spill directory for disk-overflowed objects."""
        view = self.store.get(p["object_id"])
        if view is None:
            data = self._read_spill(p["object_id"])
            if data is not None:
                self._m_spill_restore_total.inc()
                self._m_spill_restore_bytes.inc(len(data))
                self._m_pull_out_bytes.inc(len(data))
            return data
        try:
            data = bytes(view)
        finally:
            self.store.release(p["object_id"])
        self._m_pull_out_bytes.inc(len(data))
        return data

    def _h_object_info(self, p, ctx):
        """Size probe for the chunked pull path (None = not here)."""
        view = self.store.get(p["object_id"])
        if view is not None:
            try:
                return {"size": len(view)}
            finally:
                self.store.release(p["object_id"])
        try:
            return {"size": os.path.getsize(
                self._spill_path(p["object_id"])), "spilled": True}
        except OSError:
            return None

    def _h_read_chunk(self, p, ctx):
        """One chunk of a sealed (or spilled) object. Each chunk is an
        independent request, so many pipeline concurrently over the
        connection and a multi-GiB object never occupies a single frame
        or a matching-size contiguous reply buffer (reference: 64KiB-5MiB
        chunk streaming, object_manager.h / ObjectBufferPool)."""
        off, ln = p["offset"], p["length"]
        view = self.store.get(p["object_id"])
        if view is not None:
            try:
                data = bytes(view[off:off + ln])
            finally:
                self.store.release(p["object_id"])
            self._m_pull_out_bytes.inc(len(data))
            return data
        try:
            with open(self._spill_path(p["object_id"]), "rb") as f:
                f.seek(off)
                data = f.read(ln)
        except OSError:
            return None
        self._m_pull_out_bytes.inc(len(data))
        return data

    def _spill_path(self, oid: bytes) -> str:
        from ray_tpu.core.config import GlobalConfig
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.runtime.object_plane import spill_file_path
        return spill_file_path(GlobalConfig.session_dir, self.store.name,
                               ObjectID(oid).hex())

    def _read_spill(self, oid: bytes):
        from ray_tpu.core.config import GlobalConfig
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.runtime.object_plane import read_spill_file
        return read_spill_file(GlobalConfig.session_dir, self.store.name,
                               ObjectID(oid).hex())

    def _h_delete_object(self, p, ctx):
        """Owner-initiated free of a primary copy: drop the creator pin
        (held since create+seal — the primary-copy pin, reference: raylet
        pins primary copies until the owner frees), then delete. If readers
        still hold pins the store defers deletion to the last release."""
        oid = p["object_id"]
        try:
            import os
            os.unlink(self._spill_path(oid))
        except OSError:
            pass
        self.store.release(oid)
        return self.store.delete(oid)

    # ------------------------------------------------------------------ admin

    def _h_shutdown(self, p, ctx):
        threading.Thread(target=self.stop, daemon=True).start()
        return True

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            w.state = "stopping"
            try:
                w.proc.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + 3.0
        for w in workers:
            remaining = max(0.0, deadline - time.monotonic())
            try:
                w.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                w.proc.kill()
        if self.cgroups is not None:
            self.cgroups.shutdown()
        try:
            self._clients.get(self.head_addr).call(
                "unregister_node", {"node_id": self.node_id}, timeout=2.0)
        except RpcError:
            pass
        self.server.stop()
        self._clients.close_all()
        try:
            import shutil
            from ray_tpu.core.config import GlobalConfig
            from ray_tpu.runtime.object_plane import spill_dir_for
            shutil.rmtree(spill_dir_for(GlobalConfig.session_dir,
                                        self.store.name),
                          ignore_errors=True)
        except Exception:
            pass
        try:
            self.store.unlink()
        except Exception:
            pass
        self.store.close()


def main() -> None:
    """``python -m ray_tpu.runtime.node <head_addr> <session> <json_args>``"""
    import signal

    head_addr = sys.argv[1]
    session = sys.argv[2]
    args = json.loads(sys.argv[3])
    if args.get("config"):
        config_mod.GlobalConfig.apply(args["config"])
    # chaos seam: lets lifecycle tests model a node that dies before it
    # ever registers (stillborn launch)
    from ray_tpu.util.fault_injector import fire
    fire("node.boot")
    daemon = NodeDaemon(
        head_addr, session,
        resources=args.get("resources") or {"CPU": float(os.cpu_count() or 1)},
        object_store_bytes=args.get("object_store_bytes"),
        node_id=args.get("node_id"))
    signal.signal(signal.SIGTERM, lambda *_: daemon.stop())
    sys.stdout.write(f"RTPU_NODE_READY {daemon.address}\n")
    sys.stdout.flush()
    try:
        while not daemon._stopped.wait(1.0):
            pass
    except KeyboardInterrupt:
        daemon.stop()


if __name__ == "__main__":
    main()
