"""Per-process object plane: shm store client + owner protocol + transfer.

Combines the roles of the reference's CoreWorkerPlasmaStoreProvider
(reference: src/ray/core_worker/store_provider/plasma_store_provider.h:88),
the ownership-based object directory (object_manager/
ownership_based_object_directory.h — owners are asked for locations), and
the pull side of the object manager (object_manager/pull_manager.h:53 —
remote objects are fetched from the node daemon holding them and cached in
the local shm store).

Placement policy (reference memory-store/plasma split,
core_worker/store_provider/): serialized values <= memory_store_threshold
stay in the owner's in-process memory store and travel inline over RPC;
larger values are sealed into the node's shared-memory arena and move
node-to-node at most once, then are mapped zero-copy by every local reader.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Set, Tuple

from ray_tpu.core import config as config_mod
from ray_tpu.core import serialization
from ray_tpu.core._native import ObjectExists, ObjectStoreFull, ShmStore
from ray_tpu.core.ids import ObjectID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.exceptions import ObjectLostError
from ray_tpu.runtime.protocol import ClientPool, RpcError
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import trace_context


def spill_dir_for(session_dir: str, shm_name: str) -> str:
    """Shared per-cluster spill directory (same for every process that
    attaches this shm arena — workers spill, the node daemon serves)."""
    return os.path.join(session_dir, "spill", shm_name.strip("/"))


def spill_file_path(session_dir: str, shm_name: str, oid_hex: str) -> str:
    return os.path.join(spill_dir_for(session_dir, shm_name), oid_hex)


_spill_fs = None


def spill_filesystem():
    """Process-wide storage seam for spill I/O (lazy: daemons import this
    module before metrics are configured). All spill reads/writes ride the
    fault-injectable, retrying filesystem so ``storage.*`` chaos points
    and ``storage_*`` metrics cover the spill path too."""
    global _spill_fs
    if _spill_fs is None:
        from ray_tpu.util.filesystem import storage_filesystem
        _spill_fs = storage_filesystem(None)
    return _spill_fs


def read_spill_file(session_dir: str, shm_name: str,
                    oid_hex: str) -> Optional[bytes]:
    try:
        return spill_filesystem().get(
            spill_file_path(session_dir, shm_name, oid_hex))
    except FileNotFoundError:
        return None
    except Exception:  # noqa: BLE001 — a lost/corrupt spill file reads as
        return None    # absent; callers fall back to lineage reconstruction


class ObjectPlane:
    def __init__(self, worker, local_node_id: str, local_store: ShmStore,
                 head_client, node_addrs: Dict[str, str],
                 node_shm: Dict[str, str]):
        self.worker = worker
        self.local_node_id = local_node_id
        self.store = local_store
        self.head = head_client
        self.node_addrs = dict(node_addrs)     # node_id -> daemon address
        self.node_shm = dict(node_shm)         # node_id -> shm name
        self.locations: Dict[ObjectID, str] = {}   # owned large obj -> node
        # owned obj -> nodes holding secondary (cache) copies; borrowers
        # report in after a pull so later pulls stripe chunks across every
        # holder (reference: OwnershipBasedObjectDirectory location set)
        self.secondary: Dict[ObjectID, set] = {}
        self.owner_addrs: Dict[bytes, str] = {}    # worker_id -> rpc address
        self._peers = ClientPool(name="objplane")
        self._fetching: Set[ObjectID] = set()
        # admission control: bound concurrent large-object pulls so a burst
        # of gets can't open unbounded chunk pipelines (reference:
        # PullManager admission, pull_manager.h:53)
        self._pull_sem = threading.BoundedSemaphore(
            max(1, config_mod.GlobalConfig.object_pull_max_concurrent))
        self._lock = threading.Lock()
        # containment pins: owned object -> refs it contains (release on free)
        self._contained: Dict[ObjectID, list] = {}
        # shared late-delete queue: failed/unroutable delete_object sends
        # coalesce here and ONE drainer retries them after ONE node
        # refresh — a per-failure thread would storm the head exactly
        # when a node dies with many pinned objects on it
        self._late_deletes: list = []   # (node_id, key)
        self._late_thread_live = False
        # --- accounting: per-object directory + spill/pull counters that
        # ride telemetry_push into the head ('python -m ray_tpu memory').
        # Metric instances are cached here so the hot paths pay a plain
        # attribute access, not a registry lookup per event.
        self._acct = bool(config_mod.GlobalConfig.object_accounting)
        self._dir: Dict[ObjectID, dict] = {}   # oid -> size/role/owner/created
        self._journal_pending: list = []       # cluster events awaiting flush
        if self._acct:
            self._m_spill_write_total = \
                metrics_mod.object_store_spill_write_total_counter()
            self._m_spill_write_bytes = \
                metrics_mod.object_store_spill_write_bytes_counter()
            self._m_spill_restore_total = \
                metrics_mod.object_store_spill_restore_total_counter()
            self._m_spill_restore_bytes = \
                metrics_mod.object_store_spill_restore_bytes_counter()
            self._m_pull_in_bytes = \
                metrics_mod.object_store_pull_in_bytes_counter()
            self._m_pull_seconds = \
                metrics_mod.object_store_pull_seconds_histogram()
            self._m_fetch_inflight = \
                metrics_mod.object_store_fetch_inflight_count_gauge()
            self._m_primary_count = \
                metrics_mod.object_store_primary_count_gauge()
            self._m_secondary_count = \
                metrics_mod.object_store_secondary_count_gauge()
            self._m_spilled_count = \
                metrics_mod.object_store_spilled_count_gauge()

    # ------------------------------------------------------------- directory

    def refresh_nodes(self) -> None:
        try:
            for n in self.head.call("list_nodes"):
                self.node_addrs[n["node_id"]] = n["address"]
                self.node_shm[n["node_id"]] = n["shm_name"]
        except RpcError:
            pass

    def node_client(self, node_id: str):
        addr = self.node_addrs.get(node_id)
        if addr is None:
            self.refresh_nodes()
            addr = self.node_addrs.get(node_id)
            if addr is None:
                raise ObjectLostError("", f"unknown node {node_id}")
        return self._peers.get(addr)

    def owner_client(self, owner: WorkerID):
        key = owner.binary()
        addr = self.owner_addrs.get(key)
        if addr is None:
            addr = self.head.call("kv_get", {"key": f"addr:{owner.hex()}"})
            if addr is None:
                raise ObjectLostError("", f"owner {owner.hex()[:12]} unknown")
            self.owner_addrs[key] = addr
        return self._peers.get(addr)

    # ------------------------------------------------------------------- put

    def put_object(self, object_id: ObjectID, value: Any,
                   is_error: bool = False) -> None:
        """Owner-side store: small -> memory store; large -> local shm."""
        so = (serialization.serialize_error(value) if is_error
              else serialization.serialize(value))
        if so.contained_refs:
            # Durable containment borrows replace the transient serialize-
            # time pins (ObjectRef.__reduce__ fired on_ref_serialized).
            self._register_contained(object_id, so.contained_refs)
            for r in so.contained_refs:
                self.worker.refcounter.on_serialized_ref_done(r.id())
        cfg = config_mod.GlobalConfig
        if so.total_bytes <= cfg.memory_store_threshold_bytes:
            self.worker.memory_store.put(object_id, value, is_error=is_error)
            return
        self._seal_local(object_id, so)
        self.locations[object_id] = self.local_node_id
        self.worker.memory_store.mark_in_shm(object_id)

    def _seal_local(self, object_id: ObjectID, so) -> None:
        try:
            buf = self.store.create_object(object_id.binary(), so.total_bytes)
        except ObjectExists:
            return
        except ObjectStoreFull:
            # arena full even after LRU eviction: overflow to disk
            # (reference: LocalObjectManager::SpillObjects — spilled copies
            # restore on demand; see spill_path/_h_read_object fallbacks)
            self._write_spill(object_id, so.to_bytes())
            self._dir_record(object_id, so.total_bytes, "spilled")
            return
        so.write_to(memoryview(buf).cast("B"))
        self.store.seal(object_id.binary())
        self._dir_record(object_id, so.total_bytes, "primary")

    # ---------------------------------------------------------------- spill

    def _spill_dir(self) -> str:
        from ray_tpu.core.config import GlobalConfig
        return spill_dir_for(GlobalConfig.session_dir, self.store.name)

    def _write_spill(self, object_id: ObjectID, data: bytes) -> None:
        # atomic publish + transient-error retry via the storage seam
        spill_filesystem().put(
            os.path.join(self._spill_dir(), object_id.hex()), data)
        if self._acct:
            self._m_spill_write_total.inc()
            self._m_spill_write_bytes.inc(len(data))
            # arena overflow is a cluster-visible condition: queue a
            # journal event for the next telemetry flush, carrying the
            # ambient trace (if any) so `trace` can cross-link it
            ctx = trace_context.current()
            with self._lock:
                if len(self._journal_pending) < 256:
                    self._journal_pending.append({
                        "type": "spill_overflow",
                        "object_id": object_id.hex(),
                        "bytes": len(data),
                        "node": self.local_node_id,
                        "trace_id": ctx[0] if ctx else ""})

    def _read_spill(self, object_id: ObjectID) -> Optional[bytes]:
        from ray_tpu.core.config import GlobalConfig
        data = read_spill_file(GlobalConfig.session_dir, self.store.name,
                               object_id.hex())
        if data is not None and self._acct:
            self._m_spill_restore_total.inc()
            self._m_spill_restore_bytes.inc(len(data))
        return data

    def store_result_bytes(self, object_id: ObjectID, data: bytes,
                           pin: bool = True, owner: str = "") -> str:
        """Seal pre-serialized bytes into local shm.

        ``pin=True`` keeps the creator pin (primary copy — freed by the
        owner's delete path); ``pin=False`` releases it so the copy is an
        LRU-evictable cache (secondary copies from pulls). ``owner`` is
        the owning worker's hex id for the accounting directory (defaults
        to this process — correct for driver puts, overridden when a
        worker seals a return value owned by the submitter). Returns this
        node's id (reported to the owner as the location).
        """
        try:
            buf = self.store.create_object(object_id.binary(), len(data))
            memoryview(buf).cast("B")[:] = data
            self.store.seal(object_id.binary())
            if not pin:
                self.store.release(object_id.binary())
            self._dir_record(object_id, len(data),
                             "primary" if pin else "secondary", owner)
        except ObjectExists:
            pass
        except ObjectStoreFull:
            if pin:
                # primary copy: overflow to disk; the owner's free path
                # (delete_object -> node handler) unlinks it
                self._write_spill(object_id, data)
                self._dir_record(object_id, len(data), "spilled", owner)
            # secondary (cache) copies are NOT spilled: nothing would ever
            # delete them (owner free only reaches the primary node), so
            # they'd leak until node shutdown — callers fall back to the
            # in-memory bytes for the current read instead
        return self.local_node_id

    # ------------------------------------------------------------ accounting

    #: shm_store.cc kAlign — the arena charges align_up(size, 64) per
    #: object, so directory totals report the arena footprint separately
    #: from raw serialized bytes (`bytes_used` ground truth == arena_bytes)
    _ARENA_ALIGN = 64

    def _dir_record(self, object_id: ObjectID, size: int, role: str,
                    owner: str = "") -> None:
        if not self._acct:
            return
        with self._lock:
            self._dir[object_id] = {
                "size": int(size), "role": role,
                "owner": owner or self.worker.worker_id.hex(),
                "created": time.time()}

    def directory_export(self, limit: int = 200) -> dict:
        """Reconciled directory for the telemetry flush: per-object rows
        (largest first, capped at ``limit``) plus EXACT per-role totals
        over all live entries, so head-side byte/count totals stay exact
        even when rows are truncated.

        Reconciliation happens at report time, not event time: a row
        whose shm copy was LRU-evicted (secondaries) or freed behind our
        back is dropped, and a primary that only survives as a spill file
        is demoted to role=spilled — the head table never shows ghosts.
        """
        if not self._acct:
            return {}
        from ray_tpu.core.config import GlobalConfig
        now = time.time()
        with self._lock:
            items = list(self._dir.items())
        rows: list = []
        dead: list = []
        demoted: list = []
        totals: Dict[str, dict] = {}
        align = self._ARENA_ALIGN - 1
        for oid, e in items:
            role, size = e["role"], e["size"]
            spill = spill_file_path(GlobalConfig.session_dir,
                                    self.store.name, oid.hex())
            if role in ("primary", "secondary") \
                    and not self.store.contains(oid.binary()):
                if role == "primary" and os.path.exists(spill):
                    role = "spilled"
                    demoted.append(oid)
                else:
                    dead.append(oid)
                    continue
            elif role == "spilled" and not os.path.exists(spill):
                dead.append(oid)
                continue
            t = totals.setdefault(role,
                                  {"count": 0, "bytes": 0, "arena_bytes": 0})
            t["count"] += 1
            t["bytes"] += size
            if role != "spilled":
                t["arena_bytes"] += (size + align) & ~align
            rows.append({
                "object_id": oid.hex(), "size": size, "role": role,
                "owner": e["owner"][:12],
                "age_s": round(now - e["created"], 3),
                "pins": self.worker.refcounter.counts_for(oid)})
        if dead or demoted:
            with self._lock:
                for oid in dead:
                    self._dir.pop(oid, None)
                for oid in demoted:
                    if oid in self._dir:
                        self._dir[oid]["role"] = "spilled"
        self._m_primary_count.set(
            totals.get("primary", {}).get("count", 0))
        self._m_secondary_count.set(
            totals.get("secondary", {}).get("count", 0))
        self._m_spilled_count.set(
            totals.get("spilled", {}).get("count", 0))
        rows.sort(key=lambda r: -r["size"])
        if limit and len(rows) > limit:
            rows = rows[:limit]
        return {"dir": rows, "dir_totals": totals}

    def drain_journal(self) -> list:
        """Pending cluster events (spill overflows) for telemetry_push."""
        with self._lock:
            out, self._journal_pending = self._journal_pending, []
        return out

    def _register_contained(self, object_id: ObjectID, refs: list) -> None:
        """An owned object embeds other refs: hold borrows until it's freed
        (reference: ReferenceCounter nested-ref tracking,
        reference_count.h:66)."""
        with self._lock:
            self._contained[object_id] = list(refs)
        me = self.worker.worker_id.binary()
        for r in refs:
            if r.owner_id() == self.worker.worker_id:
                self.worker.refcounter.add_borrower(r.id(), me)
                continue
            try:
                self.owner_client(r.owner_id()).call(
                    "add_borrower", {"object_id": r.id().binary(),
                                     "borrower": me})
            except (RpcError, ObjectLostError):
                pass

    # ------------------------------------------------------------------- get

    def record_remote_location(self, object_id: ObjectID, node_id: str) -> None:
        """Owner learns a return value was sealed on some node's shm."""
        self.locations[object_id] = node_id
        self.worker.memory_store.mark_in_shm(object_id)

    def try_resolve(self, ref: ObjectRef) -> bool:
        if self.worker.memory_store.is_ready(ref.id()):
            return True
        if self.store.contains(ref.id().binary()):
            self.worker.memory_store.mark_in_shm(ref.id())
            return True
        return False

    def poke_resolve(self, ref: ObjectRef) -> None:
        """Start an async fetch loop for a ref we don't own locally."""
        if self.try_resolve(ref):
            return
        if ref.owner_id() == self.worker.worker_id:
            return  # we own it; the result will arrive via the reply path
        with self._lock:
            if ref.id() in self._fetching:
                return
            self._fetching.add(ref.id())
            inflight = len(self._fetching)
        if self._acct:
            self._m_fetch_inflight.set(inflight)
        threading.Thread(target=self._fetch_loop, args=(ref,), daemon=True,
                         name="objplane-fetch").start()

    def _fetch_loop(self, ref: ObjectRef) -> None:
        cfg = config_mod.GlobalConfig
        retry_s = cfg.object_pull_retry_ms / 1000.0
        failures = 0
        try:
            while True:
                if self.try_resolve(ref):
                    return
                try:
                    reply = self.owner_client(ref.owner_id()).call(
                        "get_object",
                        {"object_id": ref.id().binary(),
                         "requester": self.worker.worker_id.binary()})
                    failures = 0
                except (RpcError, ObjectLostError):
                    failures += 1
                    if failures >= cfg.rpc_retry_max_attempts:
                        self.worker.memory_store.put(
                            ref.id(),
                            ObjectLostError(ref.hex(), "owner unreachable"),
                            is_error=True)
                        return
                    time.sleep(retry_s)
                    continue
                if reply is None:
                    self.worker.memory_store.put(
                        ref.id(),
                        ObjectLostError(ref.hex(), "owner dropped the object"),
                        is_error=True)
                    return
                if reply.get("pending"):
                    time.sleep(retry_s)
                    continue
                if "inline" in reply:
                    value = serialization.deserialize(reply["inline"])
                    self.worker.memory_store.put(
                        ref.id(), value, is_error=reply.get("is_error", False))
                    return
                if "shm" in reply:
                    try:
                        oneshot = self._pull_to_local(
                            ref.id(), reply["shm"],
                            sources=reply.get("shm_all"),
                            owner=ref.owner_id().hex())
                    except (RpcError, ObjectLostError) as e:
                        # holder node died mid-pull: surface the loss
                        # instead of killing this thread (a silent death
                        # leaves rt.get() hanging forever)
                        self.worker.memory_store.put(
                            ref.id(),
                            ObjectLostError(ref.hex(), f"pull failed: {e}"),
                            is_error=True)
                        return
                    if oneshot is not None:
                        # local arena full — hand the value over directly
                        self.worker.memory_store.put(
                            ref.id(), serialization.deserialize(oneshot))
                        return
                    self._notify_pulled(ref)
                    self.worker.memory_store.mark_in_shm(ref.id())
                    return
        finally:
            with self._lock:
                self._fetching.discard(ref.id())
                inflight = len(self._fetching)
            if self._acct:
                self._m_fetch_inflight.set(inflight)

    def _pull_to_local(self, object_id: ObjectID, node_id: str,
                       sources: Optional[list] = None,
                       owner: str = "") -> Optional[bytes]:
        """Fetch a sealed object from remote node(s) into the local arena
        (reference pull path: pull_manager.h:53 -> ObjectManager::Push).

        Small objects (<= one chunk) ship in a single read_object frame;
        larger objects stream as pipelined fixed-size chunks, striped
        across every node holding a copy, so a multi-GiB object never
        occupies one RPC frame and a broadcast fans out over all holders.

        The local copy is a *secondary* (cache) copy: the creator pin is
        released right away so LRU eviction can reclaim it; the primary
        stays pinned until the owner frees it. If the local arena is too
        full to cache, the fetched bytes are RETURNED so the caller can
        still serve the current read (no disk spill for secondaries — see
        store_result_bytes)."""
        key = object_id.binary()
        if node_id == self.local_node_id or self.store.contains(key):
            return None
        t0 = time.perf_counter()
        srcs = [node_id] + [s for s in (sources or ())
                            if s != node_id and s != self.local_node_id]
        cfg = config_mod.GlobalConfig
        chunk = max(64 * 1024, cfg.object_transfer_chunk_bytes)
        # size probe from the first reachable holder
        info = None
        for i, src in enumerate(srcs):
            try:
                info = self.node_client(src).call(
                    "object_info", {"object_id": key})
            except (RpcError, ObjectLostError):
                continue
            if info is not None:
                srcs = srcs[i:] + srcs[:i]
                break
        if info is None:
            raise ObjectLostError(object_id.hex(), f"gone from {srcs}")
        if info["size"] <= chunk:
            data = self.node_client(srcs[0]).call_retrying(
                "read_object", {"object_id": key})
            if data is None:
                raise ObjectLostError(object_id.hex(),
                                      f"gone from {srcs[0]}")
            if self._acct:
                self._m_pull_in_bytes.inc(len(data))
                self._m_pull_seconds.observe(time.perf_counter() - t0)
            self.store_result_bytes(object_id, data, pin=False, owner=owner)
            if not self.store.contains(key):
                return data  # cache miss (arena full): one-shot bytes
            return None
        with self._pull_sem:
            out = self._pull_chunked(object_id, info["size"], chunk, srcs,
                                     owner)
        if self._acct:
            self._m_pull_seconds.observe(time.perf_counter() - t0)
        return out

    def _pull_chunked(self, object_id: ObjectID, size: int, chunk: int,
                      sources: list, owner: str = "") -> Optional[bytes]:
        cfg = config_mod.GlobalConfig
        key = object_id.binary()
        cached = False
        try:
            buf = self.store.create_object(key, size)
            dest = memoryview(buf).cast("B")
            cached = True
        except ObjectExists:
            # another local process is mid-pull of the same object: wait
            # for its seal instead of double-fetching
            deadline = time.monotonic() + cfg.rpc_call_timeout_s
            while time.monotonic() < deadline:
                if self.store.contains(key):
                    return None
                time.sleep(0.02)
            raise ObjectLostError(object_id.hex(),
                                  "concurrent local pull never sealed")
        except ObjectStoreFull:
            dest = memoryview(bytearray(size))
        try:
            self._fetch_chunks(object_id, size, chunk, sources, dest)
        except BaseException:
            if cached:
                # unsealed + creator-pin release -> native reclaims the
                # half-written allocation instead of leaking arena space
                self.store.release(key)
            raise
        if cached:
            self.store.seal(key)
            self.store.release(key)  # secondary copy: LRU-evictable
            self._dir_record(object_id, size, "secondary", owner)
            return None
        return bytes(dest)

    def _fetch_chunks(self, object_id: ObjectID, size: int, chunk: int,
                      sources: list, dest) -> None:
        """Sliding-window chunk pipeline, striped round-robin across
        holders (reference: PushManager max-chunks-in-flight windowing,
        push_manager.h:30). A failing holder is dropped from rotation and
        its chunks retried from the survivors."""
        from concurrent.futures import FIRST_COMPLETED, wait
        cfg = config_mod.GlobalConfig
        window = max(1, cfg.object_pull_chunk_inflight)
        key = object_id.binary()
        offsets = list(range(0, size, chunk))
        sources = list(sources)
        inflight: Dict[Any, tuple] = {}
        next_i = 0

        def issue(off: int, attempts: int = 0) -> None:
            if not sources:
                raise ObjectLostError(object_id.hex(),
                                      "every holder lost mid-pull")
            src = sources[(off // chunk) % len(sources)]
            ln = min(chunk, size - off)
            fut = self.node_client(src).call_async(
                "read_chunk",
                {"object_id": key, "offset": off, "length": ln})
            inflight[fut] = (off, ln, src, attempts)

        while next_i < len(offsets) and len(inflight) < window:
            issue(offsets[next_i])
            next_i += 1
        while inflight:
            done, _ = wait(list(inflight), return_when=FIRST_COMPLETED,
                           timeout=cfg.rpc_call_timeout_s)
            if not done:
                # A holder's connection is up but its daemon stopped
                # serving: call_async futures only fail on connection loss,
                # so force-fail every client with in-flight chunks. Their
                # futures resolve with RpcError and flow through the normal
                # failover/retry path below (a healthy source's chunks get
                # re-issued — rare and cheap vs hanging the pull, and the
                # admission permit, forever).
                for src in {v[2] for v in inflight.values()}:
                    addr = self.node_addrs.get(src)
                    if addr is not None:
                        self._peers.invalidate(addr)
                continue
            for fut in done:
                off, ln, src, attempts = inflight.pop(fut)
                exc = fut.exception()
                data = None if exc is not None else fut.result()
                if exc is not None or data is None or len(data) != ln:
                    if len(sources) > 1 and src in sources:
                        sources.remove(src)  # failover to other holders
                    elif attempts >= cfg.rpc_retry_max_attempts:
                        raise ObjectLostError(
                            object_id.hex(),
                            f"chunk @{off} failed from {src}: "
                            f"{exc or 'gone'}")
                    else:
                        time.sleep(cfg.object_pull_retry_ms / 1000.0)
                    issue(off, attempts + 1)
                    continue
                dest[off:off + ln] = data
                if self._acct:
                    self._m_pull_in_bytes.inc(ln)
                if next_i < len(offsets):
                    issue(offsets[next_i])
                    next_i += 1

    def _notify_pulled(self, ref: ObjectRef) -> None:
        """Report our freshly-cached copy to the owner's directory so
        later pulls can stripe across us too."""
        if ref.owner_id() == self.worker.worker_id:
            with self._lock:
                self.secondary.setdefault(ref.id(), set()).add(
                    self.local_node_id)
            return
        try:
            self.owner_client(ref.owner_id()).oneway(
                "add_location", {"object_id": ref.id().binary(),
                                 "node_id": self.local_node_id})
        except Exception:  # noqa: BLE001 — advisory only
            pass

    def get_from_store(self, ref: ObjectRef) -> Tuple[Any, bool]:
        """Blocking read of a sealed object; pulls cross-node if needed.

        The zero-copy view stays pinned until the object is freed locally
        (reference: plasma client pin semantics).
        """
        oid = ref.id()
        if not self.store.contains(oid.binary()):
            node_id = self.locations.get(oid)
            if node_id is None:
                reply = self.owner_client(ref.owner_id()).call(
                    "get_object",
                    {"object_id": oid.binary(),
                     "requester": self.worker.worker_id.binary()})
                if not reply or "shm" not in reply:
                    raise ObjectLostError(oid.hex(), "no longer in shm")
                node_id = reply["shm"]
                sources = reply.get("shm_all")
            else:
                with self._lock:
                    sources = list(self.secondary.get(oid, ()))
            oneshot = self._pull_to_local(oid, node_id, sources=sources,
                                          owner=ref.owner_id().hex())
            if oneshot is not None:
                return serialization.deserialize(oneshot), False
            self._notify_pulled(ref)
        # guard=True: each read holds its own pin, released when the last
        # zero-copy view derived from this get dies — NOT when the
        # ObjectRef dies. Freeing the ref must never let the arena reuse
        # memory still aliased by live numpy views (the corruption class
        # this replaced: free → LRU reuse → a later block's bytes showing
        # through an earlier block's array).
        view = self.store.get(oid.binary(), guard=True)
        if view is None:
            spilled = self._read_spill(oid)
            if spilled is not None:
                return serialization.deserialize(spilled), False
            raise ObjectLostError(oid.hex(), "evicted from shm")
        value = serialization.deserialize(view)
        return value, False

    # -------------------------------------------------- owner service handlers

    def handle_get_object(self, p, ctx):
        oid = ObjectID(p["object_id"])
        entry = self.worker.memory_store.get_if_ready(oid)
        if entry is None:
            # No value yet: either the producing task is still running
            # (refcount still tracks the oid) or we already freed it —
            # answer None so the borrower surfaces ObjectLostError instead
            # of polling forever.
            if not self.worker.refcounter.is_tracked(oid):
                return None
            return {"pending": True}
        value, is_error, in_shm = entry
        if in_shm:
            primary = self.locations.get(oid, self.local_node_id)
            with self._lock:
                alts = [n for n in self.secondary.get(oid, ())
                        if n != primary]
            return {"shm": primary, "shm_all": [primary] + alts}
        so = (serialization.serialize_error(value) if is_error
              else serialization.serialize(value))
        data = so.to_bytes()
        requester = p.get("requester")
        for r in so.contained_refs:
            # transfer-before-release: pre-register the requester as a
            # borrower of refs we own so releasing our serialize-time pin
            # can't race its registration (see worker_main._reply_ok)
            if requester and r.owner_id() == self.worker.worker_id:
                self.worker.refcounter.add_borrower(r.id(), requester)
            self.worker.refcounter.on_serialized_ref_done(r.id())
        return {"inline": data, "is_error": is_error}

    def handle_add_location(self, p, ctx):
        with self._lock:
            self.secondary.setdefault(
                ObjectID(p["object_id"]), set()).add(p["node_id"])
        return True

    def handle_add_borrower(self, p, ctx):
        self.worker.refcounter.add_borrower(
            ObjectID(p["object_id"]), p["borrower"])
        return True

    def handle_remove_borrower(self, p, ctx):
        self.worker.refcounter.remove_borrower(
            ObjectID(p["object_id"]), p["borrower"])
        return True

    # ------------------------------------------------------------------ free

    def free_object(self, object_id: ObjectID) -> None:
        """Owner decided the object is garbage (refcount hit zero).

        Read pins are guard-managed (see get_from_store) so there is
        nothing to release here; the holder node drops the primary copy
        (the store defers actual reclamation until reader pins drain —
        delete_pending in shm_store.cc)."""
        key = object_id.binary()
        node_id = self.locations.pop(object_id, None)
        with self._lock:
            secondaries = self.secondary.pop(object_id, set())
            self._dir.pop(object_id, None)
        secondaries.discard(node_id)
        # Oneway, and never a blocking call on THIS thread: this path runs
        # inside reply callbacks on the transport dispatcher, and
        # node_client's refresh path calls the head. Nodes already in the
        # cached map get their delete directly; nodes that joined after
        # our last refresh (autoscale) are handled by a background thread
        # that refreshes the map first — skipping them would leak their
        # pinned primary copies until the arena fills.
        targets = ([node_id] if node_id is not None else []) \
            + list(secondaries)
        retry = []  # unknown-addr nodes AND definite send failures: a
        # dropped delete leaks the pinned primary in the node's shm arena
        # until restart, so both get one background retry after a refresh.
        for n in targets:
            addr = self.node_addrs.get(n)
            if addr is None or not self._peers.get(addr).oneway(
                    "delete_object", {"object_id": key}):
                retry.append(n)
        if retry:
            self._queue_late_deletes(key, retry)
        with self._lock:
            contained = self._contained.pop(object_id, [])
        me = self.worker.worker_id.binary()
        for r in contained:
            if r.owner_id() == self.worker.worker_id:
                self.worker.refcounter.remove_borrower(r.id(), me)
                continue
            try:
                self.owner_client(r.owner_id()).call(
                    "remove_borrower",
                    {"object_id": r.id().binary(), "borrower": me})
            except (RpcError, ObjectLostError):
                pass

    #: attempts per late delete — a node mid-restart needs a couple of
    #: rounds; a node that never answers is presumed gone (its arena
    #: dies with it, so nothing leaks by giving up)
    _LATE_DELETE_TRIES = 3

    def _queue_late_deletes(self, key: bytes, nodes: list) -> None:
        with self._lock:
            self._late_deletes.extend((n, key, 0) for n in nodes)
            if self._late_thread_live:
                return
            self._late_thread_live = True
        threading.Thread(target=self._drain_late_deletes, daemon=True,
                         name="late-delete").start()

    def _drain_late_deletes(self) -> None:
        try:
            while True:
                time.sleep(0.2)  # coalesce a burst into one refresh
                with self._lock:
                    batch, self._late_deletes = self._late_deletes, []
                    if not batch:
                        self._late_thread_live = False
                        return
                self.refresh_nodes()  # swallows head errors; stale
                # addrs then fail the send below and re-queue
                requeue = []
                for n, key, tries in batch:
                    addr = self.node_addrs.get(n)
                    if addr is None:
                        continue  # node left the cluster: arena is gone
                    if not self._peers.get(addr).oneway(
                            "delete_object", {"object_id": key}) \
                            and tries + 1 < self._LATE_DELETE_TRIES:
                        requeue.append((n, key, tries + 1))
                if requeue:
                    with self._lock:
                        self._late_deletes.extend(requeue)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            with self._lock:
                self._late_thread_live = False

    def release_local_pin(self, object_id: ObjectID) -> None:
        """Borrow-release hook. Read pins are tied to view lifetime by the
        guard in get_from_store, so the unborrow path has nothing to
        release locally; kept as the seam where explicit local pinning
        would go (reference: plasma client Release)."""

    def shutdown(self) -> None:
        self._peers.close_all()
        self.store.close()
