"""Native transport binding: RpcServer/RpcClient over the C++ epoll loop.

Same public surface and wire format as the pure-Python classes in
protocol.py (they interoperate on one cluster), but all socket IO, framing,
and buffering run in libray_tpu_native's event loop (src/transport.cc —
role of the reference's C++ rpc layer, src/ray/rpc/grpc_server.h). One
Python dispatcher thread per process drains inbound messages in batches
(rt_poll returns many events per ctypes call), runs inline handlers and
client completions directly, and hands the rest to each server's pool —
replacing the thread-per-connection + wakeup-per-message model that
dominates small-host profiles.

Dispatcher contract: client completion callbacks (Future.set_result /
call_batch_cb callbacks) run ON the dispatcher thread and must not issue
blocking RPCs — a blocked dispatcher can't process the reply it would be
waiting for. Handlers outside `inline_methods` run on the pool and may
block freely.
"""

from __future__ import annotations

import ctypes
import os
import pickle
import struct
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

from ray_tpu.core import config as config_mod

_REPLY_BIT = 1 << 63
_FAST_BIT = 1 << 62  # binary KV fast-path frame, served inside the C loop

_MSG, _ACCEPT, _DISCONNECT = 1, 2, 3
_POLL_BATCH = 512

# fast-path ops (mirror transport.cc FastOp)
FAST_PUT, FAST_GET, FAST_DEL, FAST_PING = 1, 2, 3, 4
FAST_LEASE_ACQ, FAST_LEASE_REL = 5, 6
_FAST_REQ = struct.Struct("<BBIQ")  # op, flags, klen, vlen
_FAST_REP = struct.Struct("<BQ")    # status, vlen
_U64 = struct.Struct("<Q")


class _RtEvent(ctypes.Structure):
    _fields_ = [
        ("type", ctypes.c_uint8),
        ("conn_id", ctypes.c_uint64),
        ("req_id", ctypes.c_uint64),
        ("len", ctypes.c_uint64),
        ("data", ctypes.c_void_p),
    ]


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.rt_loop_new.restype = ctypes.c_void_p
    lib.rt_loop_free.argtypes = [ctypes.c_void_p]
    lib.rt_listen.restype = ctypes.c_uint64
    lib.rt_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rt_listen_port.restype = ctypes.c_int
    lib.rt_listen_port.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_connect.restype = ctypes.c_uint64
    lib.rt_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rt_send.restype = ctypes.c_int
    lib.rt_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_close_listener.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_poll.restype = ctypes.c_int
    lib.rt_poll.argtypes = [ctypes.c_void_p, ctypes.POINTER(_RtEvent),
                            ctypes.c_int, ctypes.c_int]
    lib.rt_fastpath_enable.restype = ctypes.c_int
    lib.rt_fastpath_enable.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_uint64]
    lib.rt_fastpath_put.restype = ctypes.c_int
    lib.rt_fastpath_put.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
    lib.rt_fastpath_get.restype = ctypes.c_int
    lib.rt_fastpath_get.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastpath_del.restype = ctypes.c_int
    lib.rt_fastpath_del.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_char_p, ctypes.c_uint32]
    lib.rt_fastpath_version.restype = ctypes.c_uint64
    lib.rt_fastpath_version.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rt_fastpath_dump.restype = ctypes.c_int64
    lib.rt_fastpath_dump.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastpath_keys.restype = ctypes.c_int64
    lib.rt_fastpath_keys.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastlease_stock.restype = ctypes.c_int
    lib.rt_fastlease_stock.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint64]
    lib.rt_fastlease_unstock.restype = ctypes.c_int
    lib.rt_fastlease_unstock.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastlease_invalidate.restype = ctypes.c_int
    lib.rt_fastlease_invalidate.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.rt_fastlease_reclaim_conn.restype = ctypes.c_int64
    lib.rt_fastlease_reclaim_conn.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastlease_pooled.restype = ctypes.c_int64
    lib.rt_fastlease_pooled.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastlease_stats.restype = ctypes.c_int
    lib.rt_fastlease_stats.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_fastlease_depth.restype = ctypes.c_int64
    lib.rt_fastlease_depth.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.rt_buf_free.argtypes = [ctypes.c_void_p]
    return lib


class _Transport:
    """Per-process singleton: one C++ loop + one Python dispatcher."""

    _instance: Optional["_Transport"] = None
    _instance_lock = threading.Lock()

    @classmethod
    def get(cls) -> "_Transport":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def __init__(self):
        from ray_tpu._native.build import build as _build
        path = _build()
        # Two bindings over one library: CDLL releases the GIL around every
        # call — mandatory for rt_poll (it sleeps) and for huge sends (big
        # memcpy / possible backpressure wait), but for microsecond calls
        # like rt_send of a small frame the GIL handoff costs ~100x the
        # call itself under thread contention (the caller re-queues for the
        # GIL behind the switch interval). PyDLL keeps the GIL held for
        # those fast paths.
        self.lib = _bind(ctypes.CDLL(path))
        self.fastlib = _bind(ctypes.PyDLL(path))
        self.loop = self.lib.rt_loop_new()
        self._reg_lock = threading.Lock()
        # conn routing: conn_id -> ("client", RpcClient) | ("server", conn)
        self._routes: Dict[int, tuple] = {}
        self._listeners: Dict[int, "RpcServer"] = {}
        self._evbuf = (_RtEvent * _POLL_BATCH)()
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        daemon=True, name="rt-dispatch")
        self._thread.start()

    # -- registration (all under _reg_lock so the dispatcher never sees a
    # conn before its owner is routable) --

    def listen(self, server: "RpcServer", host: str, port: int) -> int:
        # bind + register atomically: the listening fd is live in epoll the
        # moment rt_listen returns, and an accept raced against a separate
        # registration step would be dropped by the dispatcher
        with self._reg_lock:
            listener_id = self.lib.rt_listen(self.loop, host.encode(), port)
            if listener_id:
                self._listeners[listener_id] = server
        return listener_id

    def unregister_listener(self, listener_id: int) -> None:
        with self._reg_lock:
            self._listeners.pop(listener_id, None)

    def connect(self, client: "RpcClient", host: str, port: int) -> int:
        with self._reg_lock:
            conn = self.lib.rt_connect(self.loop, host.encode(), port)
            if conn:
                self._routes[conn] = ("client", client)
        return conn

    def drop_route(self, conn_id: int) -> None:
        with self._reg_lock:
            self._routes.pop(conn_id, None)

    def send(self, conn_id: int, req_id: int, data: bytes) -> int:
        # Small frames stay under the GIL (no handoff tax); bigger ones
        # release it. The cutoff MUST match transport.cc's backpressure
        # exemption (len >= 65536 may block in rt_send): a GIL-holding
        # sender waiting on backpressure would freeze the dispatcher that
        # is the only flusher.
        lib = self.fastlib if len(data) < 65536 else self.lib
        return lib.rt_send(self.loop, conn_id, req_id, data, len(data))

    # -- dispatch --

    def _dispatch_loop(self) -> None:
        lib, loop, evbuf = self.lib, self.loop, self._evbuf
        fast_poll = self.fastlib.rt_poll
        string_at = ctypes.string_at
        while True:
            # opportunistic GIL-held poll first (returns queued events with
            # no GIL handoff); only sleep in the GIL-releasing variant when
            # the queue is actually empty
            n = fast_poll(loop, evbuf, _POLL_BATCH, 0)
            if n == 0:
                n = lib.rt_poll(loop, evbuf, _POLL_BATCH, 200)
            for i in range(n):
                ev = evbuf[i]
                kind = ev.type
                try:
                    if kind == _MSG:
                        route = self._routes.get(ev.conn_id)
                        if route is None:
                            route = self._late_route(ev.conn_id)
                            if route is None:
                                continue
                        payload = string_at(ev.data, ev.len) if ev.len \
                            else b""
                        if route[0] == "client":
                            route[1]._on_reply_frame(ev.req_id, payload)
                        else:
                            route[1].server._on_frame(route[1], ev.req_id,
                                                      payload)
                    elif kind == _ACCEPT:
                        server = self._listeners.get(ev.req_id)
                        if server is None:
                            server = self._late_listener(ev.req_id)
                            if server is None:
                                self.lib.rt_close_conn(self.loop, ev.conn_id)
                                continue
                        peer = string_at(ev.data, ev.len).decode(
                            "utf-8", "replace")
                        conn = _ServerConn(server, ev.conn_id, peer)
                        with self._reg_lock:
                            self._routes[ev.conn_id] = ("server", conn)
                        server._conns[ev.conn_id] = conn
                    elif kind == _DISCONNECT:
                        with self._reg_lock:
                            route = self._routes.pop(ev.conn_id, None)
                        if route is None:
                            route = (None,)
                        if route[0] == "client":
                            route[1]._on_disconnect()
                        elif route[0] == "server":
                            route[1].server._on_conn_closed(route[1])
                except Exception:  # noqa: BLE001 — dispatcher must survive
                    import traceback
                    traceback.print_exc()

    def _late_route(self, conn_id: int) -> Optional[tuple]:
        # a frame can race the registration done right after rt_connect;
        # taking the lock guarantees any in-flight registration completed
        with self._reg_lock:
            return self._routes.get(conn_id)

    def _late_listener(self, listener_id: int) -> Optional["RpcServer"]:
        with self._reg_lock:
            return self._listeners.get(listener_id)


# ---------------------------------------------------------------------------
# server


class HandlerContext:
    """Passed to every handler; allows deferred replies and peer identity."""

    __slots__ = ("_conn", "_req_id", "peer", "replied", "slot_ids")

    def __init__(self, conn: "_ServerConn", req_id: int):
        self._conn = conn
        self._req_id = req_id
        self.peer = conn.peer
        self.replied = False
        # combined frames with pre-allocated per-slot reply ids (eager
        # per-task replies — see call_combined_cb); None on plain requests
        self.slot_ids = None

    def reply(self, value: Any = None,
              error: Optional[BaseException] = None) -> None:
        if self.replied:
            return
        self.replied = True
        self._conn.send_reply(self._req_id, value, error)

    def reply_to(self, req_id: int, value: Any = None,
                 error: Optional[BaseException] = None) -> None:
        """Reply to one pre-allocated slot id of a combined frame (the
        caller registered a pending entry per slot). Unlike reply(),
        callable many times — once per distinct slot."""
        self._conn.send_reply(req_id, value, error)


class _ServerConn:
    __slots__ = ("server", "conn_id", "peer", "alive")

    def __init__(self, server: "RpcServer", conn_id: int, peer: str):
        self.server = server
        self.conn_id = conn_id
        self.peer = peer
        self.alive = True

    def send_reply(self, req_id: int, value: Any,
                   error: Optional[BaseException]) -> None:
        if req_id == 0:  # oneway — no reply expected
            return
        from ray_tpu.runtime.protocol import RpcError
        try:
            payload = pickle.dumps((value, error), protocol=5)
        except Exception as e:  # unpicklable result
            payload = pickle.dumps(
                (None, RpcError(f"unpicklable reply: {e!r}")), protocol=5)
        t = self.server._transport
        t.send(self.conn_id, req_id | _REPLY_BIT, payload)

    def close(self) -> None:
        self.alive = False
        t = self.server._transport
        t.drop_route(self.conn_id)
        t.lib.rt_close_conn(t.loop, self.conn_id)


class RpcServer:
    """Native-transport RPC server (API-compatible with protocol.PyRpcServer).

    Handlers: dict method -> fn(payload, ctx). A handler returns a value
    (replied immediately), raises (error reply), or returns DEFERRED and
    calls ctx.reply() later from any thread. `inline_methods` run on the
    dispatcher thread in per-connection arrival order.
    """

    def __init__(self, handlers: Dict[str, Callable[[Any, Any], Any]],
                 host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, name: str = "rpc",
                 inline_methods: Optional[set] = None):
        self.handlers = dict(handlers)
        self.inline_methods = set(inline_methods or ())
        self._transport = _Transport.get()
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix=f"{name}-h")
        self._conns: Dict[int, _ServerConn] = {}
        self._stopped = False
        self.on_disconnect: Optional[Callable[[Any], None]] = None
        # (conn_id, peer) variant — the head uses conn_id to reclaim
        # native-fastpath lease grants held by the dropped connection
        self.on_disconnect_conn: Optional[Callable[[int, Any], None]] = None
        self._listener = self._transport.listen(self, host, port)
        if not self._listener:
            raise OSError(f"cannot listen on {host}:{port}")
        self.host = host
        self.port = self._transport.lib.rt_listen_port(
            self._transport.loop, self._listener)
        self.address = f"{self.host}:{self.port}"

    # -- dispatcher entry points --

    def _on_frame(self, conn: _ServerConn, req_id: int,
                  payload: bytes) -> None:
        from ray_tpu.runtime.protocol import RpcError
        try:
            msg = pickle.loads(payload)
        except BaseException as e:  # noqa: BLE001
            HandlerContext(conn, req_id).reply(
                None, error=RpcError(f"bad request: {e!r}"))
            return
        method = msg[0]
        if method == "__batch__":
            # batched frame: [(req_id, method, body), ...] — dispatch each
            # as an individual request (replies flow per inner id and are
            # re-coalesced by the C++ writer)
            for rid, m, body in msg[1]:
                self._dispatch_one(conn, rid, m, body)
            return
        # (method, body) or (method, body, slot_ids) — the 3rd element
        # carries pre-allocated per-slot reply ids of an eager combined
        # call; old 2-tuple frames stay accepted
        slot_ids = list(msg[2]) if len(msg) > 2 and msg[2] else None
        self._dispatch_one(conn, req_id, method, msg[1], slot_ids)

    def _dispatch_one(self, conn: _ServerConn, req_id: int, method: str,
                      body: Any, slot_ids=None) -> None:
        if method in self.inline_methods:
            self._run_handler(conn, req_id, method, body, slot_ids)
        else:
            self._pool.submit(self._run_handler, conn, req_id, method, body,
                              slot_ids)

    def _run_handler(self, conn: _ServerConn, req_id: int, method: str,
                     body: Any, slot_ids=None) -> None:
        from ray_tpu.runtime.protocol import DEFERRED, RpcError
        ctx = HandlerContext(conn, req_id)
        ctx.slot_ids = slot_ids
        try:
            handler = self.handlers.get(method)
            if handler is None:
                raise RpcError(f"no handler for {method!r}")
            result = handler(body, ctx)
            if result is DEFERRED:
                return
            ctx.reply(result)
        except BaseException as e:  # noqa: BLE001
            ctx.reply(None, error=e)

    # -- native KV fast-path (host-side access) --

    def enable_kv_fastpath(self, incarnation: int = 0) -> bool:
        """Serve FAST_* frames on this listener entirely inside the C
        loop. The host process reads/writes the SAME table via the
        kv_fast_* methods below (role of the reference's C++
        GcsInternalKVManager with Python-side accessors)."""
        t = self._transport
        return t.lib.rt_fastpath_enable(t.loop, self._listener,
                                        incarnation) == 0

    def kv_fast_put(self, key: bytes, val: bytes,
                    overwrite: bool = True) -> bool:
        t = self._transport
        rc = t.fastlib.rt_fastpath_put(t.loop, self._listener, key,
                                       len(key), val, len(val),
                                       1 if overwrite else 0)
        return rc == 1  # newly created

    def kv_fast_get(self, key: bytes) -> Optional[bytes]:
        t = self._transport
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        rc = t.fastlib.rt_fastpath_get(t.loop, self._listener, key,
                                       len(key), ctypes.byref(out),
                                       ctypes.byref(out_len))
        if rc != 1:
            return None
        try:
            return ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)

    def kv_fast_del(self, key: bytes) -> bool:
        t = self._transport
        return t.fastlib.rt_fastpath_del(t.loop, self._listener, key,
                                         len(key)) == 1

    def kv_fast_keys(self, prefix: bytes = b"") -> list:
        """Keys matching prefix — filtered C-side so values (possibly
        megabytes of export blobs) never cross the boundary."""
        t = self._transport
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        n = t.fastlib.rt_fastpath_keys(t.loop, self._listener, prefix,
                                       len(prefix), ctypes.byref(out),
                                       ctypes.byref(out_len))
        if n < 0:
            return []
        try:
            buf = ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)
        keys = []
        off = 0
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            keys.append(buf[off:off + klen])
            off += klen
        return keys

    def kv_fast_version(self) -> int:
        t = self._transport
        return t.fastlib.rt_fastpath_version(t.loop, self._listener)

    def kv_fast_items(self) -> Dict[bytes, bytes]:
        t = self._transport
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        n = t.lib.rt_fastpath_dump(t.loop, self._listener,
                                   ctypes.byref(out), ctypes.byref(out_len))
        if n < 0:
            return {}
        try:
            buf = ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)
        items: Dict[bytes, bytes] = {}
        off = 0
        for _ in range(n):
            (klen,) = struct.unpack_from("<I", buf, off)
            off += 4
            key = buf[off:off + klen]
            off += klen
            (vlen,) = struct.unpack_from("<Q", buf, off)
            off += 8
            items[key] = buf[off:off + vlen]
            off += vlen
        return items

    # -- native lease pool (host-side policy access; served peer-side by
    # FOP_LEASE_ACQ/REL inside the C loop — see transport.cc FastLease) --

    def lease_stock(self, sig: int, lease_key: int, grant: bytes) -> bool:
        t = self._transport
        return t.fastlib.rt_fastlease_stock(
            t.loop, self._listener, sig, lease_key, grant, len(grant)) == 0

    def lease_unstock(self, sig: int) -> Optional[tuple]:
        """Pop one pooled grant: (lease_key, grant_bytes) or None."""
        t = self._transport
        out_key = ctypes.c_uint64()
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        rc = t.fastlib.rt_fastlease_unstock(
            t.loop, self._listener, sig, ctypes.byref(out_key),
            ctypes.byref(out), ctypes.byref(out_len))
        if rc != 1:
            return None
        try:
            return out_key.value, ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)

    def lease_invalidate(self, lease_key: int) -> int:
        """2 = was held, 1 = was pooled, 0 = unknown, -1 = no fastpath."""
        t = self._transport
        return t.fastlib.rt_fastlease_invalidate(t.loop, self._listener,
                                                 lease_key)

    def lease_reclaim_conn(self, conn_id: int) -> list:
        """All grants held by a disconnected conn: [(lease_key, sig,
        grant_bytes)], removed from the C-side table."""
        t = self._transport
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        n = t.fastlib.rt_fastlease_reclaim_conn(
            t.loop, self._listener, conn_id, ctypes.byref(out),
            ctypes.byref(out_len))
        if n <= 0:
            if n > -1 and out.value:
                t.fastlib.rt_buf_free(out)
            return []
        try:
            buf = ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)
        items = []
        off = 0
        for _ in range(n):
            lkey, sig, blen = struct.unpack_from("<QQQ", buf, off)
            off += 24
            items.append((lkey, sig, buf[off:off + blen]))
            off += blen
        return items

    def lease_pooled_keys(self) -> list:
        """Lease keys currently POOLED (grantable, un-held) — their
        resources are reclaimable in one drain and therefore reported as
        available by the head."""
        t = self._transport
        out = ctypes.c_void_p()
        out_len = ctypes.c_uint64()
        n = t.fastlib.rt_fastlease_pooled(
            t.loop, self._listener, ctypes.byref(out),
            ctypes.byref(out_len))
        if n <= 0:
            if n > -1 and out.value:
                t.fastlib.rt_buf_free(out)
            return []
        try:
            buf = ctypes.string_at(out.value, out_len.value)
        finally:
            t.fastlib.rt_buf_free(out)
        keys = []
        for off in range(0, len(buf), 16):
            _sig, lkey = struct.unpack_from("<QQ", buf, off)
            keys.append(lkey)
        return keys

    def lease_stats(self) -> Optional[dict]:
        t = self._transport
        out = (ctypes.c_uint64 * 4)()
        if t.fastlib.rt_fastlease_stats(t.loop, self._listener, out) != 0:
            return None
        return {"hits": out[0], "misses": out[1], "pooled": out[2],
                "held": out[3]}

    def lease_depth(self, sig: int) -> int:
        t = self._transport
        return max(0, t.fastlib.rt_fastlease_depth(t.loop, self._listener,
                                                   sig))

    def _on_conn_closed(self, conn: _ServerConn) -> None:
        conn.alive = False
        self._conns.pop(conn.conn_id, None)
        if not self._stopped and self.on_disconnect_conn is not None:
            try:
                self.on_disconnect_conn(conn.conn_id, conn.peer)
            except Exception:  # noqa: BLE001
                pass
        if self.on_disconnect is not None and not self._stopped:
            try:
                self.on_disconnect(conn.peer)
            except Exception:  # noqa: BLE001
                pass

    def stop(self) -> None:
        self._stopped = True
        self._transport.unregister_listener(self._listener)
        self._transport.lib.rt_close_listener(self._transport.loop,
                                              self._listener)
        for conn in list(self._conns.values()):
            conn.close()
        self._conns.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)


# ---------------------------------------------------------------------------
# client


class RpcClient:
    """Native-transport client (API-compatible with protocol.PyRpcClient).

    Many calls pipeline over one connection; completions are resolved by
    the process-wide dispatcher thread. call_batch_cb() sends many requests
    in ONE frame (one pickle, one send) with per-request completion
    callbacks — the task submitters' hot path.
    """

    def __init__(self, address: str, name: str = "client"):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._name = name
        self._transport = _Transport.get()
        self._conn: Optional[int] = None
        self._conn_lock = threading.Lock()
        self._pending: Dict[int, Any] = {}  # req_id -> Future | callback
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._closed = False

    # -- connection management --

    def _connect(self) -> int:
        from ray_tpu.runtime.protocol import RpcError
        with self._conn_lock:
            if self._conn is not None:
                return self._conn
            if self._closed:
                raise RpcError("client closed")
            conn = self._transport.connect(self, self._host, self._port)
            if not conn:
                raise RpcError(f"cannot resolve {self.address}")
            self._conn = conn
            return conn

    def _on_disconnect(self) -> None:
        from ray_tpu.runtime.protocol import RpcError
        self._fail_all(RpcError(f"connection to {self.address} lost"))

    def _fail_all(self, exc: Exception) -> None:
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            self._transport.drop_route(conn)
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for entry in pending:
            self._complete(entry, None, exc)

    # -- completion plumbing (dispatcher thread) --

    @staticmethod
    def _complete(entry: Any, value: Any, error: Optional[BaseException]
                  ) -> None:
        if isinstance(entry, Future):
            if entry.done():
                return
            if error is not None:
                entry.set_exception(error)
            else:
                entry.set_result(value)
        else:
            try:
                entry(value, error)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()

    def _on_reply_frame(self, req_id: int, payload: bytes) -> None:
        from ray_tpu.runtime.protocol import RpcError
        req_id &= ~_REPLY_BIT
        fast = bool(req_id & _FAST_BIT)
        req_id &= ~_FAST_BIT
        with self._pending_lock:
            entry = self._pending.pop(req_id, None)
        if entry is None:
            return
        if fast:
            # binary fast-path reply: (status, value bytes). A peer that
            # answered via the Python path instead (conn accepted before
            # rt_fastpath_enable, or head restarted without the fastpath)
            # sends a pickled tuple here — its first byte (0x80) is not a
            # valid status, so validate the frame shape and surface a
            # transport error rather than returning garbage as a KV miss.
            ok = len(payload) >= _FAST_REP.size
            if ok:
                status, vlen = _FAST_REP.unpack_from(payload)
                ok = status in (0, 1) and vlen == len(payload) - _FAST_REP.size
            if not ok:
                from ray_tpu.runtime.protocol import FastPathUnavailable
                self._complete(entry, None, FastPathUnavailable(
                    "fast-path reply malformed (peer likely served the "
                    "Python path); use the pickle path"))
                return
            self._complete(entry, (status, payload[_FAST_REP.size:]), None)
            return
        try:
            value, error = pickle.loads(payload)
        except BaseException as e:  # noqa: BLE001
            self._complete(entry, None, RpcError(f"bad reply: {e!r}"))
            return
        self._complete(entry, value, error)

    # -- calls --

    def _alloc_id(self) -> int:
        with self._id_lock:
            self._next_id += 1
            return self._next_id

    def _send(self, conn: int, req_id: int, data: bytes) -> bool:
        return self._transport.send(conn, req_id, data) == 0

    def call_async(self, method: str, payload: Any = None) -> Future:
        from ray_tpu.runtime.protocol import (ChaosInjectedError, RpcError,
                                              _chaos_should_fail)
        fut: Future = Future()
        if _chaos_should_fail(method):
            fut.set_exception(ChaosInjectedError(f"chaos: {method}"))
            return fut
        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        req_id = self._alloc_id()
        fut._rtpu_req_id = req_id  # lets call() reap on timeout
        with self._pending_lock:
            self._pending[req_id] = fut
        try:
            conn = self._connect()
            data = pickle.dumps((method, payload), protocol=5)
            if not self._send(conn, req_id, data):
                raise RpcError(f"connection to {self.address} lost")
        except BaseException as e:  # noqa: BLE001
            with self._pending_lock:
                self._pending.pop(req_id, None)
            if not fut.done():
                fut.set_exception(
                    e if isinstance(e, RpcError) else RpcError(repr(e)))
        return fut

    def call_combined_cb(self, method: str, payloads: list,
                         callback: Callable[
                             [int, Any, Optional[BaseException]], None]
                         ) -> None:
        """Send N sub-payloads as ONE request frame, with a pre-allocated
        reply id per slot shipped alongside (3rd frame element). An eager
        peer replies per slot the moment that slot finishes — so a slot
        whose result a batchmate depends on is never withheld behind
        unfinished batchmates — then closes with _COMBINED_DONE on the
        main id. A peer that instead replies once with a list of N
        (value, error) pairs (old single-reply servers, plain handlers)
        is equally accepted. Either way callback(i, value, error) fires
        exactly once per slot, on the dispatcher thread (must not block).
        On transport failure every not-yet-fired callback fires with the
        error, same contract as call_batch_cb."""
        from ray_tpu.runtime.protocol import (ChaosInjectedError,
                                              RpcError, _COMBINED_DONE,
                                              _chaos_should_fail)
        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        n = len(payloads)
        lock = threading.Lock()
        done = [False] * n

        def fire(i, value, error):
            with lock:
                if done[i]:
                    return
                done[i] = True
            callback(i, value, error)

        slot_ids = [self._alloc_id() for _ in range(n)]
        req_id = self._alloc_id()

        def fanout(value, error):
            # main-request reply: drop the slot entries first so a peer
            # that answered with one combined list (or an error) doesn't
            # leak N pending entries
            with self._pending_lock:
                for rid in slot_ids:
                    self._pending.pop(rid, None)
            if error is None:
                if isinstance(value, list) and len(value) == n:
                    for i, (v, e) in enumerate(value):
                        fire(i, v, e)
                    return
                if value == _COMBINED_DONE:
                    # all slots should have their own replies by now (the
                    # marker is sent last on the same ordered connection);
                    # any still-unfired slot means the peer lost one
                    error = RpcError(
                        f"combined call {method}: peer finished without "
                        f"replying to every slot")
                else:
                    error = RpcError(
                        f"malformed combined reply for {method}: "
                        f"expected list of {n}, got {type(value).__name__}")
            for i in range(n):
                fire(i, None, error)

        with self._pending_lock:
            for i, rid in enumerate(slot_ids):
                self._pending[rid] = (lambda v, e, i=i: fire(i, v, e))
            self._pending[req_id] = fanout
        try:
            if _chaos_should_fail(method):
                raise ChaosInjectedError(f"chaos: {method}")
            conn = self._connect()
            data = pickle.dumps((method, payloads, slot_ids), protocol=5)
            if not self._send(conn, req_id, data):
                raise RpcError(f"connection to {self.address} lost")
        except BaseException as e:  # noqa: BLE001
            with self._pending_lock:
                entry = self._pending.pop(req_id, None)
                for rid in slot_ids:
                    self._pending.pop(rid, None)
            if entry is not None:
                err = e if isinstance(e, RpcError) else RpcError(repr(e))
                for i in range(n):
                    fire(i, None, err)

    def call_batch_cb(self, method: str, payloads: list,
                      callback: Callable[[int, Any, Optional[BaseException]],
                                         None]) -> list:
        """Send many requests of one method in a single frame.

        callback(index, value, error) fires once per request, on the
        dispatcher thread (must not block). Returns the request ids.
        On transport failure, every not-yet-completed request's callback
        fires with the error.
        """
        from ray_tpu.runtime.protocol import (ChaosInjectedError, RpcError,
                                              _chaos_should_fail)
        cfg = config_mod.GlobalConfig
        if cfg.testing_rpc_delay_ms:
            time.sleep(cfg.testing_rpc_delay_ms / 1000.0)
        items = []
        ids = []
        with self._pending_lock:
            for i, p in enumerate(payloads):
                req_id = self._alloc_id()
                ids.append(req_id)
                self._pending[req_id] = \
                    (lambda v, e, i=i: callback(i, v, e))
                items.append((req_id, method, p))
        chaos_fail = _chaos_should_fail(method)
        try:
            if chaos_fail:
                raise ChaosInjectedError(f"chaos: {method}")
            conn = self._connect()
            data = pickle.dumps(("__batch__", items), protocol=5)
            if not self._send(conn, 0, data):
                raise RpcError(f"connection to {self.address} lost")
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, RpcError) else RpcError(repr(e))
            with self._pending_lock:
                entries = [self._pending.pop(rid, None) for rid in ids]
            for entry in entries:
                if entry is not None:
                    self._complete(entry, None, err)
        return ids

    def call_fast(self, op: int, key: bytes = b"", val: bytes = b"",
                  flags: int = 0,
                  timeout: Optional[float] = None) -> tuple:
        """Binary KV fast-path call, served inside the peer's C loop
        (no Python on the server). Returns (status, value_bytes).
        Only valid against a server with the fastpath enabled."""
        from ray_tpu.runtime.protocol import RpcError
        if timeout is None:
            timeout = config_mod.GlobalConfig.rpc_call_timeout_s
        fut: Future = Future()
        req_id = self._alloc_id()
        with self._pending_lock:
            self._pending[req_id] = fut
        data = _FAST_REQ.pack(op, flags, len(key), len(val)) + key + val
        try:
            conn = self._connect()
            if self._transport.send(conn, req_id | _FAST_BIT, data) != 0:
                raise RpcError(f"connection to {self.address} lost")
        except BaseException as e:  # noqa: BLE001
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise e if isinstance(e, RpcError) else RpcError(repr(e))
        try:
            return fut.result(timeout=timeout)
        except TimeoutError:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise RpcError(f"fast call to {self.address} timed out "
                           f"after {timeout}s") from None

    def call(self, method: str, payload: Any = None,
             timeout: Optional[float] = None) -> Any:
        from ray_tpu.runtime.protocol import RpcError
        cfg = config_mod.GlobalConfig
        if timeout is None:
            timeout = cfg.rpc_call_timeout_s
        fut = self.call_async(method, payload)
        try:
            return fut.result(timeout=timeout)
        except TimeoutError:
            req_id = getattr(fut, "_rtpu_req_id", None)
            if req_id is not None:
                with self._pending_lock:
                    self._pending.pop(req_id, None)
            raise RpcError(f"call {method} to {self.address} timed out "
                           f"after {timeout}s") from None

    def call_retrying(self, method: str, payload: Any = None,
                      timeout: Optional[float] = None) -> Any:
        from ray_tpu.runtime.protocol import RpcError
        cfg = config_mod.GlobalConfig
        attempts = max(1, cfg.rpc_retry_max_attempts)
        delay = cfg.rpc_retry_base_ms / 1000.0
        last: Optional[Exception] = None
        for i in range(attempts):
            try:
                return self.call(method, payload, timeout=timeout)
            except RpcError as e:
                last = e
                if i + 1 < attempts:
                    time.sleep(delay)
                    delay = min(delay * 2, 5.0)
        raise last  # type: ignore[misc]

    def oneway(self, method: str, payload: Any = None) -> bool:
        """Fire-and-forget. Returns True if the frame was handed to the
        transport (rt_send accepted it); False on a definite send failure
        so cleanup-critical callers (object deletes) can retry."""
        from ray_tpu.runtime.protocol import _chaos_should_fail
        if _chaos_should_fail(method):
            return True
        try:
            conn = self._connect()
            data = pickle.dumps((method, payload), protocol=5)
            return self._send(conn, 0, data)
        except BaseException:  # noqa: BLE001
            return False

    def close(self) -> None:
        from ray_tpu.runtime.protocol import RpcError
        self._closed = True
        with self._conn_lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            self._transport.drop_route(conn)
            self._transport.lib.rt_close_conn(self._transport.loop, conn)
        self._fail_all(RpcError("client closed"))
