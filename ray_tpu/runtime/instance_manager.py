"""Instance lifecycle state machine for autoscaler-owned nodes.

Role-equivalent to the reference's autoscaler-v2 InstanceManager
(reference: autoscaler/v2/instance_manager/instance_manager.py:29 +
instance_storage.py — every cloud launch becomes a declarative Instance
record whose transitions are versioned storage writes): a provider launch
is no longer a bare handle in a process-local list but an
``InstanceRecord`` that moves through

    REQUESTED -> ALLOCATED -> RUNNING -> DRAINING -> TERMINATED
         |           |            `----------------> DEAD
         |           `-> LAUNCH_FAILED
         `-> RUNNING (crash-window adoption: node registered while down)

with every transition (a) validated against the allowed-transition map,
(b) persisted through the head's KV table — which rides the head's
existing snapshot/restore path, so records survive BOTH autoscaler and
head restarts — and (c) journaled into the head's ClusterEventJournal
under the record's trace id, one id per instance, so
``python -m ray_tpu events --follow`` replays a whole launch/drain storm
and `trace` can join it.

Crash consistency is write-ahead: the REQUESTED record (carrying the
node identity the daemon will register under) is persisted BEFORE the
provider call, and the provider's own ledger (LocalNodeProvider's ledger
file; a cloud provider's instance-list API) closes the residual window
between "provider created" and "ALLOCATED persisted". ``reconcile``
replays that state against the head's live node table after a restart:
records whose node registered while the manager was down are re-adopted
into RUNNING; REQUESTED/ALLOCATED records past the orphan grace whose
node never registered are terminated through the provider so no handle
is ever leaked — SIGKILLing the autoscaler between ``create_node`` and
node registration must converge to zero orphans (tier-1 asserted).

This module must stay importable WITHOUT jax (same contract as
llm/request_log.py): it runs inside the autoscaler daemon and the tier-1
CPU sweep with no accelerator stack at all.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("ray_tpu.instance_manager")

# ------------------------------------------------------------------ states

REQUESTED = "REQUESTED"          # record persisted; provider call imminent
ALLOCATED = "ALLOCATED"          # provider created (handle/metadata known)
RUNNING = "RUNNING"              # node daemon registered with the head
DRAINING = "DRAINING"            # scale-down victim: leaving, not yet gone
TERMINATED = "TERMINATED"        # released through the provider (terminal)
LAUNCH_FAILED = "LAUNCH_FAILED"  # died before ever registering (terminal)
DEAD = "DEAD"                    # died after RUNNING without a drain (terminal)

TERMINAL_STATES = frozenset({TERMINATED, LAUNCH_FAILED, DEAD})

#: the declarative transition map — anything not listed is a bug, not a
#: race (reference: instance_manager.py's get_transition checks). Every
#: live state may terminate (crash-reconcile can orphan-kill from any of
#: them) and REQUESTED may fail before a handle exists (provider raised).
_ALLOWED: Dict[str, frozenset] = {
    # REQUESTED -> RUNNING is the crash-window adoption: the ALLOCATED
    # persist never landed but the node registered anyway
    REQUESTED: frozenset({ALLOCATED, RUNNING, LAUNCH_FAILED, TERMINATED}),
    ALLOCATED: frozenset({RUNNING, LAUNCH_FAILED, TERMINATED}),
    RUNNING: frozenset({DRAINING, DEAD, TERMINATED}),
    DRAINING: frozenset({TERMINATED, DEAD}),
    TERMINATED: frozenset(),
    LAUNCH_FAILED: frozenset(),
    DEAD: frozenset(),
}

#: journal event type per entered state (the REQUESTED event is emitted
#: by ``request()``); kept 1:1 so a journal dump filtered by trace_id IS
#: the instance's transition history.
_EVENT_BY_STATE = {
    ALLOCATED: "instance_allocated",
    RUNNING: "instance_running",
    DRAINING: "instance_draining",
    TERMINATED: "instance_terminated",
    LAUNCH_FAILED: "node_launch_failed",
    DEAD: "instance_dead",
}


class InvalidTransition(RuntimeError):
    """A transition outside the allowed map — state-machine corruption."""


class InstanceRecord:
    """One autoscaler-owned instance. ``node_id`` doubles as the instance
    id: it is the identity the launched daemon registers under, chosen
    BEFORE the provider call so a crash between create and persist can
    still be reconciled by identity."""

    __slots__ = ("node_id", "node_type", "resources", "state", "trace_id",
                 "metadata", "created_wall", "updated_wall", "history",
                 "handle")

    def __init__(self, node_id: str, node_type: str,
                 resources: Dict[str, float], trace_id: str,
                 state: str = REQUESTED):
        self.node_id = node_id
        self.node_type = node_type
        self.resources = dict(resources)
        self.state = state
        self.trace_id = trace_id
        self.metadata: Dict[str, Any] = {}   # provider-side (pid, name...)
        self.created_wall = time.time()
        self.updated_wall = self.created_wall
        self.history: List[Tuple[str, float]] = [(state, self.created_wall)]
        # in-memory only (a Popen / _SliceHandle): lost across restarts —
        # the provider ledger + metadata stand in for it after one
        self.handle: Any = None

    @property
    def live(self) -> bool:
        return self.state not in TERMINAL_STATES

    @property
    def age_s(self) -> float:
        return time.time() - self.created_wall

    def to_dict(self) -> dict:
        """Persisted wire form (plain JSON-able types; no handle)."""
        return {"node_id": self.node_id, "node_type": self.node_type,
                "resources": dict(self.resources), "state": self.state,
                "trace_id": self.trace_id, "metadata": dict(self.metadata),
                "created_wall": self.created_wall,
                "updated_wall": self.updated_wall,
                "history": [[s, ts] for s, ts in self.history]}

    @classmethod
    def from_dict(cls, d: dict) -> "InstanceRecord":
        rec = cls(d["node_id"], d["node_type"], d.get("resources") or {},
                  d.get("trace_id", ""), state=d.get("state", REQUESTED))
        rec.metadata = dict(d.get("metadata") or {})
        rec.created_wall = float(d.get("created_wall", rec.created_wall))
        rec.updated_wall = float(d.get("updated_wall", rec.created_wall))
        rec.history = [(s, float(ts)) for s, ts in d.get("history") or
                       [[rec.state, rec.created_wall]]]
        return rec


# ------------------------------------------------------------------- stores

#: KV key prefix the persisted records live under — inside the head's KV
#: table, which the head's snapshot/restore path already makes durable
KV_PREFIX = "__rtpu/instance/"


class MemoryInstanceStore:
    """Dict-backed store for unit tests (same contract as the KV store)."""

    def __init__(self):
        self._d: Dict[str, dict] = {}

    def put(self, node_id: str, rec: dict) -> None:
        self._d[node_id] = dict(rec)

    def delete(self, node_id: str) -> None:
        self._d.pop(node_id, None)

    def load_all(self) -> Dict[str, dict]:
        return {k: dict(v) for k, v in self._d.items()}


class KvInstanceStore:
    """Records persisted through the head's KV table (kv_put / kv_get /
    kv_keys RPCs) — the head's existing persistence path; a head restart
    restores them from its snapshot, an autoscaler restart re-reads them
    over RPC. Store failures raise: a transition that could not be made
    durable must not be treated as committed."""

    def __init__(self, head_client):
        self.head = head_client

    def put(self, node_id: str, rec: dict) -> None:
        from ray_tpu.util.fault_injector import fire
        fire("instance_store.put")
        self.head.call("kv_put", {"key": KV_PREFIX + node_id,
                                  "value": rec, "overwrite": True},
                       timeout=10)

    def delete(self, node_id: str) -> None:
        self.head.call("kv_del", {"key": KV_PREFIX + node_id}, timeout=10)

    def load_all(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for key in self.head.call("kv_keys", {"prefix": KV_PREFIX},
                                  timeout=10) or []:
            rec = self.head.call("kv_get", {"key": key}, timeout=10)
            if isinstance(rec, dict) and rec.get("node_id"):
                out[rec["node_id"]] = rec
        return out


# ------------------------------------------------------------------ manager

class InstanceManager:
    """Owns the record table and enforces persist-then-journal on every
    transition. ``journal(event_type, trace_id, **fields)`` is injected
    (the autoscaler routes it to the head's journal_record RPC); it is
    best-effort — journaling must never block a state change that is
    already durable."""

    def __init__(self, store, journal: Optional[Callable[..., Any]] = None):
        self.store = store
        self._journal = journal or (lambda *_a, **_k: None)
        self._lock = threading.Lock()
        self._records: Dict[str, InstanceRecord] = {}

    # ------------------------------------------------------------- access

    def get(self, node_id: str) -> Optional[InstanceRecord]:
        with self._lock:
            return self._records.get(node_id)

    def records(self, *states: str) -> List[InstanceRecord]:
        """Records in any of ``states`` (all records when none given)."""
        with self._lock:
            recs = list(self._records.values())
        if states:
            recs = [r for r in recs if r.state in states]
        return recs

    def live_counts(self) -> Dict[str, int]:
        """Per-type count of instances holding (or about to hold)
        capacity: REQUESTED/ALLOCATED/RUNNING. DRAINING is excluded — a
        draining node is on its way out and must not block a scale-up."""
        counts: Dict[str, int] = {}
        for rec in self.records(REQUESTED, ALLOCATED, RUNNING):
            counts[rec.node_type] = counts.get(rec.node_type, 0) + 1
        return counts

    # -------------------------------------------------------- transitions

    def request(self, node_type: str, resources: Dict[str, float],
                node_id: str, trace_id: str = "") -> InstanceRecord:
        """Write-ahead REQUESTED record: persisted (and journaled) BEFORE
        the provider call, so a crash mid-launch leaves a record to
        reconcile instead of an untracked cloud instance."""
        if not trace_id:
            from ray_tpu.util import trace_context
            trace_id = trace_context.new_trace_id()
        rec = InstanceRecord(node_id, node_type, resources, trace_id)
        self.store.put(node_id, rec.to_dict())
        with self._lock:
            self._records[node_id] = rec
        self._emit("instance_requested", rec)
        return rec

    def transition(self, node_id: str, new_state: str,
                   metadata: Optional[Dict[str, Any]] = None,
                   **journal_fields) -> InstanceRecord:
        """Validated persist-then-journal state change. Terminal states
        delete the persisted key (the journal keeps the history; a
        tombstone would otherwise grow the KV table one entry per launch
        forever) but the in-memory record is kept for inspection."""
        with self._lock:
            rec = self._records.get(node_id)
            if rec is None:
                raise KeyError(f"unknown instance {node_id!r}")
            if new_state not in _ALLOWED[rec.state]:
                raise InvalidTransition(
                    f"instance {node_id[:12]}: {rec.state} -> {new_state} "
                    f"is not an allowed transition")
            prev = rec.state
            rec.state = new_state
            rec.updated_wall = time.time()
            rec.history.append((new_state, rec.updated_wall))
            if metadata:
                rec.metadata.update(metadata)
        if new_state in TERMINAL_STATES:
            self.store.delete(node_id)
        else:
            self.store.put(node_id, rec.to_dict())
        self._emit(_EVENT_BY_STATE[new_state], rec, prev_state=prev,
                   **journal_fields)
        return rec

    def _emit(self, event_type: str, rec: InstanceRecord,
              **fields) -> None:
        try:
            self._journal(event_type, trace_id=rec.trace_id,
                          node_id=rec.node_id, node_type=rec.node_type,
                          state=rec.state, **fields)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            logger.debug("journal emit failed for %s", event_type)

    # ---------------------------------------------------------- reconcile

    def load(self) -> int:
        """Read persisted records (an earlier incarnation's) into memory;
        returns how many were restored. Existing in-memory records win —
        load() is for a fresh manager after a restart."""
        restored = 0
        for node_id, d in self.store.load_all().items():
            try:
                rec = InstanceRecord.from_dict(d)
            except Exception:  # noqa: BLE001 — torn/alien record
                logger.warning("discarding unreadable instance record %r",
                               node_id[:12])
                try:
                    self.store.delete(node_id)
                except Exception:  # noqa: BLE001
                    pass
                continue
            with self._lock:
                if node_id not in self._records:
                    self._records[node_id] = rec
                    restored += 1
        return restored

    def reconcile(self, registered: set,
                  provider_live: Optional[Dict[str, dict]] = None,
                  terminate: Optional[Callable[[InstanceRecord], None]]
                  = None, orphan_grace_s: float = 0.0) -> Dict[str, list]:
        """Converge restored records against the head's live node table.

        * REQUESTED/ALLOCATED whose node DID register while we were down
          -> adopt straight to RUNNING (journaled ``instance_adopted``
          detail on the transition).
        * REQUESTED/ALLOCATED whose node never registered and is older
          than ``orphan_grace_s`` -> terminate through the provider (the
          record's metadata / the provider ledger locates it without an
          in-memory handle) -> TERMINATED. Young ones are left pending —
          the normal adoption loop picks them up.
        * RUNNING whose node is gone -> DEAD.
        * DRAINING whose node is gone -> TERMINATED (the drain finished
          while we were down).
        * ``provider_live`` entries with NO record at all (the crash won
          the tiny create-vs-persist race) -> terminate, journaled as
          ``instance_unrecorded`` orphans.

        Idempotent: a second reconcile over converged state is a no-op,
        so a double restart journals no duplicate transitions.
        """
        now = time.time()
        actions: Dict[str, list] = {"adopted": [], "orphaned": [],
                                    "dead": [], "drained": [],
                                    "pending": [], "unrecorded": []}
        for rec in self.records():
            if rec.state in (REQUESTED, ALLOCATED):
                if rec.node_id in registered:
                    self.transition(rec.node_id, RUNNING,
                                    detail="adopted-after-restart")
                    actions["adopted"].append(rec.node_id)
                elif now - rec.created_wall >= orphan_grace_s:
                    if terminate is not None:
                        try:
                            terminate(rec)
                        except Exception:  # noqa: BLE001 — a failed
                            # orphan kill must not wedge reconcile; the
                            # next pass retries
                            logger.exception(
                                "orphan terminate failed for %s",
                                rec.node_id[:12])
                            actions["pending"].append(rec.node_id)
                            continue
                    self.transition(rec.node_id, TERMINATED,
                                    detail="orphaned-launch",
                                    age_s=round(now - rec.created_wall, 2))
                    actions["orphaned"].append(rec.node_id)
                else:
                    actions["pending"].append(rec.node_id)
            elif rec.state == RUNNING and rec.node_id not in registered:
                self.transition(rec.node_id, DEAD,
                                detail="missing-after-restart")
                actions["dead"].append(rec.node_id)
            elif rec.state == DRAINING and rec.node_id not in registered:
                self.transition(rec.node_id, TERMINATED,
                                detail="drain-finished-across-restart")
                actions["drained"].append(rec.node_id)
        if provider_live:
            with self._lock:
                known = set(self._records)
            for node_id, meta in provider_live.items():
                if node_id in known or node_id in registered:
                    continue
                # provider created it, no record ever landed: the record
                # write crashed mid-flight — still not a leak
                if terminate is not None:
                    ghost = InstanceRecord(node_id, "?", {}, "")
                    ghost.metadata = dict(meta or {})
                    try:
                        terminate(ghost)
                    except Exception:  # noqa: BLE001
                        logger.exception("unrecorded orphan terminate "
                                         "failed for %s", node_id[:12])
                        continue
                self._journal("instance_unrecorded", trace_id="",
                              node_id=node_id, detail="terminated")
                actions["unrecorded"].append(node_id)
        return actions
