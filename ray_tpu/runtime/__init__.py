"""Multiprocess cluster runtime: head, node daemon, workers, transport.

Layer map (each module cites its reference counterpart):
  protocol.py        framed RPC w/ retries + chaos   (src/ray/rpc/)
  head.py            global control service          (src/ray/gcs/gcs_server/)
  node.py            per-node daemon + worker pool   (src/ray/raylet/)
  worker_main.py     worker process execute loop     (src/ray/core_worker/ exec side)
  cluster_backend.py owner-side submission/transport (src/ray/core_worker/ submit side)
  object_plane.py    shm store + ownership/transfer  (src/ray/object_manager/)
  wire.py            spec wire format                (src/ray/protobuf/common.proto)
"""
