"""User-facing exception hierarchy.

Mirrors the reference's error taxonomy (reference: python/ray/exceptions.py):
task errors wrap the remote traceback and re-raise at `get()`; actor errors
distinguish death-in-flight from dead-at-submit; system errors cover object
loss, OOM kills and node failure.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception remotely; re-raised on get().

    Carries the remote traceback text so the user sees the real failure site.
    """

    def __init__(self, cause_cls_name: str, cause_repr: str, remote_tb: str, cause=None):
        self.cause_cls_name = cause_cls_name
        self.cause_repr = cause_repr
        self.remote_tb = remote_tb
        self.cause = cause
        super().__init__(f"{cause_cls_name}: {cause_repr}\n\nRemote traceback:\n{remote_tb}")

    @classmethod
    def from_exception(cls, exc: BaseException) -> "TaskError":
        return cls(
            type(exc).__name__,
            repr(exc),
            "".join(traceback.format_exception(type(exc), exc, exc.__traceback__)),
            cause=exc,
        )

    def __reduce__(self):
        # cause may be unpicklable (it crossed a process already); drop it.
        return (TaskError,
                (self.cause_cls_name, self.cause_repr, self.remote_tb))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    """The actor is dead (creation failed, crashed past max_restarts, or killed)."""

    def __init__(self, actor_id_hex: str = "", reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"actor {actor_id_hex} died: {reason}")

    def __reduce__(self):
        return (ActorDiedError, (self.actor_id_hex, self.reason))


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (restarting); call may be retried."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""

    def __init__(self, object_id_hex: str = "", reason: str = ""):
        self.object_id_hex = object_id_hex
        self.reason = reason
        super().__init__(f"object {object_id_hex} lost: {reason}")

    def __reduce__(self):
        return (ObjectLostError, (self.object_id_hex, self.reason))


class ObjectStoreFullError(RayTpuError):
    """Shared-memory arena is full and eviction could not make room."""


class OutOfMemoryError(RayTpuError):
    """Worker killed by the memory monitor."""


class NodeDiedError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get(timeout=...) expired."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id_hex: str = ""):
        self.task_id_hex = task_id_hex
        super().__init__(f"task {task_id_hex} was cancelled")

    def __reduce__(self):
        return (TaskCancelledError, (self.task_id_hex,))


class RuntimeEnvSetupError(RayTpuError):
    """Per-task/actor runtime environment failed to materialize."""


class PlacementGroupUnschedulableError(RayTpuError):
    """Bundles cannot fit the cluster under the requested strategy."""
