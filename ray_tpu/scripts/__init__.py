"""CLI entrypoints (reference: python/ray/scripts/scripts.py)."""
