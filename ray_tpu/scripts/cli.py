"""`python -m ray_tpu` — cluster CLI.

Role-equivalent to the reference's `ray` CLI (reference:
python/ray/scripts/scripts.py:89 — start/stop/status and the state-API
`ray list ...` family, python/ray/util/state/api.py:110). argparse instead
of click; the head's state_dump RPC is the single aggregation point
(reference: dashboard/state_aggregator.py collapses GCS+raylet sources the
same way).

Commands:
  start --head [--num-cpus N] [--port P]     boot a head (+ 1 node daemon)
  start --address H:P [--num-cpus N]         add a node daemon to a cluster
  status [--address H:P]                     cluster resources + nodes
  list {nodes,actors,workers,placement-groups,objects} [--address H:P]
  top [--watch] [--interval S]               node/worker hardware table
  memory [--group-by node|owner] [--top N]   object-store directory + totals
  events [--follow] [--type T]               cluster event journal
  requests [--slowest N] [--live]            LLM request timelines
  trace [--request RID | --trace-id T]       span tree / request timeline
  stop [--address H:P]                       stop node daemons + head
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional

ADDRESS_FILE = "head_address"


def _session_dir() -> str:
    from ray_tpu.core.config import GlobalConfig
    return GlobalConfig.session_dir


def save_address(address: str) -> None:
    os.makedirs(_session_dir(), exist_ok=True)
    with open(os.path.join(_session_dir(), ADDRESS_FILE), "w") as f:
        f.write(address)


def load_address(explicit: Optional[str]) -> str:
    if explicit:
        return explicit
    env = os.environ.get("RTPU_ADDRESS")
    if env:
        return env
    path = os.path.join(_session_dir(), ADDRESS_FILE)
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        raise SystemExit(
            "no cluster address: pass --address, set RTPU_ADDRESS, or "
            "run `python -m ray_tpu start --head` first") from None


def _client(address: str):
    from ray_tpu.runtime.protocol import RpcClient
    return RpcClient(address, name="cli")


def cmd_start(args) -> int:
    from ray_tpu.runtime.cluster_backend import start_head, start_node
    resources = {"CPU": float(args.num_cpus if args.num_cpus is not None
                              else (os.cpu_count() or 1))}
    if args.head:
        session = os.urandom(4).hex()
        head_proc, address = start_head(session, port=args.port or None)
        node_proc = start_node(address, session, resources=resources)
        save_address(address)
        print(f"head started at {address} "
              f"(head pid {head_proc.pid}, node pid {node_proc.pid})")
        print(f"connect with: ray_tpu.init(address={address!r})")
        return 0
    address = load_address(args.address)
    client = _client(address)
    session = client.call("connect_driver", {}).get("session", "")
    from ray_tpu.runtime.cluster_backend import start_node as _sn
    proc = _sn(address, session, resources=resources)
    deadline = time.monotonic() + 30
    known = time.monotonic()
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            print(f"node daemon exited rc={proc.returncode}",
                  file=sys.stderr)
            return 1
        time.sleep(0.2)
        nodes = client.call("list_nodes")
        if any(n["alive"] for n in nodes):
            break
    print(f"node daemon pid {proc.pid} joined {address}")
    return 0


def cmd_status(args) -> int:
    address = load_address(args.address)
    client = _client(address)
    total = client.call("cluster_resources")
    avail = client.call("available_resources")
    nodes = client.call("list_nodes")
    alive = [n for n in nodes if n["alive"]]
    print(f"cluster at {address}: {len(alive)}/{len(nodes)} nodes alive")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0.0):g}/{total[k]:g} available")
    return 0


def cmd_list(args) -> int:
    address = load_address(args.address)
    client = _client(address)
    dump = client.call("state_dump")
    if args.what == "nodes":
        rows = dump["nodes"]
    elif args.what == "actors":
        rows = dump["actors"]
    elif args.what == "placement-groups":
        rows = dump["placement_groups"]
    elif args.what == "workers":
        rows = []
        for n in dump["nodes"]:
            if not n["alive"]:
                continue
            try:
                for w in _client(n["address"]).call("list_workers"):
                    rows.append({"node_id": n["node_id"], **w})
            except Exception:
                pass
    elif args.what == "objects":
        # per-owner object tables (ownership model) + per-node arena stats
        rows = list(dump.get("objects", []))
        for n in dump["nodes"]:
            if not n["alive"]:
                continue
            try:
                st = _client(n["address"]).call("store_stats")
                rows.append({"node_id": n["node_id"], **st})
            except Exception:
                pass
    elif args.what == "tasks":
        rows = dump.get("tasks", [])
    else:
        raise SystemExit(f"unknown list target {args.what}")
    if args.format == "json":
        print(json.dumps(rows, indent=2, default=str))
    else:
        for r in rows:
            print("  ".join(f"{k}={v}" for k, v in r.items()))
    print(f"({len(rows)} {args.what})", file=sys.stderr)
    return 0


def cmd_metrics(args) -> int:
    address = load_address(args.address)
    agg = _client(address).call("metrics_dump")
    if args.format == "json":
        print(json.dumps(agg, indent=2, default=str))
        return 0
    for name, m in sorted(agg.items()):
        if m["type"] == "histogram":
            for k, v in m["values"].items():
                mean = v["sum"] / v["n"] if v["n"] else 0.0
                print(f"{name}{{{k}}}  n={v['n']} mean={mean:.6g}")
        else:
            for k, v in m["values"].items():
                print(f"{name}{{{k}}}  {v:g}")
    print(f"({len(agg)} metrics)", file=sys.stderr)
    return 0


def _hist_quantile(metrics: dict, name: str, q: float):
    """Quantile estimate from an aggregated histogram dump: counts sum
    across tag values, the answer is the UPPER BOUND of the bucket the
    quantile lands in (conservative; exact values aren't on the wire).
    None when the histogram is absent or empty."""
    m = metrics.get(name)
    if not m or m.get("type") != "histogram" or not m.get("values"):
        return None
    bounds = list(m.get("boundaries") or ())
    if not bounds:
        return None
    total = [0] * (len(bounds) + 1)
    for v in m["values"].values():
        for i, c in enumerate(v.get("counts") or ()):
            if i < len(total):
                total[i] += c
    n = sum(total)
    if n == 0:
        return None
    run = 0
    for i, c in enumerate(total):
        run += c
        if run >= q * n:
            # +Inf bucket: report the largest finite bound we know
            return bounds[min(i, len(bounds) - 1)]
    return bounds[-1]


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}GiB"


def _render_top(client, address: str) -> str:
    """One frame of `top`: nodes with hardware gauges, worker rows under
    each node (data: state_dump + the newest hardware time-series point
    per series + aggregated app metrics)."""
    dump = client.call("state_dump", timeout=10)
    latest = client.call("timeseries_dump",
                         {"latest": True, "max_age_s": 30.0}, timeout=10)
    metrics = client.call("metrics_dump", timeout=10)

    node_gauges = {}   # node_id -> {metric: value}      (untagged series)
    workers = {}       # node_id -> {wid: {cpu, rss, state}}
    hbm = {}           # node_id -> {device: {used, limit}}
    for s in latest:
        nid, metric, tags = s["node"], s["metric"], s.get("tags") or {}
        if metric in ("worker_cpu_percent", "worker_rss_bytes"):
            w = workers.setdefault(nid, {}).setdefault(
                tags.get("worker", "?"), {"state": tags.get("state", "")})
            w["cpu" if metric == "worker_cpu_percent" else "rss"] = \
                s["value"]
            if tags.get("state"):
                w["state"] = tags["state"]
        elif metric in ("tpu_hbm_used_bytes", "tpu_hbm_limit_bytes"):
            # device indices are process-local: key per (worker, device)
            # so two workers' chip 0 don't collide in the per-node sum
            d = hbm.setdefault(nid, {}).setdefault(
                (tags.get("worker", ""), tags.get("device", "?")), {})
            d["used" if metric == "tpu_hbm_used_bytes" else "limit"] = \
                s["value"]
        elif not tags:
            node_gauges.setdefault(nid, {})[metric] = s["value"]

    qd = metrics.get("queue_depth", {}).get("values", {})
    queue_depth = sum(qd.values()) if qd else 0
    inflight = metrics.get("serve_inflight_requests", {}).get("values", {})

    def _gauge(name):
        vals = metrics.get(name, {}).get("values", {})
        return sum(vals.values()) if vals else None

    def _gauge_mean(name):
        # fraction-valued gauges (SLO attainment) MEAN across workers —
        # summing fractions over engines would overshoot 1.0
        vals = metrics.get(name, {}).get("values", {})
        return sum(vals.values()) / len(vals) if vals else None

    # LLM engine gauges (present when an InferenceEngine runs anywhere
    # on the cluster): one summary line mirroring what vLLM logs per step
    llm_decode = _gauge("llm_decode_tokens_per_s")
    llm_line = ""
    if llm_decode is not None:
        kv = _gauge("llm_kv_page_utilization") or 0.0
        hit = _gauge("llm_prefix_cache_hit_rate") or 0.0
        pf = _gauge("llm_prefill_tokens_per_s") or 0.0
        lq = _gauge("llm_queue_depth") or 0
        llm_line = (f"llm: decode {llm_decode:.0f} tok/s  "
                    f"prefill {pf:.0f} tok/s  kv_util {kv:.0%}  "
                    f"prefix_hit {hit:.0%}  queued {lq:g}")
        # request-level serving latencies from the flight-recorder
        # histograms (bucket upper bounds, hence the <=)
        ttft50 = _hist_quantile(metrics, "llm_ttft_seconds", 0.5)
        ttft99 = _hist_quantile(metrics, "llm_ttft_seconds", 0.99)
        tpot50 = _hist_quantile(metrics, "llm_tpot_seconds", 0.5)
        if ttft50 is not None and ttft99 is not None:
            llm_line += (f"  ttft p50<={ttft50 * 1e3:.0f}ms "
                         f"p99<={ttft99 * 1e3:.0f}ms")
        if tpot50 is not None:
            llm_line += f"  tpot p50<={tpot50 * 1e3:.1f}ms"
        slo_ttft = _gauge_mean("llm_slo_ttft_attainment")
        slo_tpot = _gauge_mean("llm_slo_tpot_attainment")
        if slo_ttft is not None and slo_tpot is not None:
            llm_line += (f"  slo ttft {slo_ttft:.0%} "
                         f"tpot {slo_tpot:.0%}")

    # object-store summary: used/cap from the hardware series, spill and
    # pull rates from the accounting counters (object_accounting=True)
    store_line = ""
    st_used = sum(v.get("object_store_used_bytes", 0)
                  for v in node_gauges.values())
    st_cap = sum(v.get("object_store_capacity_bytes", 0)
                 for v in node_gauges.values())
    spill_n = _gauge("object_store_spill_write_total")
    spill_b = _gauge("object_store_spill_write_bytes")
    pull_in = _gauge("object_store_pull_in_bytes")
    pull_out = _gauge("object_store_pull_out_bytes")
    infl = _gauge("object_store_fetch_inflight_count")
    if st_cap or spill_n is not None or pull_in is not None:
        store_line = (f"store: {_fmt_bytes(st_used)}/{_fmt_bytes(st_cap)}"
                      f"  spills {spill_n or 0:g}"
                      f" ({_fmt_bytes(spill_b or 0)})"
                      f"  pull in/out {_fmt_bytes(pull_in or 0)}/"
                      f"{_fmt_bytes(pull_out or 0)}"
                      f"  fetches {infl or 0:g}")
        p50 = _hist_quantile(metrics, "object_store_pull_seconds", 0.5)
        if p50 is not None:
            store_line += f"  pull p50<={p50 * 1e3:.0f}ms"
    nodes = dump["nodes"]
    alive = [n for n in nodes if n["alive"]]
    lines = [
        f"ray_tpu top — {address}  "
        f"nodes {len(alive)}/{len(nodes)}  leases {dump.get('leases', 0)}  "
        f"queue_depth {queue_depth:g}"
        + (f"  serve_inflight {sum(inflight.values()):g}" if inflight
           else ""),
    ] + ([llm_line] if llm_line else []) \
      + ([store_line] if store_line else []) + [
        "",
        f"{'NODE':<14}{'ALIVE':<7}{'CPU%':>6}  {'MEM':>19}  "
        f"{'STORE':>19}  {'OBJS':>6}  {'HBM':>19}",
    ]
    # series are keyed by the daemon's full node_id; state rows carry the
    # same id, but match by prefix so either side may be truncated
    def _series_for(table, node_id):
        for nid, v in table.items():
            if node_id.startswith(nid) or nid.startswith(node_id):
                return v
        return {}

    for n in sorted(nodes, key=lambda r: r["node_id"]):
        g = _series_for(node_gauges, n["node_id"])
        mem_u, mem_t = g.get("node_mem_used_bytes"), \
            g.get("node_mem_total_bytes")
        st_u, st_c = g.get("object_store_used_bytes"), \
            g.get("object_store_capacity_bytes")
        cpu = g.get("node_cpu_percent")
        devs = _series_for(hbm, n["node_id"])
        if devs:
            used = sum(d.get("used", 0) for d in devs.values())
            limit = sum(d.get("limit", 0) for d in devs.values())
            hbm_s = f"{_fmt_bytes(used)}/{_fmt_bytes(limit)}"
        else:
            hbm_s = "-"
        lines.append(
            f"{n['node_id'][:12]:<14}"
            f"{('yes' if n['alive'] else 'NO'):<7}"
            f"{(f'{cpu:.1f}' if cpu is not None else '-'):>6}  "
            f"{(f'{_fmt_bytes(mem_u)}/{_fmt_bytes(mem_t)}' if mem_u is not None and mem_t else '-'):>19}  "
            f"{(f'{_fmt_bytes(st_u)}/{_fmt_bytes(st_c)}' if st_u is not None and st_c else '-'):>19}  "
            f"{g.get('object_store_num_objects', 0):>6g}  "
            f"{hbm_s:>19}")
        rows = _series_for(workers, n["node_id"])
        for wid in sorted(rows):
            w = rows[wid]
            cpu_s = f"{w['cpu']:.1f}" if "cpu" in w else "-"
            rss_s = _fmt_bytes(w["rss"]) if "rss" in w else "-"
            lines.append(f"  {wid:<12}  {w.get('state', ''):<8}"
                         f"cpu {cpu_s:>6}  rss {rss_s:>9}")
    return "\n".join(lines)


def cmd_top(args) -> int:
    """Live node/worker hardware table (reference: `ray status` + the
    dashboard node view, as a terminal table over the head's hardware
    time-series rings)."""
    address = load_address(args.address)
    client = _client(address)
    if not args.watch:
        print(_render_top(client, address))
        return 0
    try:
        while True:
            frame = _render_top(client, address)
            # clear + home, then the frame — repaint without scrollback spam
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_profile(args) -> int:
    """Cluster-wide sampling profiles: merged collapsed stacks from the
    head's ProfileStore (continuous, every process at profile_hz), or a
    --record burst fanned out to head + node daemons + workers. Renders
    a self/cumulative top-frames table, --flame collapsed output
    (flamegraph.pl / speedscope paste), or --speedscope JSON."""
    from ray_tpu.util.stack_profiler import (merge_stacks, to_speedscope,
                                             top_frames)
    address = load_address(args.address)
    payload = {"role": "head" if args.head else "",
               "node": args.node or "", "worker": args.worker or ""}
    client = _client(address)
    if args.record:
        payload.update({"seconds": args.record, "hz": args.hz})
        data = client.call("profiles_record", payload,
                           timeout=args.record + 30.0)
    else:
        data = client.call("profiles_dump", payload, timeout=10)
    procs = (data or {}).get("procs") or []
    if args.format == "json":
        print(json.dumps(data, indent=2, default=str))
        return 0
    stacks = merge_stacks([p.get("stacks") for p in procs])
    samples = sum(int(p.get("samples") or 0) for p in procs)
    dropped = sum(int(p.get("dropped") or 0) for p in procs)
    if args.flame:
        for stack, count in sorted(stacks.items(),
                                   key=lambda kv: (-kv[1], kv[0])):
            print(f"{stack} {count}")
        return 0
    if args.speedscope is not None:
        name = "ray_tpu burst" if args.record else "ray_tpu continuous"
        out = json.dumps(to_speedscope(stacks, name=name))
        if args.speedscope == "-":
            print(out)
        else:
            with open(args.speedscope, "w") as f:
                f.write(out)
            print(f"wrote {args.speedscope} ({len(stacks)} stacks, "
                  f"{samples} samples)", file=sys.stderr)
        return 0
    if not procs:
        print("no profiles yet — is profile_enabled on, and has a "
              "telemetry flush landed? (try --record 2)")
        return 1
    mode = (f"burst {args.record:g}s @ {args.hz:g}Hz" if args.record
            else "continuous")
    print(f"{len(procs)} process(es), {samples} samples"
          + (f" ({dropped} dropped on table overflow)" if dropped else "")
          + f"  [{mode}]")
    for r in sorted(procs, key=lambda r: -(r.get("samples") or 0)):
        where = r.get("node") or ""
        label = r.get("role") or "?"
        ident = r.get("worker") or r.get("key", "")[:12]
        print(f"  {label:<7}{ident:<14}node={where or '-':<14}"
              f"samples={r.get('samples', 0):<8}"
              f"window={r.get('window_s', 0.0):g}s")
    print()
    print(f"{'self':>7} {'self%':>6} {'cum':>7} {'cum%':>6}  frame")
    for row in top_frames(stacks, args.top):
        sp = 100.0 * row["self"] / max(1, samples)
        cp = 100.0 * row["cum"] / max(1, samples)
        print(f"{row['self']:>7} {sp:>5.1f}% {row['cum']:>7} "
              f"{cp:>5.1f}%  {row['frame']}")
    return 0


def cmd_memory(args) -> int:
    """Cluster object-store directory: every tracked object with size,
    role (primary/secondary/spilled), owner, age and pin counts, grouped
    per node or per owner, plus exact per-node arena totals (reference:
    `ray memory`, python/ray/util/state/memory_utils.py — theirs walks
    core-worker ref tables; ours rides the owners' telemetry_push)."""
    address = load_address(args.address)
    client = _client(address)
    od = client.call("objects_dump", timeout=10) or {}
    rows = list(od.get("rows", ()))
    totals = od.get("totals", {})
    if args.format == "json":
        print(json.dumps({"rows": rows, "totals": totals},
                         indent=2, default=str))
        return 0
    # leak heuristic: a PRIMARY that has sat in the arena past --leak-age
    # with no live references at its owner (or whose owner process no
    # longer reports at all) is probably a leaked ObjectRef. Heuristic
    # only: drivers legitimately hold old pinned results.
    reporters = {r.get("reporter", "") for r in rows}
    leaks = 0
    for r in rows:
        pins = r.get("pins")
        unreferenced = (pins is not None
                        and not (pins.get("local") or pins.get("submitted")
                                 or pins.get("borrowers")))
        orphaned = pins is None and r.get("owner", "") not in reporters
        r["_leak"] = bool(r.get("role") == "primary"
                          and r.get("age_s", 0) > args.leak_age
                          and (unreferenced or orphaned))
        leaks += r["_leak"]
    key = "node" if args.group_by == "node" else "owner"
    groups = {}
    for r in rows:
        groups.setdefault(str(r.get(key, "?")), []).append(r)
    n_bytes = sum(r.get("size", 0) for r in rows)
    print(f"object store @ {address}: {len(rows)} object(s), "
          f"{_fmt_bytes(n_bytes)} tracked"
          + (f", {leaks} LEAK suspect(s)" if leaks else ""))
    for gid in sorted(groups):
        rs = sorted(groups[gid], key=lambda r: -r.get("size", 0))
        gb = sum(r.get("size", 0) for r in rs)
        print(f"\n{key} {gid[:12]}  "
              f"({len(rs)} object(s), {_fmt_bytes(gb)})")
        if key == "node":
            for role, t in sorted((totals.get(gid) or {}).items()):
                print(f"  {role:<10} count={t['count']} "
                      f"bytes={t['bytes']} arena_bytes={t['arena_bytes']}")
        for r in rs[:args.top]:
            pins = r.get("pins")
            pin_s = (f"l{pins['local']}/s{pins['submitted']}"
                     f"/b{pins['borrowers']}" if pins else "-")
            print(f"  {str(r.get('object_id', '?'))[:16]:<18}"
                  f"{_fmt_bytes(r.get('size', 0)):>10}  "
                  f"{r.get('role', '?'):<10}"
                  f"owner={str(r.get('owner', '?')):<14}"
                  f"age={r.get('age_s', 0):>7.1f}s  pins={pin_s}"
                  + ("  LEAK?" if r.get("_leak") else ""))
        if len(rs) > args.top:
            print(f"  ... {len(rs) - args.top} more")
    if not rows:
        print("(no object directory rows at the head yet — owners flush "
              "every metrics_export_period_s; object_accounting on?)")
    return 0


def _fmt_event(ev: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
    ms = int((ev.get("ts", 0) % 1) * 1000)
    extras = "  ".join(
        f"{k}={v}" for k, v in sorted(ev.items())
        if k not in ("seq", "ts", "type", "trace_id"))
    trace = f"  trace={ev['trace_id']}" if ev.get("trace_id") else ""
    return (f"#{ev.get('seq', 0):<6} {ts}.{ms:03d}  "
            f"{ev.get('type', '?'):<22} {extras}{trace}")


def cmd_events(args) -> int:
    """Head's cluster event journal: node register/dead, worker death
    (exit cause), actor restart/dead, spill overflow, lease-grant
    failures, autoscaler decisions — monotonically sequenced and
    trace-id stamped (reference: `ray list cluster_events` over the GCS
    event journal; src/ray/gcs keeps the same bounded ring)."""
    address = load_address(args.address)
    client = _client(address)
    if not args.follow:
        evs = client.call("events_dump",
                          {"type": args.type or "",
                           "limit": int(args.limit or 0)}, timeout=10)
        if args.format == "json":
            print(json.dumps(evs, indent=2, default=str))
            return 0
        for ev in evs:
            print(_fmt_event(ev))
        print(f"({len(evs)} event(s))", file=sys.stderr)
        return 0
    after = 0
    frames = args.frames  # hidden test hook: bounded poll count
    try:
        while True:
            evs = client.call("events_dump",
                              {"after_seq": after,
                               "type": args.type or ""}, timeout=10)
            for ev in evs:
                print(_fmt_event(ev))
                after = max(after, int(ev.get("seq", 0)))
            sys.stdout.flush()
            if frames is not None:
                frames -= 1
                if frames <= 0:
                    break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def _logs_payload(args) -> dict:
    return {
        "role": "head" if getattr(args, "head", False) else "",
        "node": args.node or "",
        "worker": args.worker or "",
        "level": args.level or "",
        "since": float(args.since or 0.0),
        "grep": args.grep or "",
        "trace": args.trace or "",
        "request": args.request or "",
    }


def cmd_logs(args) -> int:
    """Search (or follow) the head's cluster-wide structured log store:
    every process's recent records, severity-ring bounded, filterable by
    node/worker/role/level/regex and correlated by trace or request id
    (reference: `ray logs` over the per-session log directory; here the
    records also ride telemetry_push into a head-side ring so the CLI
    works without reaching into each node's filesystem)."""
    from ray_tpu.util.log_plane import format_record
    address = load_address(args.address)
    client = _client(address)
    if not args.follow:
        payload = _logs_payload(args)
        payload["limit"] = int(args.limit or 0)
        data = client.call("logs_dump", payload, timeout=10)
        if args.format == "json":
            print(json.dumps(data, indent=2, default=str))
            return 0
        recs = data.get("records", [])
        for rec in recs:
            print(format_record(rec))
        dropped = data.get("dropped_total", 0)
        note = f", {dropped} dropped at sources" if dropped else ""
        print(f"({len(recs)} record(s){note})", file=sys.stderr)
        return 0
    after = 0
    frames = args.frames  # hidden test hook: bounded poll count
    try:
        while True:
            payload = _logs_payload(args)
            payload["after_seq"] = after
            data = client.call("logs_dump", payload, timeout=10)
            for rec in data.get("records", []):
                print(format_record(rec))
                after = max(after, int(rec.get("seq", 0)))
            after = max(after, int(data.get("last_seq", 0)))
            sys.stdout.flush()
            if frames is not None:
                frames -= 1
                if frames <= 0:
                    break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def _compiles_payload(args) -> dict:
    return {
        "role": "",
        "node": args.node or "",
        "worker": args.worker or "",
        "callable": args.callable or "",
        "recompiles_only": bool(args.recompiles),
        "by_callable": bool(args.by_callable),
        "limit": int(args.limit or 0),
    }


def _fmt_compile_record(rec: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(rec.get("ts", 0)))
    dur = rec.get("measured_s") or rec.get("duration_s") or 0.0
    name = rec.get("name") or "<unattributed>"
    mark = "RECOMPILE " if rec.get("recompile") else ""
    sig = rec.get("signature") or []
    sig_s = ", ".join(sig[:6]) + (", ..." if len(sig) > 6 else "")
    line = (f"{ts}  {rec.get('role', '?'):<7}"
            f"{(rec.get('worker') or '')[:12]:<13}"
            f"{mark}{name}  [{rec.get('kind', '?')}] {dur * 1e3:.1f}ms"
            f"  ({sig_s})")
    for d in rec.get("diff") or []:
        line += f"\n           diff {d}"
    return line


def _render_compiles(client, args) -> str:
    data = client.call("compiles_dump", _compiles_payload(args),
                       timeout=10)
    if args.format == "json":
        return json.dumps(data, indent=2, default=str)
    lines = []
    if args.by_callable:
        agg = data.get("by_callable") or {}
        if not agg:
            return ("no compile records at the head (jax-bearing "
                    "processes flush every metrics_export_period_s; is "
                    "compile_tracker_enabled on?)")
        lines.append(f"{'callable':<28} {'compiles':>8} {'recompiles':>10}"
                     f" {'seconds':>9} {'procs':>6}  last signature")
        rows = sorted(agg.items(),
                      key=lambda kv: (-kv[1]["recompiles"],
                                      -kv[1]["seconds"]))
        for name, a in rows:
            sig = a.get("last_sig") or []
            sig_s = ", ".join(sig[:4]) + (", ..." if len(sig) > 4 else "")
            lines.append(f"{name:<28} {a['compiles']:>8}"
                         f" {a['recompiles']:>10} {a['seconds']:>9.3f}"
                         f" {a['procs']:>6}  ({sig_s})")
            for d in a.get("last_diff") or []:
                lines.append(f"{'':<28} diff {d}")
    else:
        recs = data.get("records", [])
        if not recs:
            return ("no compile records at the head (jax-bearing "
                    "processes flush every metrics_export_period_s; is "
                    "compile_tracker_enabled on?)")
        for rec in recs:
            lines.append(_fmt_compile_record(rec))
    dropped = data.get("dropped_total", 0)
    note = f", {dropped} dropped" if dropped else ""
    lines.append(f"({data.get('procs', 0)} process(es){note})")
    return "\n".join(lines)


def cmd_compiles(args) -> int:
    """XLA compile records aggregated at the head (per-process rings
    fed by telemetry_push; util/compile_tracker.py): every compile with
    its callable, arg shape/dtype signature and duration — recompiles
    flagged with the exact signature diff that caused them. --storms
    lists the journal's once-per-excursion compile_storm events."""
    address = load_address(args.address)
    client = _client(address)
    if args.storms:
        evs = client.call("events_dump",
                          {"type": "compile_storm",
                           "limit": int(args.limit or 0)}, timeout=10)
        if args.format == "json":
            print(json.dumps(evs, indent=2, default=str))
            return 0
        for ev in evs:
            print(_fmt_event(ev))
        print(f"({len(evs)} storm(s))", file=sys.stderr)
        return 0
    if not args.watch:
        print(_render_compiles(client, args))
        return 0
    frames = args.frames  # hidden test hook: bounded repaint count
    try:
        while True:
            frame = _render_compiles(client, args)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            if frames is not None:
                frames -= 1
                if frames <= 0:
                    break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def _fmt_ms(v) -> str:
    return f"{v * 1e3:.1f}ms" if v is not None else "-"


def format_request_timeline(r: dict, indent: str = "") -> str:
    """Render one flight-recorder record (wire dict) as a lifecycle
    timeline: enqueue -> admit (queue wait, cached tokens) -> prefill
    chunks -> first token (TTFT) -> decode -> finish reason."""
    p = indent
    where = ""
    if r.get("worker") or r.get("node"):
        where = f"  @{r.get('worker', '')}" \
                + (f"/{r['node'][:12]}" if r.get("node") else "")
    trace = f"  trace {r['trace_id']}" if r.get("trace_id") else ""
    status = r.get("finish_reason") or "in-flight"
    lines = [f"{p}{r.get('rid', '?')}  [{status}]{where}{trace}"]
    lines.append(f"{p}  enqueue   +0.0ms  "
                 f"(prompt {r.get('prompt_tokens', 0)} tok, "
                 f"max_new {r.get('max_new_tokens', 0)})")
    admits = r.get("admits") or []
    for i, (ts, cached) in enumerate(admits):
        tag = "" if len(admits) == 1 else f" #{i + 1}"
        lines.append(f"{p}  admit{tag}     +{ts * 1e3:.1f}ms  "
                     f"(queue wait {_fmt_ms(r.get('queue_wait')) if i == 0 else _fmt_ms(ts)}, "
                     f"cached {cached} tok)")
    chunks = r.get("chunks") or []
    if chunks:
        toks = "+".join(str(c[1]) for c in chunks[:8]) \
            + ("+..." if len(chunks) > 8 else "")
        disp = sorted({c[2] for c in chunks})
        disp_s = f"{disp[0]}..{disp[-1]}" if len(disp) > 1 else f"{disp[0]}"
        lines.append(f"{p}  prefill   {len(chunks)} chunk(s) "
                     f"[{toks} tok]  dispatch {disp_s}  "
                     f"last +{chunks[-1][0] * 1e3:.1f}ms")
    if r.get("ttft") is not None:
        lines.append(f"{p}  first tok +{r['ttft'] * 1e3:.1f}ms  (TTFT)")
    n_out = r.get("n_generated", 0)
    if n_out > 1 and r.get("tpot"):
        tpot = r["tpot"]
        lines.append(f"{p}  decode    {n_out} tok in "
                     f"{len(r.get('decode') or [])} dispatch(es)  "
                     f"tpot {tpot * 1e3:.2f}ms  "
                     f"({1.0 / tpot:.0f} tok/s)")
    extras = []
    if r.get("stalls"):
        extras.append(f"stalls {r['stalls']}")
    if r.get("preempts"):
        extras.append(f"preempts {r['preempts']} "
                      f"(at {', '.join(f'+{t * 1e3:.1f}ms' for t in r.get('preempt_ts', []))})")
    if extras:
        lines.append(f"{p}  pressure  " + "  ".join(extras))
    if r.get("e2e") is not None:
        lines.append(f"{p}  finish    +{r['e2e'] * 1e3:.1f}ms  "
                     f"reason={r.get('finish_reason')}")
    return "\n".join(lines)


def _render_requests(client, args) -> str:
    payload = {"slowest": int(getattr(args, "slowest", 0) or 0)}
    recs = client.call("requests_dump", payload, timeout=10)
    if not recs:
        return ("no request records at the head (engines flush every "
                "metrics_export_period_s; is the recorder enabled?)")
    if getattr(args, "format", "plain") == "json":
        return json.dumps(recs, indent=2, default=str)
    head = "slowest " if payload["slowest"] else ""
    out = [f"{len(recs)} {head}request(s)", ""]
    out += [format_request_timeline(r) + "\n" for r in recs]
    return "\n".join(out).rstrip("\n")


def cmd_requests(args) -> int:
    """Per-request serving timelines from the engines' flight recorders,
    aggregated at the head (requests_dump RPC over telemetry_push)."""
    address = load_address(args.address)
    client = _client(address)
    if not args.live:
        print(_render_requests(client, args))
        return 0
    frames = args.frames  # hidden test hook: bounded repaint count
    try:
        while True:
            frame = _render_requests(client, args)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            if frames is not None:
                frames -= 1
                if frames <= 0:
                    break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_timeline(args) -> int:
    from ray_tpu.runtime.events import to_chrome_trace
    address = load_address(args.address)
    events = _client(address).call("timeline_dump")
    trace = to_chrome_trace(events)
    out = args.out or "ray_tpu_timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"wrote {len(trace)} events to {out} "
          "(load in chrome://tracing or ui.perfetto.dev)")
    return 0


def _render_train_step(step: dict, fmt: str) -> int:
    """Phase table for one profiled train step (trace --train-step)."""
    total_ms = max(0.0, step["end"] - step["start"]) * 1e3
    if fmt == "json":
        print(json.dumps(step, indent=2, default=str))
        return 0
    print(f"train step  {total_ms:.2f}ms  (trace {step['trace_id']})")
    print(f"  {'phase':<16} {'ms':>10} {'% of step':>10}")
    for c in step.get("children", []):
        dur_ms = max(0.0, c["end"] - c["start"]) * 1e3
        pct = 100.0 * dur_ms / total_ms if total_ms else 0.0
        print(f"  {c['name']:<16} {dur_ms:>10.2f} {pct:>9.1f}%")
    return 0


def cmd_trace(args) -> int:
    """Assemble one distributed trace from the head's timeline and print
    it as an indented span tree (or JSON)."""
    from ray_tpu.util.tracing import assemble_trace, latest_train_step
    address = load_address(args.address)
    client = _client(address)
    events = client.call("timeline_dump")
    if getattr(args, "perfetto", ""):
        # multi-plane export: task spans + train phases + LLM request
        # timelines + XLA compile events + journal markers as named
        # lanes on one wall clock (runtime/events.to_perfetto)
        from ray_tpu.runtime.events import to_perfetto
        compiles = []
        requests = []
        journal = []
        try:
            compiles = client.call("compiles_dump", {},
                                   timeout=10).get("records", [])
        except Exception:  # noqa: BLE001 — lane degrades to empty
            pass
        try:
            requests = client.call("requests_dump", {}, timeout=10) or []
        except Exception:  # noqa: BLE001
            pass
        try:
            journal = client.call("events_dump", {}, timeout=10) or []
        except Exception:  # noqa: BLE001
            pass
        trace = to_perfetto(events, compiles=compiles,
                            requests=requests, journal=journal)
        with open(args.perfetto, "w") as f:
            json.dump(trace, f)
        n = len(trace["traceEvents"])
        lanes = sum(1 for e in trace["traceEvents"]
                    if e.get("ph") == "M"
                    and e.get("name") == "process_name")
        print(f"wrote {n} events across {lanes} lanes to "
              f"{args.perfetto} (load in ui.perfetto.dev)")
        return 0
    if getattr(args, "request", ""):
        # merged view for one LLM request: the router/replica span tree
        # (via the trace_id the record carries) + the engine's
        # flight-recorder timeline under it
        recs = client.call("requests_dump", {"request": args.request},
                           timeout=10)
        if not recs:
            print(f"no request record for {args.request!r} (records "
                  "reach the head on the engine worker's next telemetry "
                  "flush)", file=sys.stderr)
            return 1
        rec = recs[0]
        tid = rec.get("trace_id") or args.trace_id
        roots = assemble_trace(events, trace_id=tid) if tid else []
        # log lines stamped with this request id (or its trace id) from
        # the head's structured log store, interleaved under the render
        logs = []
        try:
            data = client.call("logs_dump", {"request": args.request},
                               timeout=10)
            logs = data.get("records", [])
            if tid:
                data = client.call("logs_dump", {"trace": tid},
                                   timeout=10)
                have = {(r.get("seq"), r.get("pid")) for r in logs}
                logs += [r for r in data.get("records", [])
                         if (r.get("seq"), r.get("pid")) not in have]
            logs.sort(key=lambda r: r.get("ts", 0))
        except Exception:
            logs = []
        if args.format == "json":
            print(json.dumps({"record": rec, "spans": roots,
                              "logs": logs}, indent=2, default=str))
            return 0
        print(f"request {rec['rid']}  trace {tid or '-'}")
        for r in roots:
            _show_span(r, 1)
        if not roots:
            print("  (no spans for this trace yet — the router's "
                  "telemetry flush may still be pending)")
        print(format_request_timeline(rec, indent="  "))
        if logs:
            from ray_tpu.util.log_plane import format_record
            print(f"  logs ({len(logs)} correlated line(s)):")
            for lrec in logs:
                print(f"    {format_record(lrec)}")
        return 0
    if getattr(args, "train_step", False):
        step = latest_train_step(events)
        if step is None:
            print("no train_step spans in the timeline (run "
                  "train.profile_train_step, then wait for the worker's "
                  "telemetry flush)", file=sys.stderr)
            return 1
        return _render_train_step(step, args.format)
    roots = assemble_trace(events, trace_id=args.trace_id or "",
                           task_id=args.task_id or "")
    if not roots:
        hint = args.trace_id or args.task_id or "<missing selector>"
        print(f"no spans found for {hint} "
              "(pass --trace-id or --task-id; spans appear after the "
              "owners' next telemetry flush)", file=sys.stderr)
        return 1
    if args.format == "json":
        print(json.dumps(roots, indent=2, default=str))
        return 0
    print(f"trace {roots[0]['trace_id']}")

    n = 0

    def count(span):
        nonlocal n
        n += 1
        for c in span["children"]:
            count(c)
    for r in roots:
        _show_span(r, 0)
        count(r)
    print(f"({n} spans)", file=sys.stderr)
    return 0


def _show_span(span, depth) -> None:
    dur_ms = max(0.0, span["end"] - span["start"]) * 1e3
    mark = "" if span.get("ok", True) else "  [FAILED]"
    where = span.get("worker", "")
    where = f" @{where}" if where else ""
    print(f"{'  ' * depth}- {span['name']}  {dur_ms:.2f}ms"
          f"{where}{mark}  span={span['span_id']}")
    for c in span["children"]:
        _show_span(c, depth + 1)


def cmd_dashboard(args) -> int:
    from ray_tpu.dashboard import Dashboard
    address = load_address(args.address)
    dash = Dashboard(address, port=args.port)
    print(f"dashboard at http://127.0.0.1:{dash.port} (ctrl-c to stop)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.stop()
    return 0


def cmd_stop(args) -> int:
    address = load_address(args.address)
    client = _client(address)
    nodes = client.call("list_nodes")
    for n in nodes:
        if not n["alive"]:
            continue
        try:
            _client(n["address"]).call("shutdown", timeout=5.0)
        except Exception:
            pass
    print(f"stopped {sum(1 for n in nodes if n['alive'])} node daemon(s); "
          "head left running (kill its pid to stop fully)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="boot a head or join a cluster")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--port", type=int, default=None)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("status", help="cluster resources and nodes")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("list", help="list cluster state")
    sp.add_argument("what", choices=["nodes", "actors", "workers",
                                     "placement-groups", "objects",
                                     "tasks"])
    sp.add_argument("--address")
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("metrics", help="aggregated application metrics")
    sp.add_argument("--address")
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser("top", help="node/worker hardware table "
                                    "(cpu/rss/hbm/store)")
    sp.add_argument("--address")
    sp.add_argument("--watch", action="store_true",
                    help="repaint continuously until ctrl-c")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("profile",
                        help="cluster-wide sampling profiles: top hot "
                             "frames, --flame collapsed stacks, or "
                             "--speedscope JSON (continuous, or an "
                             "on-demand --record burst)")
    sp.add_argument("--address")
    sp.add_argument("--head", action="store_true",
                    help="only the head process")
    sp.add_argument("--node", help="only processes on this node id "
                                   "(prefix match)")
    sp.add_argument("--worker", help="only this worker id (prefix match)")
    sp.add_argument("--record", type=float, default=0.0,
                    metavar="SECONDS",
                    help="burst-capture for SECONDS at --hz across the "
                         "selected processes instead of reading the "
                         "continuous profile")
    sp.add_argument("--hz", type=float, default=99.0,
                    help="burst sampling rate (with --record)")
    sp.add_argument("--top", type=int, default=20,
                    help="rows in the frame table")
    sp.add_argument("--flame", action="store_true",
                    help="print merged collapsed stacks ('stack N' "
                         "lines; flamegraph.pl / speedscope input)")
    sp.add_argument("--speedscope", metavar="FILE",
                    help="write speedscope JSON to FILE ('-' = stdout)")
    sp.add_argument("--format", choices=["plain", "json"],
                    default="plain")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("memory",
                        help="object-store directory: per-object rows "
                             "(size, role, owner, pins) + per-node arena "
                             "totals and a leak heuristic")
    sp.add_argument("--address")
    sp.add_argument("--group-by", choices=["node", "owner"],
                    default="node", dest="group_by")
    sp.add_argument("--top", type=int, default=10,
                    help="largest N objects per group")
    sp.add_argument("--leak-age", type=float, default=300.0,
                    help="flag unreferenced primaries older than this (s)")
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("events",
                        help="cluster event journal (node/worker/actor "
                             "lifecycle, spill overflow, lease failures, "
                             "autoscaler decisions)")
    sp.add_argument("--address")
    sp.add_argument("--type", default="",
                    help="only events of this type (e.g. worker_death)")
    sp.add_argument("--limit", type=int, default=0,
                    help="newest N events only")
    sp.add_argument("--follow", action="store_true",
                    help="poll for new events until ctrl-c")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--frames", type=int, default=None,
                    help=argparse.SUPPRESS)  # test hook: bounded polls
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("logs",
                        help="search the cluster-wide structured log "
                             "store (per-process rings at the head): "
                             "filter by node/worker/level/regex, "
                             "correlate by --trace / --request, or "
                             "--follow live")
    sp.add_argument("--address")
    sp.add_argument("--follow", action="store_true",
                    help="poll for new records until ctrl-c")
    sp.add_argument("--grep", default="",
                    help="only records whose message matches this regex")
    sp.add_argument("--level", default="",
                    help="severity floor (debug/info/warning/error)")
    sp.add_argument("--node", default="",
                    help="only processes on this node id (prefix match)")
    sp.add_argument("--worker", default="",
                    help="only this worker id (prefix match)")
    sp.add_argument("--head", action="store_true",
                    help="only the head process")
    sp.add_argument("--trace", default="",
                    help="only records stamped with this trace id")
    sp.add_argument("--request", default="",
                    help="only records stamped with this LLM request id")
    sp.add_argument("--since", type=float, default=0.0,
                    help="only records newer than this unix timestamp")
    sp.add_argument("--limit", type=int, default=0,
                    help="newest N records only")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--frames", type=int, default=None,
                    help=argparse.SUPPRESS)  # test hook: bounded polls
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("compiles",
                        help="XLA compile records aggregated at the "
                             "head: callable, arg signature, duration; "
                             "recompiles carry the signature diff that "
                             "caused them (util/compile_tracker.py)")
    sp.add_argument("--address")
    sp.add_argument("--node", default="",
                    help="only processes on this node id (prefix match)")
    sp.add_argument("--worker", default="",
                    help="only this worker id (prefix match)")
    sp.add_argument("--callable", default="",
                    help="only compiles of callables matching this "
                         "substring (e.g. llm. or train.)")
    sp.add_argument("--recompiles", action="store_true",
                    help="only recompiles (same callable, new arg "
                         "signature — each carries its diff)")
    sp.add_argument("--by-callable", action="store_true",
                    dest="by_callable",
                    help="aggregate per callable: compiles, recompiles, "
                         "total seconds, processes")
    sp.add_argument("--storms", action="store_true",
                    help="list compile_storm journal events (one per "
                         "recompile-rate excursion)")
    sp.add_argument("--watch", action="store_true",
                    help="repaint continuously until ctrl-c")
    sp.add_argument("--limit", type=int, default=0,
                    help="newest N records only")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--frames", type=int, default=None,
                    help=argparse.SUPPRESS)  # test hook: bounded repaints
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_compiles)

    sp = sub.add_parser("timeline", help="export task timeline "
                                         "(chrome trace)")
    sp.add_argument("--address")
    sp.add_argument("--out")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("trace", help="assemble one distributed trace "
                                      "as a span tree")
    sp.add_argument("--address")
    sp.add_argument("--trace-id", default="")
    sp.add_argument("--task-id", default="",
                    help="resolve the trace via this task's exec span")
    sp.add_argument("--train-step", action="store_true",
                    help="show the latest profiled train step's phase "
                         "breakdown (train.profile_train_step)")
    sp.add_argument("--request", default="",
                    help="merged timeline for one LLM request id: router/"
                         "replica spans + the engine's flight-recorder "
                         "lifecycle events")
    sp.add_argument("--perfetto", default="", metavar="OUT",
                    help="write a unified multi-plane Perfetto trace to "
                         "OUT: task spans, train phases, LLM request "
                         "timelines, XLA compiles and journal markers "
                         "as named lanes on one clock")
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("requests",
                        help="per-request LLM serving timelines (queue "
                             "wait, prefill chunks, TTFT, decode tok/s, "
                             "finish reason)")
    sp.add_argument("--address")
    sp.add_argument("--slowest", type=int, default=0,
                    help="only the N worst end-to-end latencies")
    sp.add_argument("--live", action="store_true",
                    help="repaint continuously until ctrl-c")
    sp.add_argument("--interval", type=float, default=2.0)
    sp.add_argument("--frames", type=int, default=None,
                    help=argparse.SUPPRESS)  # test hook: bounded repaints
    sp.add_argument("--format", choices=["plain", "json"], default="plain")
    sp.set_defaults(fn=cmd_requests)

    sp = sub.add_parser("dashboard", help="serve the HTTP dashboard")
    sp.add_argument("--address")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser("stop", help="stop node daemons")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_stop)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
