"""Blocks — the unit of data the streaming executor moves through the store.

Role-equivalent to the reference's Block/BlockAccessor (reference:
python/ray/data/block.py:256), redesigned columnar-numpy-first for TPU:
batches come out as dense ``np.ndarray`` columns with static dtypes so a
training loop can feed them straight to jitted programs without conversion.
Arrow/pandas interop is deliberately out of scope — numpy is the lingua
franca of the JAX host world.

A block is one of:
  - ``dict[str, np.ndarray]``  columnar table (canonical form)
  - ``np.ndarray``             single unnamed column (wrapped as {"data": a})
  - ``list``                   rows of arbitrary Python objects
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Sequence, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], np.ndarray, list]

#: metadata travelling beside every block in the owner's memory store so the
#: executor can make flow decisions without fetching block payloads
#: (reference: BlockMetadata in data/block.py).
BlockMeta = Dict[str, Any]  # {"num_rows": int, "size_bytes": int}


def block_meta(block: Block) -> BlockMeta:
    acc = BlockAccessor.for_block(block)
    return {"num_rows": acc.num_rows(), "size_bytes": acc.size_bytes()}


class BlockAccessor:
    """Format-generic view over one block."""

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        if isinstance(block, dict):
            return _TableAccessor(block)
        if isinstance(block, np.ndarray):
            return _TableAccessor({"data": block})
        if isinstance(block, list):
            return _ListAccessor(block)
        raise TypeError(f"unsupported block type {type(block).__name__}")

    @staticmethod
    def concat(blocks: Sequence[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor.for_block(b).num_rows()]
        if not blocks:
            return []
        first = BlockAccessor.for_block(blocks[0])
        if isinstance(first, _ListAccessor):
            out: list = []
            for b in blocks:
                out.extend(BlockAccessor.for_block(b).to_rows())
            return out
        cols: Dict[str, List[np.ndarray]] = {}
        for b in blocks:
            tbl = BlockAccessor.for_block(b).to_table()
            for k, v in tbl.items():
                cols.setdefault(k, []).append(v)
        return {k: np.concatenate(v, axis=0) for k, v in cols.items()}

    @staticmethod
    def from_rows(rows: Sequence[Any]) -> Block:
        """Build a block from rows; dict rows become a columnar table."""
        rows = list(rows)
        if rows and all(isinstance(r, dict) for r in rows):
            keys = rows[0].keys()
            if all(r.keys() == keys for r in rows):
                try:
                    return {k: np.asarray([r[k] for r in rows]) for k in keys}
                except (ValueError, TypeError):
                    return rows
        return rows

    # -- interface -----------------------------------------------------------
    def num_rows(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Block:
        raise NotImplementedError

    def to_rows(self) -> list:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        return iter(self.to_rows())

    def to_table(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def to_batch(self, batch_format: str) -> Any:
        """Materialize as a user-facing batch.

        ``"dict"``/``"numpy"`` → dict of numpy columns; ``"rows"`` → list.
        A bare-ndarray block round-trips to the array itself under "numpy"
        (reference's simple-dataset ergonomics).
        """
        if batch_format == "rows":
            return self.to_rows()
        tbl = self.to_table()
        if batch_format == "numpy" and set(tbl) == {"data"}:
            return tbl["data"]
        if batch_format in ("numpy", "dict"):
            return tbl
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def schema(self) -> Any:
        raise NotImplementedError


class _TableAccessor(BlockAccessor):
    def __init__(self, table: Dict[str, np.ndarray]):
        self._t = {k: np.asarray(v) for k, v in table.items()}

    def num_rows(self) -> int:
        if not self._t:
            return 0
        return len(next(iter(self._t.values())))

    def size_bytes(self) -> int:
        return int(sum(v.nbytes for v in self._t.values()))

    def slice(self, start: int, end: int) -> Block:
        return {k: v[start:end] for k, v in self._t.items()}

    def to_rows(self) -> list:
        keys = list(self._t)
        n = self.num_rows()
        return [{k: self._t[k][i] for k in keys} for i in range(n)]

    def to_table(self) -> Dict[str, np.ndarray]:
        return dict(self._t)

    def schema(self):
        return {k: v.dtype for k, v in self._t.items()}


class _ListAccessor(BlockAccessor):
    def __init__(self, rows: list):
        self._rows = rows

    def num_rows(self) -> int:
        return len(self._rows)

    def size_bytes(self) -> int:
        # cheap estimate; exact pickled size is not worth computing per block
        return sum(getattr(r, "nbytes", 64) for r in self._rows)

    def slice(self, start: int, end: int) -> Block:
        return self._rows[start:end]

    def to_rows(self) -> list:
        return list(self._rows)

    def to_table(self) -> Dict[str, np.ndarray]:
        b = BlockAccessor.from_rows(self._rows)
        if isinstance(b, dict):
            return b
        try:
            return {"data": np.asarray(self._rows)}
        except (ValueError, TypeError):
            raise TypeError("list block is not convertible to columns; "
                            "use batch_format='rows'") from None

    def schema(self):
        return type(self._rows[0]) if self._rows else None
