"""Dataset — lazy, streaming, block-partitioned datasets.

Role-equivalent to the reference's Dataset (reference:
python/ray/data/dataset.py:153 with the logical-plan machinery under
data/_internal/logical/). Redesigned TPU-first:

  - a Dataset is a list of picklable read thunks plus a linear chain of
    per-block transforms — no operator DAG, because the TPU ingest path is
    a straight line ending in a host→device feed;
  - execution is the streaming executor (one fused task per block, bounded
    in-flight window — see _internal/streaming_executor.py);
  - ``iter_batches`` re-chunks rows to EXACT batch_size across block
    boundaries so downstream jitted programs see one static shape
    (XLA recompiles per shape; the reference has no such constraint).
"""

from __future__ import annotations

import copy
import inspect
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, block_meta
from ray_tpu.data._internal.streaming_executor import (
    ExecStats, execute_streaming)


#: internal transform signature: fn(block, block_index) -> block; the index
#: lets stateless per-block transforms derive distinct randomness per block
_Transform = Callable[[Block, int], Block]


@dataclass
class _Plan:
    """read thunks + fused transform chain (+ executor knobs)."""
    read_fns: List[Callable[[], Block]]
    transforms: List[_Transform] = field(default_factory=list)
    limit_rows: Optional[int] = None
    max_in_flight: int = 8
    ray_remote_args: Dict[str, Any] = field(default_factory=dict)

    def fused(self) -> Optional[_Transform]:
        if not self.transforms:
            return None
        chain = list(self.transforms)

        def _fused(block: Block, idx: int) -> Block:
            for t in chain:
                block = t(block, idx)
            return block
        return _fused


def _map_rows_transform(fn: Callable[[Any], Any]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        rows = BlockAccessor.for_block(block).to_rows()
        return BlockAccessor.from_rows([fn(r) for r in rows])
    return _t


def _flat_map_transform(fn: Callable[[Any], Sequence[Any]]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        out: List[Any] = []
        for r in BlockAccessor.for_block(block).to_rows():
            out.extend(fn(r))
        return BlockAccessor.from_rows(out)
    return _t


def _filter_transform(fn: Callable[[Any], bool]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        rows = BlockAccessor.for_block(block).to_rows()
        return BlockAccessor.from_rows([r for r in rows if fn(r)])
    return _t


def _map_batches_transform(fn, batch_format: str,
                           batch_size: Optional[int]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        if batch_size is None or n <= batch_size:
            return _normalize_batch(fn(acc.to_batch(batch_format)))
        outs = []
        for s in range(0, n, batch_size):
            sub = BlockAccessor.for_block(acc.slice(s, min(s + batch_size, n)))
            outs.append(_normalize_batch(fn(sub.to_batch(batch_format))))
        return BlockAccessor.concat(outs)
    return _t


def _normalize_batch(batch: Any) -> Block:
    if isinstance(batch, (dict, np.ndarray, list)):
        return batch
    raise TypeError(
        f"map_batches fn must return dict/ndarray/list, got {type(batch)}")


def _shuffle_transform(seed: int) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        # seed per (epoch seed, block index): a single seed would permute
        # every same-size block identically, correlating rows across blocks
        perm = np.random.default_rng((seed, idx)).permutation(n)
        if isinstance(block, dict):
            return {k: v[perm] for k, v in acc.to_table().items()}
        if isinstance(block, np.ndarray):
            return block[perm]
        rows = acc.to_rows()
        return [rows[i] for i in perm]
    return _t


def _copy_chunk(b: Block) -> Block:
    """Per-block COPY of a slice — binding views would make every
    downstream task cloudpickle the whole source block (numpy views
    pickle only their elements, but deep-copy drops the base ref)."""
    if isinstance(b, dict):
        return {k: np.array(v) for k, v in b.items()}
    if isinstance(b, np.ndarray):
        return np.array(b)
    return list(b)


def _slice_into_reads(block: Block, num_blocks: int) -> List[Callable[[], Block]]:
    """Near-even re-slice of one block into num_blocks copied read thunks
    (shared by repartition and zip)."""
    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    reads = []
    for i in range(num_blocks):
        s, e = i * n // num_blocks, (i + 1) * n // num_blocks
        chunk = _copy_chunk(acc.slice(s, e))
        reads.append(lambda _c=chunk: _c)
    return reads


class Dataset:
    def __init__(self, plan: _Plan):
        self._plan = plan
        self._last_stats: Optional[ExecStats] = None

    # ---------------------------------------------------------- transforms
    def _with_transform(self, t: Callable[[Block], Block]) -> "Dataset":
        plan = copy.copy(self._plan)
        plan.transforms = self._plan.transforms + [t]
        return Dataset(plan)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_transform(_map_rows_transform(fn))

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        return self._with_transform(_flat_map_transform(fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with_transform(_filter_transform(fn))

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_format: str = "dict",
                    batch_size: Optional[int] = None,
                    compute: Optional["ActorPoolStrategy"] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None
                    ) -> "Dataset":
        """Per-batch transform. With ``compute=ActorPoolStrategy(n)`` and
        a CLASS for ``fn``, batches run on a pool of n stateful actors —
        the class is constructed once per actor (model-per-actor
        inference; reference: ActorPoolMapOperator,
        data/_internal/execution/operators/actor_pool_map_operator.py)."""
        if compute is not None or inspect.isclass(fn):
            if not inspect.isclass(fn):
                raise ValueError(
                    "compute=ActorPoolStrategy requires a class UDF "
                    "(constructed once per pool actor)")
            compute = compute or ActorPoolStrategy()
            return _ActorStageDataset(
                upstream=self, cls=fn,
                ctor_args=tuple(fn_constructor_args),
                ctor_kwargs=dict(fn_constructor_kwargs or {}),
                size=compute.size, batch_format=batch_format,
                batch_size=batch_size,
                ray_remote_args=dict(self._plan.ray_remote_args))
        return self._with_transform(
            _map_batches_transform(fn, batch_format, batch_size))

    # ----------------------------------------------------- shuffle family

    def _materialize_exact(self) -> "MaterializedDataset":
        """Materialize with limit_rows APPLIED to the stored blocks.
        materialize() only stops submission at the limit — the boundary
        block keeps its extra rows, which exchange-based ops (sort/
        groupby) would otherwise process and silently un-limit."""
        if self._plan.limit_rows is None:
            return self.materialize()

        @ray_tpu.remote
        def trunc(block: Block, n: int) -> Block:
            acc = BlockAccessor.for_block(block)
            sub = acc.slice(0, n)
            # slices are views into the parent block: copy so the stored
            # object doesn't pin the untruncated original
            if isinstance(sub, dict):
                return {k: np.array(v) for k, v in sub.items()}
            if isinstance(sub, np.ndarray):
                return np.array(sub)
            return list(sub)

        refs: List[Any] = []
        budget = self._plan.limit_rows
        for ref, meta in self._execute():
            if budget <= 0:
                break
            take = min(meta["num_rows"], budget)
            refs.append(ref if take == meta["num_rows"]
                        else trunc.remote(ref, take))
            budget -= take
        return MaterializedDataset(refs)

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        """Global sort via range-partition exchange (reference:
        dataset.sort -> SortTaskSpec sample + range partition + per-range
        sort, data/_internal/planner/exchange/sort_task_spec.py)."""
        from ray_tpu.data._internal import shuffle as sh
        mat = self._materialize_exact()
        refs = mat._refs  # noqa: SLF001
        if not refs:
            return mat
        num_parts = max(1, len(refs))
        kf = sh.key_fn(key)

        # sample each block for range boundaries (one small task per block)
        @ray_tpu.remote
        def sample(block, k=32):
            rows = BlockAccessor.for_block(block).to_rows()
            if not rows:
                return []
            idx = np.linspace(0, len(rows) - 1,
                              min(k, len(rows))).astype(int)
            return [kf(rows[i]) for i in idx]

        samples: List[Any] = []
        for part in ray_tpu.get([sample.remote(r) for r in refs],
                                timeout=600):
            samples.extend(part)
        samples.sort()
        if not samples:
            return mat
        # fewer samples than partitions (tiny/ragged datasets) would index
        # negatively and build non-monotonic boundaries -> silent missort
        num_parts = min(num_parts, len(samples))
        boundaries = [samples[max(0, (i + 1) * len(samples)
                                  // num_parts - 1)]
                      for i in range(num_parts - 1)]
        out = sh.exchange(
            refs, sh._map_range_partition, (key, boundaries),
            sh._reduce_sort, (key, descending), num_parts,
            ray_remote_args=self._plan.ray_remote_args)
        if descending:
            out = list(reversed(out))
        return MaterializedDataset(out)

    def groupby(self, key) -> "GroupedData":
        """Hash-partition the dataset by key for aggregation /
        per-group transforms (reference: dataset.groupby -> GroupedData,
        grouped_data.py over the aggregate exchange)."""
        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Whole-dataset aggregation (single implicit group)."""
        gd = GroupedData(self, key=None, whole=True)
        rows = gd.aggregate(*aggs).take_all()
        if not rows:
            return {}
        row = dict(rows[0])
        row.pop("key", None)
        return row

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle block order globally + rows within each block.

        An approximation of the reference's all-to-all shuffle
        (data/_internal/planner/exchange/) that never materializes the
        dataset — adequate for training-epoch decorrelation; not a uniform
        global permutation.
        """
        rng = random.Random(seed)
        plan = copy.copy(self._plan)
        plan.read_fns = list(self._plan.read_fns)
        rng.shuffle(plan.read_fns)
        plan.transforms = self._plan.transforms + [
            _shuffle_transform(rng.randrange(2**31))]
        return Dataset(plan)

    def limit(self, n: int) -> "Dataset":
        plan = copy.copy(self._plan)
        plan.limit_rows = n if plan.limit_rows is None \
            else min(plan.limit_rows, n)
        return Dataset(plan)

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets. Each side's transform chain is baked into
        its read thunks so the union has a single (empty) chain."""
        def _baked(ds: "Dataset") -> List[Callable[[], Block]]:
            if type(ds)._execute is not Dataset._execute:
                # custom execution (e.g. an actor-pool stage): its plan has
                # no read thunks — materialize to capture its real blocks
                ds = ds.materialize()
            fused = ds._plan.fused()
            if fused is None:
                return list(ds._plan.read_fns)

            def bake(rf, i, _fused=fused):
                return lambda: _fused(rf(), i)
            return [bake(rf, i)
                    for i, rf in enumerate(ds._plan.read_fns)]

        for ds in (self, *others):
            if ds._plan.limit_rows is not None:
                raise ValueError("union after limit is not supported")
        reads: List[Callable[[], Block]] = []
        for ds in (self, *others):
            reads.extend(_baked(ds))
        return Dataset(_Plan(read_fns=reads,
                             max_in_flight=self._plan.max_in_flight,
                             ray_remote_args=dict(self._plan.ray_remote_args)))

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-wise combine with another dataset of the SAME length
        (reference: dataset.py zip): dict blocks merge columns (right
        side's colliding names get a ``_1`` suffix, as the reference
        suffixes duplicates); other block kinds pair rows into tuples.
        Both sides materialize — zip is an alignment barrier by nature."""
        left = self._materialize_exact()
        right = other._materialize_exact()
        lb = [ray_tpu.get(r) for r in left._refs]    # noqa: SLF001
        rb = [ray_tpu.get(r) for r in right._refs]   # noqa: SLF001
        la = BlockAccessor.concat(lb) if lb else []
        ra = BlockAccessor.concat(rb) if rb else []
        lacc = BlockAccessor.for_block(la)
        racc = BlockAccessor.for_block(ra)
        if lacc.num_rows() != racc.num_rows():
            raise ValueError(
                f"zip needs equal lengths, got {lacc.num_rows()} vs "
                f"{racc.num_rows()}")
        if isinstance(la, dict) and isinstance(ra, dict):
            merged = dict(la)
            for k, v in ra.items():
                name = k
                i = 1
                while name in merged:   # find a FREE suffix — writing to
                    name = f"{k}_{i}"   # an occupied one would clobber a
                    i += 1              # left-side column silently
                merged[name] = v
            combined: Block = merged
        else:
            lrows = lacc.to_rows()
            rrows = racc.to_rows()
            combined = [(a, b) for a, b in zip(lrows, rrows)]
        # preserve the left side's block count so parallelism carries over
        return Dataset(_Plan(
            read_fns=_slice_into_reads(combined, max(1, len(lb)))))

    def split(self, n: int) -> List["Dataset"]:
        """Round-robin block partition into n shards (reference:
        dataset.py streaming_split's per-consumer sharding role), used to
        give each train worker a disjoint shard."""
        if n <= 0:
            raise ValueError("split(n) needs n >= 1")
        shards: List[Dataset] = []
        for i in range(n):
            plan = copy.copy(self._plan)
            plan.read_fns = self._plan.read_fns[i::n]
            plan.transforms = list(self._plan.transforms)
            shards.append(Dataset(plan))
        return shards

    def repartition(self, num_blocks: int) -> "Dataset":
        """Materialize then re-slice into num_blocks near-even blocks
        (sizes differ by at most one row; blocks are empty only when the
        dataset has fewer rows than num_blocks)."""
        mat = self.materialize()
        block = BlockAccessor.concat(
            [ray_tpu.get(r) for r in mat._refs])  # noqa: SLF001
        return Dataset(_Plan(
            read_fns=_slice_into_reads(block, num_blocks)))

    # ---------------------------------------------------------- execution
    def _execute(self) -> Iterator:
        stats = ExecStats()
        self._last_stats = stats
        return execute_streaming(
            self._plan.read_fns, self._plan.fused(),
            max_in_flight=self._plan.max_in_flight,
            limit_rows=self._plan.limit_rows,
            stats=stats,
            ray_remote_args=self._plan.ray_remote_args)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "dict",
                     drop_last: bool = False) -> Iterator[Any]:
        """Stream exact-size batches, re-chunking across block boundaries.

        Blocks are buffered as (accessor, offset) and consumed by advancing
        the offset — table slices are numpy views, so each row is copied at
        most once (by the concat of a boundary-straddling batch), never
        re-concatenated per yielded batch.
        """
        budget = self._plan.limit_rows
        buf: List[BlockAccessor] = []
        head_off = 0  # consumed rows of buf[0]
        buffered = 0

        def emit(k: int) -> Block:
            nonlocal head_off, buffered
            parts: List[Block] = []
            need = k
            while need:
                acc = buf[0]
                avail = acc.num_rows() - head_off
                take = min(avail, need)
                parts.append(acc.slice(head_off, head_off + take))
                head_off += take
                need -= take
                buffered -= take
                if head_off == acc.num_rows():
                    buf.pop(0)
                    head_off = 0
            merged = parts[0] if len(parts) == 1 \
                else BlockAccessor.concat(parts)
            return BlockAccessor.for_block(merged).to_batch(batch_format)

        for block_ref, meta in self._execute():
            block = ray_tpu.get(block_ref)
            acc = BlockAccessor.for_block(block)
            if budget is not None:
                take = min(acc.num_rows(), budget)
                acc = BlockAccessor.for_block(acc.slice(0, take))
                budget -= take
            if acc.num_rows():
                buf.append(acc)
                buffered += acc.num_rows()
            while buffered >= batch_size:
                yield emit(batch_size)
            if budget is not None and budget <= 0:
                break
        if buffered and not drop_last:
            yield emit(buffered)

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=4096, batch_format="rows"):
            yield from batch

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        if self._plan.limit_rows is not None:
            return sum(1 for _ in self.iter_rows())
        total = 0
        for _, meta in self._execute():
            total += meta["num_rows"]
        return total

    def schema(self) -> Any:
        for block_ref, _ in self._execute():
            return BlockAccessor.for_block(ray_tpu.get(block_ref)).schema()
        return None

    def materialize(self) -> "MaterializedDataset":
        refs = [block_ref for block_ref, _ in self._execute()]
        return MaterializedDataset(refs, limit_rows=self._plan.limit_rows)

    # --------------------------------------------------------------- output

    def to_pandas(self):
        """Whole dataset as one pandas DataFrame (reference:
        dataset.py to_pandas). Assembled from columnar batches — no
        per-row dict churn for table datasets."""
        import pandas as pd
        parts = list(self.iter_batches(batch_size=65536,
                                       batch_format="dict"))
        if not parts:
            return pd.DataFrame()
        first = parts[0]
        if isinstance(first, dict) and first and \
                all(isinstance(v, np.ndarray) for v in first.values()):
            cols = {k: np.concatenate([p[k] for p in parts])
                    for k in first}
            return pd.DataFrame(cols)
        rows = [r for p in parts
                for r in BlockAccessor.for_block(p).to_rows()]
        if rows and isinstance(rows[0], dict):
            return pd.DataFrame(rows)
        return pd.DataFrame({"value": rows})

    def _write_blocks(self, path: str, suffix: str,
                      write_one: Callable[[Block, str], None]) -> List[str]:
        """Write one file per block via remote tasks (reference:
        data write tasks fan out per block). Returns written paths."""
        import os
        os.makedirs(path, exist_ok=True)
        src = self
        if self._plan.limit_rows is not None:
            # _execute() only stops SUBMISSION at the limit: the boundary
            # block keeps its overshoot rows; materialize-exact truncates
            src = self._materialize_exact()

        @ray_tpu.remote
        def _write(block: Block, out_path: str) -> str:
            write_one(block, out_path)
            return out_path

        refs = []
        for i, (block_ref, meta) in enumerate(src._execute()):
            out_path = os.path.join(path, f"part-{i:05d}{suffix}")
            refs.append(_write.remote(block_ref, out_path))
        return ray_tpu.get(refs)

    def write_json(self, path: str) -> List[str]:
        """One JSON-lines file per block under ``path`` (reference:
        dataset.py write_json)."""
        def write_one(block: Block, out_path: str) -> None:
            import json
            acc = BlockAccessor.for_block(block)

            def clean(r):
                if isinstance(r, dict):
                    return {k: v.tolist() if hasattr(v, "tolist") else v
                            for k, v in r.items()}
                return r.tolist() if hasattr(r, "tolist") else r
            with open(out_path, "w") as f:
                for r in acc.to_rows():
                    f.write(json.dumps(clean(r)) + "\n")
        return self._write_blocks(path, ".jsonl", write_one)

    def write_csv(self, path: str) -> List[str]:
        """One CSV file per block under ``path`` (reference:
        dataset.py write_csv). Requires dict (columnar) blocks."""
        def write_one(block: Block, out_path: str) -> None:
            import csv
            acc = BlockAccessor.for_block(block)
            rows = acc.to_rows()
            if rows and not isinstance(rows[0], dict):
                rows = [{"value": r} for r in rows]
            cols = list(rows[0].keys()) if rows else []
            with open(out_path, "w", newline="") as f:
                w = csv.DictWriter(f, fieldnames=cols)
                w.writeheader()
                for r in rows:
                    w.writerow({k: (v.item() if hasattr(v, "item") else v)
                                for k, v in r.items()})
        return self._write_blocks(path, ".csv", write_one)

    def write_parquet(self, path: str) -> List[str]:
        """One parquet file per block under ``path`` (reference:
        dataset.py write_parquet). Gated on pyarrow."""
        try:
            import pyarrow  # noqa: F401
        except ImportError as e:
            raise ImportError("write_parquet requires pyarrow; use "
                              "write_json/write_csv") from e

        def write_one(block: Block, out_path: str) -> None:
            import pyarrow as pa
            import pyarrow.parquet as pq
            acc = BlockAccessor.for_block(block)
            table = acc.to_table()
            pq.write_table(
                pa.table({k: np.asarray(v) for k, v in table.items()}),
                out_path)
        return self._write_blocks(path, ".parquet", write_one)

    def write_npy(self, path: str) -> List[str]:
        """One .npy file per block under ``path`` — TENSOR datasets only
        (a dict/row block would pickle into an object array that
        read_npy's allow_pickle=False then refuses to load; use
        write_parquet/write_json for tables)."""
        def write_one(block: Block, out_path: str) -> None:
            if not isinstance(block, np.ndarray):
                arr = np.asarray(block)
                if arr.dtype == object:
                    raise TypeError(
                        "write_npy needs tensor blocks; this dataset has "
                        f"{type(block).__name__} blocks — use "
                        "write_parquet or write_json")
            else:
                arr = block
            np.save(out_path, arr)
        return self._write_blocks(path, ".npy", write_one)

    def iterator(self):
        """A DataIterator over this dataset (reference: dataset.py
        iterator() -> DataIterator)."""
        from ray_tpu.data.iterator import DataIterator
        return DataIterator(self)

    def num_blocks(self) -> int:
        return len(self._plan.read_fns)

    def stats(self) -> Dict[str, Any]:
        return self._last_stats.summary() if self._last_stats else {}

    def __repr__(self) -> str:
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"num_transforms={len(self._plan.transforms)})")


class MaterializedDataset(Dataset):
    """A Dataset whose blocks already live in the object store; holding the
    MaterializedDataset pins them (refcount via the held ObjectRefs)."""

    def __init__(self, refs: List[ray_tpu.ObjectRef],
                 limit_rows: Optional[int] = None):
        self._refs = list(refs)

        def mk(ref):
            return lambda: ray_tpu.get(ref)
        super().__init__(_Plan(read_fns=[mk(r) for r in self._refs],
                               limit_rows=limit_rows))


class GroupedData:
    """Result of ``ds.groupby(key)`` (reference: data/grouped_data.py)."""

    def __init__(self, ds: Dataset, key, whole: bool = False):
        self._ds = ds
        self._key = key
        # whole=True: single implicit group (Dataset.aggregate)
        self._whole = whole

    def _exchange(self, reduce_fn, reduce_args) -> Dataset:
        from ray_tpu.data._internal import shuffle as sh
        mat = self._ds._materialize_exact()
        refs = mat._refs  # noqa: SLF001
        if not refs:
            return mat
        num_parts = 1 if self._whole else max(1, len(refs))
        key = (lambda r: 0) if self._whole else self._key
        out = sh.exchange(
            refs, sh._map_hash_partition, (key, num_parts),
            reduce_fn, reduce_args, num_parts,
            ray_remote_args=self._ds._plan.ray_remote_args)
        return MaterializedDataset(out)

    def aggregate(self, *aggs) -> Dataset:
        """One output row per group: the key plus one column per
        aggregation (AggregateFn instances)."""
        from ray_tpu.data._internal import shuffle as sh
        specs = [(a.name, a.fn) for a in aggs]
        key = (lambda r: 0) if self._whole else self._key
        return self._exchange(sh._reduce_groups, (key, specs))

    def map_groups(self, fn) -> Dataset:
        """Apply ``fn(rows) -> row | list[row]`` per group (reference:
        grouped_data.map_groups)."""
        from ray_tpu.data._internal import shuffle as sh
        key = (lambda r: 0) if self._whole else self._key
        return self._exchange(sh._reduce_map_groups, (key, fn))

    def count(self) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.count())

    def sum(self, col=None) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.sum(col))

    def mean(self, col=None) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.mean(col))

    def min(self, col=None) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.min(col))

    def max(self, col=None) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.max(col))

    def std(self, col=None) -> Dataset:
        from ray_tpu.data._internal.shuffle import AggregateFn
        return self.aggregate(AggregateFn.std(col))


class ActorPoolStrategy:
    """Compute strategy for stateful map_batches (reference:
    data/_internal/compute.py ActorPoolStrategy — fixed size here; the
    reference's min/max autoscaling rides the serve autoscaler design)."""

    def __init__(self, size: int = 2):
        if size < 1:
            raise ValueError("ActorPoolStrategy size must be >= 1")
        self.size = size


class _BatchMapWorker:
    """Pool actor hosting one constructed UDF instance."""

    def __init__(self, cls_blob: bytes, args: tuple, kwargs: dict):
        import cloudpickle
        self._fn = cloudpickle.loads(cls_blob)(*args, **kwargs)

    def apply(self, block: Block, batch_format: str,
              batch_size: Optional[int]):
        t = _map_batches_transform(self._fn, batch_format, batch_size)
        out = t(block, 0)
        return out, block_meta(out)


class _ActorStageDataset(Dataset):
    """Dataset whose execution feeds upstream blocks through a pool of
    stateful actors (reference: ActorPoolMapOperator). Transforms chained
    AFTER this stage run as ordinary fused tasks on the stage's outputs."""

    def __init__(self, upstream: Dataset, cls, ctor_args: tuple,
                 ctor_kwargs: dict, size: int, batch_format: str,
                 batch_size: Optional[int],
                 ray_remote_args: Dict[str, Any]):
        super().__init__(_Plan(read_fns=[],
                               ray_remote_args=dict(ray_remote_args),
                               limit_rows=upstream._plan.limit_rows))
        self._upstream = upstream
        self._cls = cls
        self._ctor_args = ctor_args
        self._ctor_kwargs = ctor_kwargs
        self._size = size
        self._batch_format = batch_format
        self._batch_size = batch_size

    def _clone(self) -> "_ActorStageDataset":
        clone = _ActorStageDataset(
            self._upstream, self._cls, self._ctor_args, self._ctor_kwargs,
            self._size, self._batch_format, self._batch_size,
            dict(self._plan.ray_remote_args))
        clone._plan.transforms = list(self._plan.transforms)
        clone._plan.limit_rows = self._plan.limit_rows
        return clone

    def _with_transform(self, t) -> "Dataset":
        clone = self._clone()
        clone._plan.transforms = clone._plan.transforms + [t]
        return clone

    def num_blocks(self) -> int:
        return self._upstream.num_blocks()

    def split(self, n: int) -> List["Dataset"]:
        return self.materialize().split(n)

    def union(self, *others: "Dataset") -> "Dataset":
        return self.materialize().union(*others)

    def limit(self, n: int) -> "Dataset":
        # base limit() rebuilds a plain Dataset from our plan, whose
        # read_fns is [] (blocks flow through _execute) — every row would
        # silently vanish. Clone the stage and let iter_batches' row
        # budget enforce the cap.
        clone = self._clone()
        clone._plan.limit_rows = n if self._plan.limit_rows is None \
            else min(self._plan.limit_rows, n)
        return clone

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self.materialize().random_shuffle(seed=seed)

    def repartition(self, num_blocks: int) -> "Dataset":
        return self.materialize().repartition(num_blocks)

    def _execute(self) -> Iterator:
        import time as _time

        import cloudpickle
        stats = ExecStats()
        self._last_stats = stats
        cls_blob = cloudpickle.dumps(self._cls)
        worker_cls = ray_tpu.remote(_BatchMapWorker)
        if self._plan.ray_remote_args:
            worker_cls = worker_cls.options(**self._plan.ray_remote_args)
        actors = [worker_cls.remote(cls_blob, self._ctor_args,
                                    self._ctor_kwargs)
                  for _ in range(self._size)]
        fused = self._plan.fused()

        @ray_tpu.remote(num_returns=2)
        def _post(block: Block, idx: int):
            out = fused(block, idx)
            return out, block_meta(out)

        t0 = _time.monotonic()

        def emit(pair):
            block_ref, meta_ref = pair
            meta = ray_tpu.get(meta_ref, timeout=600)
            stats.tasks += 1
            stats.rows += meta["num_rows"]
            stats.bytes += meta["size_bytes"]
            stats.wall_s = _time.monotonic() - t0
            return block_ref, meta

        # round-robin over the pool with a bounded window; results yield
        # in submission order (actor method queues keep per-actor FIFO, so
        # each actor runs one batch at a time — the statefulness contract)
        window: List[tuple] = []
        cap = max(2, 2 * self._size)
        try:
            idx = 0
            for block_ref, _ in self._upstream._execute():
                actor = actors[idx % self._size]
                pair = actor.apply.options(num_returns=2).remote(
                    block_ref, self._batch_format, self._batch_size)
                if fused is not None:
                    pair = _post.remote(pair[0], idx)
                window.append(pair)
                idx += 1
                while len(window) >= cap:
                    yield emit(window.pop(0))
            while window:
                yield emit(window.pop(0))
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass
