"""Dataset — lazy, streaming, block-partitioned datasets.

Role-equivalent to the reference's Dataset (reference:
python/ray/data/dataset.py:153 with the logical-plan machinery under
data/_internal/logical/). Redesigned TPU-first:

  - a Dataset is a list of picklable read thunks plus a linear chain of
    per-block transforms — no operator DAG, because the TPU ingest path is
    a straight line ending in a host→device feed;
  - execution is the streaming executor (one fused task per block, bounded
    in-flight window — see _internal/streaming_executor.py);
  - ``iter_batches`` re-chunks rows to EXACT batch_size across block
    boundaries so downstream jitted programs see one static shape
    (XLA recompiles per shape; the reference has no such constraint).
"""

from __future__ import annotations

import copy
import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data._internal.streaming_executor import (
    ExecStats, execute_streaming)


#: internal transform signature: fn(block, block_index) -> block; the index
#: lets stateless per-block transforms derive distinct randomness per block
_Transform = Callable[[Block, int], Block]


@dataclass
class _Plan:
    """read thunks + fused transform chain (+ executor knobs)."""
    read_fns: List[Callable[[], Block]]
    transforms: List[_Transform] = field(default_factory=list)
    limit_rows: Optional[int] = None
    max_in_flight: int = 8
    ray_remote_args: Dict[str, Any] = field(default_factory=dict)

    def fused(self) -> Optional[_Transform]:
        if not self.transforms:
            return None
        chain = list(self.transforms)

        def _fused(block: Block, idx: int) -> Block:
            for t in chain:
                block = t(block, idx)
            return block
        return _fused


def _map_rows_transform(fn: Callable[[Any], Any]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        rows = BlockAccessor.for_block(block).to_rows()
        return BlockAccessor.from_rows([fn(r) for r in rows])
    return _t


def _flat_map_transform(fn: Callable[[Any], Sequence[Any]]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        out: List[Any] = []
        for r in BlockAccessor.for_block(block).to_rows():
            out.extend(fn(r))
        return BlockAccessor.from_rows(out)
    return _t


def _filter_transform(fn: Callable[[Any], bool]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        rows = BlockAccessor.for_block(block).to_rows()
        return BlockAccessor.from_rows([r for r in rows if fn(r)])
    return _t


def _map_batches_transform(fn, batch_format: str,
                           batch_size: Optional[int]) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        if batch_size is None or n <= batch_size:
            return _normalize_batch(fn(acc.to_batch(batch_format)))
        outs = []
        for s in range(0, n, batch_size):
            sub = BlockAccessor.for_block(acc.slice(s, min(s + batch_size, n)))
            outs.append(_normalize_batch(fn(sub.to_batch(batch_format))))
        return BlockAccessor.concat(outs)
    return _t


def _normalize_batch(batch: Any) -> Block:
    if isinstance(batch, (dict, np.ndarray, list)):
        return batch
    raise TypeError(
        f"map_batches fn must return dict/ndarray/list, got {type(batch)}")


def _shuffle_transform(seed: int) -> _Transform:
    def _t(block: Block, idx: int) -> Block:
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        # seed per (epoch seed, block index): a single seed would permute
        # every same-size block identically, correlating rows across blocks
        perm = np.random.default_rng((seed, idx)).permutation(n)
        if isinstance(block, dict):
            return {k: v[perm] for k, v in acc.to_table().items()}
        if isinstance(block, np.ndarray):
            return block[perm]
        rows = acc.to_rows()
        return [rows[i] for i in perm]
    return _t


class Dataset:
    def __init__(self, plan: _Plan):
        self._plan = plan
        self._last_stats: Optional[ExecStats] = None

    # ---------------------------------------------------------- transforms
    def _with_transform(self, t: Callable[[Block], Block]) -> "Dataset":
        plan = copy.copy(self._plan)
        plan.transforms = self._plan.transforms + [t]
        return Dataset(plan)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._with_transform(_map_rows_transform(fn))

    def flat_map(self, fn: Callable[[Any], Sequence[Any]]) -> "Dataset":
        return self._with_transform(_flat_map_transform(fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._with_transform(_filter_transform(fn))

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_format: str = "dict",
                    batch_size: Optional[int] = None) -> "Dataset":
        return self._with_transform(
            _map_batches_transform(fn, batch_format, batch_size))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Shuffle block order globally + rows within each block.

        An approximation of the reference's all-to-all shuffle
        (data/_internal/planner/exchange/) that never materializes the
        dataset — adequate for training-epoch decorrelation; not a uniform
        global permutation.
        """
        rng = random.Random(seed)
        plan = copy.copy(self._plan)
        plan.read_fns = list(self._plan.read_fns)
        rng.shuffle(plan.read_fns)
        plan.transforms = self._plan.transforms + [
            _shuffle_transform(rng.randrange(2**31))]
        return Dataset(plan)

    def limit(self, n: int) -> "Dataset":
        plan = copy.copy(self._plan)
        plan.limit_rows = n if plan.limit_rows is None \
            else min(plan.limit_rows, n)
        return Dataset(plan)

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets. Each side's transform chain is baked into
        its read thunks so the union has a single (empty) chain."""
        def _baked(ds: "Dataset") -> List[Callable[[], Block]]:
            fused = ds._plan.fused()
            if fused is None:
                return list(ds._plan.read_fns)

            def bake(rf, i, _fused=fused):
                return lambda: _fused(rf(), i)
            return [bake(rf, i)
                    for i, rf in enumerate(ds._plan.read_fns)]

        for ds in (self, *others):
            if ds._plan.limit_rows is not None:
                raise ValueError("union after limit is not supported")
        reads: List[Callable[[], Block]] = []
        for ds in (self, *others):
            reads.extend(_baked(ds))
        return Dataset(_Plan(read_fns=reads,
                             max_in_flight=self._plan.max_in_flight,
                             ray_remote_args=dict(self._plan.ray_remote_args)))

    def split(self, n: int) -> List["Dataset"]:
        """Round-robin block partition into n shards (reference:
        dataset.py streaming_split's per-consumer sharding role), used to
        give each train worker a disjoint shard."""
        if n <= 0:
            raise ValueError("split(n) needs n >= 1")
        shards: List[Dataset] = []
        for i in range(n):
            plan = copy.copy(self._plan)
            plan.read_fns = self._plan.read_fns[i::n]
            plan.transforms = list(self._plan.transforms)
            shards.append(Dataset(plan))
        return shards

    def repartition(self, num_blocks: int) -> "Dataset":
        """Materialize then re-slice into num_blocks near-even blocks
        (sizes differ by at most one row; blocks are empty only when the
        dataset has fewer rows than num_blocks)."""
        mat = self.materialize()
        block = BlockAccessor.concat(
            [ray_tpu.get(r) for r in mat._refs])  # noqa: SLF001
        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()

        # Bind per-block COPIES, not a closure over the full concatenated
        # block — otherwise every downstream task/shard would cloudpickle
        # the entire dataset (numpy views pickle only their own elements,
        # and deep-copying also drops the base-array reference).
        def copy_chunk(b: Block) -> Block:
            if isinstance(b, dict):
                return {k: np.array(v) for k, v in b.items()}
            if isinstance(b, np.ndarray):
                return np.array(b)
            return list(b)

        reads = []
        for i in range(num_blocks):
            s, e = i * n // num_blocks, (i + 1) * n // num_blocks
            chunk = copy_chunk(acc.slice(s, e))
            reads.append(lambda _c=chunk: _c)
        return Dataset(_Plan(read_fns=reads))

    # ---------------------------------------------------------- execution
    def _execute(self) -> Iterator:
        stats = ExecStats()
        self._last_stats = stats
        return execute_streaming(
            self._plan.read_fns, self._plan.fused(),
            max_in_flight=self._plan.max_in_flight,
            limit_rows=self._plan.limit_rows,
            stats=stats,
            ray_remote_args=self._plan.ray_remote_args)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "dict",
                     drop_last: bool = False) -> Iterator[Any]:
        """Stream exact-size batches, re-chunking across block boundaries.

        Blocks are buffered as (accessor, offset) and consumed by advancing
        the offset — table slices are numpy views, so each row is copied at
        most once (by the concat of a boundary-straddling batch), never
        re-concatenated per yielded batch.
        """
        budget = self._plan.limit_rows
        buf: List[BlockAccessor] = []
        head_off = 0  # consumed rows of buf[0]
        buffered = 0

        def emit(k: int) -> Block:
            nonlocal head_off, buffered
            parts: List[Block] = []
            need = k
            while need:
                acc = buf[0]
                avail = acc.num_rows() - head_off
                take = min(avail, need)
                parts.append(acc.slice(head_off, head_off + take))
                head_off += take
                need -= take
                buffered -= take
                if head_off == acc.num_rows():
                    buf.pop(0)
                    head_off = 0
            merged = parts[0] if len(parts) == 1 \
                else BlockAccessor.concat(parts)
            return BlockAccessor.for_block(merged).to_batch(batch_format)

        for block_ref, meta in self._execute():
            block = ray_tpu.get(block_ref)
            acc = BlockAccessor.for_block(block)
            if budget is not None:
                take = min(acc.num_rows(), budget)
                acc = BlockAccessor.for_block(acc.slice(0, take))
                budget -= take
            if acc.num_rows():
                buf.append(acc)
                buffered += acc.num_rows()
            while buffered >= batch_size:
                yield emit(batch_size)
            if budget is not None and budget <= 0:
                break
        if buffered and not drop_last:
            yield emit(buffered)

    def iter_rows(self) -> Iterator[Any]:
        for batch in self.iter_batches(batch_size=4096, batch_format="rows"):
            yield from batch

    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def count(self) -> int:
        if self._plan.limit_rows is not None:
            return sum(1 for _ in self.iter_rows())
        total = 0
        for _, meta in self._execute():
            total += meta["num_rows"]
        return total

    def schema(self) -> Any:
        for block_ref, _ in self._execute():
            return BlockAccessor.for_block(ray_tpu.get(block_ref)).schema()
        return None

    def materialize(self) -> "MaterializedDataset":
        refs = [block_ref for block_ref, _ in self._execute()]
        return MaterializedDataset(refs, limit_rows=self._plan.limit_rows)

    def num_blocks(self) -> int:
        return len(self._plan.read_fns)

    def stats(self) -> Dict[str, Any]:
        return self._last_stats.summary() if self._last_stats else {}

    def __repr__(self) -> str:
        return (f"Dataset(num_blocks={self.num_blocks()}, "
                f"num_transforms={len(self._plan.transforms)})")


class MaterializedDataset(Dataset):
    """A Dataset whose blocks already live in the object store; holding the
    MaterializedDataset pins them (refcount via the held ObjectRefs)."""

    def __init__(self, refs: List[ray_tpu.ObjectRef],
                 limit_rows: Optional[int] = None):
        self._refs = list(refs)

        def mk(ref):
            return lambda: ray_tpu.get(ref)
        super().__init__(_Plan(read_fns=[mk(r) for r in self._refs],
                               limit_rows=limit_rows))
