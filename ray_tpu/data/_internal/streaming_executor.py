"""Streaming executor: bounded-in-flight task dispatch over blocks.

Role-equivalent to the reference's StreamingExecutor (reference:
python/ray/data/_internal/execution/streaming_executor.py:48 with
backpressure policies under .../backpressure_policy/). Redesigned for the
common TPU-ingest shape — a linear chain of per-block transforms feeding a
device loop — instead of a general operator DAG:

  - the whole transform chain is FUSED into one task per input block
    (the reference fuses compatible MapOperators the same way), so a block
    crosses the object store exactly twice (produce, consume);
  - backpressure is a sliding in-flight window: at most ``max_in_flight``
    block tasks outstanding, new work submitted only as the consumer drains
    results, so the shm store holds O(window) blocks, not O(dataset);
  - ordering is preserved: blocks are yielded in plan order so iteration is
    deterministic (needed for resumable training epochs).

Block payloads stay in the object store; only (ref, meta) pairs flow here.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, block_meta


@ray_tpu.remote(num_returns=2)
def _run_block_task(read_fn: Callable[[], Block],
                    fused: Optional[Callable[[Block, int], Block]],
                    index: int):
    """Produce one block: run the read, then the fused transform chain.

    Returns (block, meta); meta is small and lands in the owner's memory
    store so the driver can count rows without fetching the block.
    """
    block = read_fn()
    if fused is not None:
        block = fused(block, index)
    return block, block_meta(block)


class ExecStats:
    def __init__(self) -> None:
        self.tasks = 0
        self.rows = 0
        self.bytes = 0
        self.wall_s = 0.0

    def summary(self) -> Dict[str, Any]:
        return {"tasks": self.tasks, "rows": self.rows,
                "bytes": self.bytes, "wall_s": round(self.wall_s, 3)}


def execute_streaming(
    read_fns: List[Callable[[], Block]],
    fused: Optional[Callable[[Block], Block]],
    *,
    max_in_flight: int = 8,
    limit_rows: Optional[int] = None,
    stats: Optional[ExecStats] = None,
    ray_remote_args: Optional[Dict[str, Any]] = None,
) -> Iterator[Tuple[ray_tpu.ObjectRef, Dict[str, Any]]]:
    """Yield (block_ref, meta) in plan order with bounded in-flight work.

    ``limit_rows`` stops *submission* once enough rows are known to be in
    flight — the limit pushdown that lets ``ds.limit(5).take()`` touch one
    block of a thousand-block dataset.
    """
    t0 = time.monotonic()
    task = _run_block_task
    if ray_remote_args:
        task = task.options(num_returns=2, **ray_remote_args)
    window: List[Tuple[Any, Any]] = []  # [(block_ref, meta_ref)] in order
    next_read = 0
    produced_rows = 0  # rows confirmed by fetched metas
    in_flight_budget_open = True

    def _submit_until_full() -> None:
        nonlocal next_read, in_flight_budget_open
        while (in_flight_budget_open and len(window) < max_in_flight
               and next_read < len(read_fns)):
            b, m = task.remote(read_fns[next_read], fused, next_read)
            window.append((b, m))
            next_read += 1

    _submit_until_full()
    while window:
        block_ref, meta_ref = window.pop(0)
        meta = ray_tpu.get(meta_ref)
        produced_rows += meta["num_rows"]
        if stats is not None:
            stats.tasks += 1
            stats.rows += meta["num_rows"]
            stats.bytes += meta["size_bytes"]
            stats.wall_s = time.monotonic() - t0
        if limit_rows is not None and produced_rows >= limit_rows:
            in_flight_budget_open = False
        yield block_ref, meta
        if not in_flight_budget_open:
            break
        _submit_until_full()
