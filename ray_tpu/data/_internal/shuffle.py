"""All-to-all exchange: hash/range partition via tasks + reduce build.

Role-equivalent to the reference's shuffle-family operator planner
(reference: python/ray/data/_internal/planner/exchange/ —
ShuffleTaskSpec map-side partitioning into N outputs, reduce-side build;
operators wired in data/_internal/execution/operators/). Redesigned on
this build's primitives: the map task uses ``num_returns=P`` so each
partition travels as its own object (reduce j pulls only column j of the
partition matrix — the same data movement as the reference's exchange,
without a dedicated shuffle service).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def key_fn(key) -> Callable[[Any], Any]:
    """Row -> sort/group key. A string key indexes dict rows (table
    datasets); a callable is used as-is; None = identity."""
    if key is None:
        return lambda r: r
    if callable(key):
        return key
    return lambda r, _k=key: r[_k]


def _partition_rows(rows: List[Any], part_of: Callable[[Any], int],
                    num_parts: int) -> List[Block]:
    buckets: List[List[Any]] = [[] for _ in range(num_parts)]
    for r in rows:
        buckets[part_of(r)].append(r)
    return [BlockAccessor.from_rows(b) for b in buckets]


def _stable_hash(value: Any) -> int:
    """Process-independent hash: builtin hash() is salted per process
    (PYTHONHASHSEED), so two map workers would send the same string key
    to DIFFERENT partitions — the shuffle would silently split groups."""
    import hashlib
    import pickle
    try:
        blob = pickle.dumps(value, protocol=4)
    except Exception:  # noqa: BLE001 — unpicklable key: fall back to repr
        blob = repr(value).encode()
    return int.from_bytes(hashlib.md5(blob).digest()[:8], "little")


def _map_hash_partition(block: Block, key, num_parts: int) -> tuple:
    if num_parts == 1:
        return block  # single partition: skip per-row hashing entirely
    kf = key_fn(key)
    rows = BlockAccessor.for_block(block).to_rows()
    parts = _partition_rows(
        rows, lambda r: _stable_hash(kf(r)) % num_parts, num_parts)
    return tuple(parts)


def _map_range_partition(block: Block, key, boundaries: list) -> tuple:
    if not boundaries:
        return block  # single partition
    kf = key_fn(key)
    rows = BlockAccessor.for_block(block).to_rows()
    num_parts = len(boundaries) + 1

    def part_of(r):
        import bisect
        return bisect.bisect_right(boundaries, kf(r))
    parts = _partition_rows(rows, part_of, num_parts)
    return tuple(parts)


def exchange(block_refs: List[Any], map_fn: Callable[..., tuple],
             map_args: tuple, reduce_fn: Callable[..., Block],
             reduce_args: tuple, num_parts: int,
             ray_remote_args: Optional[Dict[str, Any]] = None
             ) -> List[Any]:
    """Generic 2-phase exchange: every input block is partitioned into
    ``num_parts`` outputs by a map task; reduce task j builds its final
    block from partition j of every map. Returns the reduce block refs."""
    remote_args = dict(ray_remote_args or {})

    mapper = ray_tpu.remote(map_fn).options(
        num_returns=num_parts, **remote_args)
    part_matrix: List[Sequence[Any]] = []  # [map][part] -> ref
    for ref in block_refs:
        out = mapper.remote(ref, *map_args)
        part_matrix.append((out,) if num_parts == 1 else out)

    reducer = ray_tpu.remote(reduce_fn).options(**remote_args)
    return [reducer.remote(*reduce_args,
                           *[row[j] for row in part_matrix])
            for j in range(num_parts)]


# --------------------------------------------------------------- reducers


def _reduce_sort(key, descending: bool, *parts: Block) -> Block:
    kf = key_fn(key)
    rows: List[Any] = []
    for p in parts:
        rows.extend(BlockAccessor.for_block(p).to_rows())
    rows.sort(key=kf, reverse=descending)
    return BlockAccessor.from_rows(rows)


def _reduce_groups(key, agg_specs: list, *parts: Block) -> Block:
    """Build {key -> rows}, apply each aggregation, one output row per
    group (reference: SortAggregateTaskSpec's combine step)."""
    kf = key_fn(key)
    groups: Dict[Any, List[Any]] = {}
    for p in parts:
        for r in BlockAccessor.for_block(p).to_rows():
            groups.setdefault(kf(r), []).append(r)
    out_rows = []
    key_name = key if isinstance(key, str) else "key"
    for k in sorted(groups, key=lambda x: (str(type(x)), x)):
        rows = groups[k]
        out: Dict[str, Any] = {key_name: k}
        for name, fn in agg_specs:
            out[name] = fn(rows)
        out_rows.append(out)
    return BlockAccessor.from_rows(out_rows)


def _reduce_map_groups(key, fn, *parts: Block) -> Block:
    kf = key_fn(key)
    groups: Dict[Any, List[Any]] = {}
    for p in parts:
        for r in BlockAccessor.for_block(p).to_rows():
            groups.setdefault(kf(r), []).append(r)
    out_rows: List[Any] = []
    for k in sorted(groups, key=lambda x: (str(type(x)), x)):
        res = fn(groups[k])
        if isinstance(res, list):
            out_rows.extend(res)
        else:
            out_rows.append(res)
    return BlockAccessor.from_rows(out_rows)


# ------------------------------------------------------------ aggregations


def _values(rows: List[Any], col: Optional[str]) -> list:
    if col is None:
        return rows
    return [r[col] for r in rows]


class AggregateFn:
    """A named aggregation over a group's rows (reference:
    data/aggregate.py AggregateFn — collapsed to a whole-group callable,
    which is exact because groups are fully assembled reduce-side)."""

    def __init__(self, name: str, fn: Callable[[List[Any]], Any]):
        self.name = name
        self.fn = fn

    @classmethod
    def count(cls) -> "AggregateFn":
        return cls("count()", len)

    @classmethod
    def sum(cls, col: Optional[str] = None) -> "AggregateFn":
        return cls(f"sum({col or ''})",
                   lambda rows: float(np.sum(_values(rows, col))))

    @classmethod
    def mean(cls, col: Optional[str] = None) -> "AggregateFn":
        return cls(f"mean({col or ''})",
                   lambda rows: float(np.mean(_values(rows, col))))

    @classmethod
    def min(cls, col: Optional[str] = None) -> "AggregateFn":
        return cls(f"min({col or ''})",
                   lambda rows: np.min(_values(rows, col)).item())

    @classmethod
    def max(cls, col: Optional[str] = None) -> "AggregateFn":
        return cls(f"max({col or ''})",
                   lambda rows: np.max(_values(rows, col)).item())

    @classmethod
    def std(cls, col: Optional[str] = None) -> "AggregateFn":
        return cls(f"std({col or ''})",
                   lambda rows: float(np.std(_values(rows, col), ddof=1)))
