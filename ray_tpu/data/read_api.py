"""Dataset constructors.

Role-equivalent to the reference's read API (reference:
python/ray/data/read_api.py — range :2367, from_items :87, read_* family
over datasource/). Reads are lazy thunks executed inside block tasks, so
file IO happens on workers, parallel across blocks, never on the driver.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from builtins import range as _builtin_range

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import Dataset, _Plan

_DEFAULT_BLOCK_ROWS = 64 * 1024


def _num_blocks(n_rows: int, override: Optional[int]) -> int:
    if override is not None:
        return max(1, min(override, max(n_rows, 1)))
    return max(1, math.ceil(n_rows / _DEFAULT_BLOCK_ROWS))


def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    """Integers [0, n) as an {"id": int64} table (reference: range())."""
    nb = _num_blocks(n, num_blocks)
    bounds = np.linspace(0, n, nb + 1).astype(np.int64)

    def mk(lo: int, hi: int):
        return lambda: {"id": np.arange(lo, hi, dtype=np.int64)}
    return Dataset(_Plan(read_fns=[
        mk(int(bounds[i]), int(bounds[i + 1])) for i in _builtin_range(nb)]))


def from_items(items: Sequence[Any], *,
               num_blocks: Optional[int] = None) -> Dataset:
    items = list(items)
    nb = _num_blocks(len(items), num_blocks)
    bounds = np.linspace(0, len(items), nb + 1).astype(int)

    def mk(chunk: List[Any]):
        return lambda: BlockAccessor.from_rows(chunk)
    reads = [mk(items[int(bounds[i]):int(bounds[i + 1])])
             for i in _builtin_range(nb)]
    return Dataset(_Plan(read_fns=reads))


def from_pandas(df, *, num_blocks: Optional[int] = None) -> Dataset:
    """Dataset from a pandas DataFrame (reference: data/read_api.py
    from_pandas): columns become the dict-block table."""
    cols = [str(c) for c in df.columns]
    if len(set(cols)) != len(cols):
        # pandas allows duplicate labels; df[c] would then return a 2-D
        # frame and the dict would silently drop all but one column
        raise ValueError(f"from_pandas needs unique column names, got "
                         f"{cols}")
    table = {str(c): df[c].to_numpy() for c in df.columns}
    return from_numpy(table, num_blocks=num_blocks)


def from_numpy(arr: Union[np.ndarray, Dict[str, np.ndarray]], *,
               num_blocks: Optional[int] = None) -> Dataset:
    if isinstance(arr, dict):
        n = len(next(iter(arr.values())))
    else:
        n = len(arr)
    nb = _num_blocks(n, num_blocks)
    bounds = np.linspace(0, n, nb + 1).astype(int)

    # Bind per-block COPIES at construction: a closure over (arr, s, e)
    # would cloudpickle the entire source array into every block task (and
    # every train-worker shard); numpy slices are views whose pickle still
    # serializes only their own elements, but .copy() also releases the
    # base-array reference so the driver can drop `arr`.
    def mk(s: int, e: int):
        if isinstance(arr, dict):
            chunk = {k: v[s:e].copy() for k, v in arr.items()}
            return lambda: chunk
        chunk = arr[s:e].copy()
        return lambda: chunk
    reads = [mk(int(bounds[i]), int(bounds[i + 1]))
             for i in _builtin_range(nb)]
    return Dataset(_Plan(read_fns=reads))


def _expand_paths(paths: Union[str, Sequence[str]], suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(suffix)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no {suffix or 'input'} files under {paths}")
    return out


def read_text(paths: Union[str, Sequence[str]], *,
              suffix: str = ".txt") -> Dataset:
    """One block per file; rows are stripped lines."""
    files = _expand_paths(paths, suffix)

    def mk(path: str):
        def read() -> Block:
            with open(path, "r", encoding="utf-8") as f:
                return [ln.rstrip("\n") for ln in f]
        return read
    return Dataset(_Plan(read_fns=[mk(p) for p in files]))


def read_json(paths: Union[str, Sequence[str]], *,
              suffix: str = ".jsonl") -> Dataset:
    """JSONL files; one block per file, dict rows → columnar when uniform."""
    files = _expand_paths(paths, suffix)

    def mk(path: str):
        def read() -> Block:
            with open(path, "r", encoding="utf-8") as f:
                return BlockAccessor.from_rows(
                    [json.loads(ln) for ln in f if ln.strip()])
        return read
    return Dataset(_Plan(read_fns=[mk(p) for p in files]))


def read_npy(paths: Union[str, Sequence[str]]) -> Dataset:
    """One .npy file per block, zero-copy numpy load on the worker."""
    files = _expand_paths(paths, ".npy")

    def mk(path: str):
        return lambda: np.load(path)
    return Dataset(_Plan(read_fns=[mk(p) for p in files]))


def read_csv(paths: Union[str, Sequence[str]], *,
             suffix: str = ".csv") -> Dataset:
    """Header-row CSVs via numpy; one block per file."""
    files = _expand_paths(paths, suffix)

    def mk(path: str):
        def read() -> Block:
            data = np.genfromtxt(path, delimiter=",", names=True,
                                 dtype=None, encoding="utf-8")
            data = np.atleast_1d(data)  # single-row files come back 0-d
            names = data.dtype.names or ()
            return {n: np.asarray(data[n]) for n in names}
        return read
    return Dataset(_Plan(read_fns=[mk(p) for p in files]))


def read_parquet(paths: Union[str, Sequence[str]]) -> Dataset:
    """Parquet via pyarrow when available (gated: pyarrow is optional)."""
    try:
        import pyarrow.parquet as pq  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "read_parquet requires pyarrow, which is not installed in this "
            "environment; use read_npy/read_json/read_csv") from e
    files = _expand_paths(paths, ".parquet")

    def mk(path: str):
        def read() -> Block:
            import pyarrow.parquet as pq
            t = pq.read_table(path)
            return {name: t.column(name).to_numpy()
                    for name in t.column_names}
        return read
    return Dataset(_Plan(read_fns=[mk(p) for p in files]))
