"""DataIterator — the train-worker-facing view of a dataset shard.

Role-equivalent to the reference's DataIterator (reference:
python/ray/data/iterator.py, surfaced in train via
session.get_dataset_shard). TPU addition: ``iter_jax_batches`` pads the
trailing partial batch to the full batch_size (mask column supplied) so a
jitted train step sees one static shape for the whole epoch.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

from ray_tpu.data.dataset import Dataset


class DataIterator:
    def __init__(self, dataset: Dataset):
        self._ds = dataset

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "dict",
                     drop_last: bool = False) -> Iterator[Any]:
        return self._ds.iter_batches(batch_size=batch_size,
                                     batch_format=batch_format,
                                     drop_last=drop_last)

    def iter_rows(self) -> Iterator[Any]:
        return self._ds.iter_rows()

    def iter_jax_batches(self, *, batch_size: int = 256,
                         pad_last: bool = True,
                         mask_column: str = "__valid__",
                         ) -> Iterator[Dict[str, np.ndarray]]:
        """Dict-of-numpy batches with a guaranteed static leading dim.

        The final partial batch is zero-padded to ``batch_size`` and a
        boolean ``mask_column`` marks real rows — the standard trick for
        keeping one XLA executable per epoch instead of recompiling on the
        ragged tail.
        """
        for batch in self._ds.iter_batches(batch_size=batch_size,
                                           batch_format="dict",
                                           drop_last=False):
            n = len(next(iter(batch.values()))) if batch else 0
            if n == 0:
                continue
            if n == batch_size or not pad_last:
                # mask present on EVERY batch (also the unpadded tail) so
                # the epoch yields one consistent pytree structure
                batch = dict(batch)
                batch[mask_column] = np.ones(n, dtype=bool)
                yield batch
                continue
            padded: Dict[str, np.ndarray] = {}
            for k, v in batch.items():
                pad_width = [(0, batch_size - n)] + [(0, 0)] * (v.ndim - 1)
                padded[k] = np.pad(v, pad_width)
            mask = np.zeros(batch_size, dtype=bool)
            mask[:n] = True
            padded[mask_column] = mask
            yield padded

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None, device: str = "cpu",
                           drop_last: bool = False) -> Iterator[Any]:
        """Dict-of-torch-tensor batches (reference: data/iterator.py
        iter_torch_batches). Gated on torch; numeric columns convert
        zero-copy via torch.from_numpy, others stay as lists."""
        import torch

        def to_tensor(v):
            if isinstance(v, np.ndarray) and v.dtype.kind in "biuf":
                arr = np.ascontiguousarray(v)
                if not arr.flags.writeable:
                    # torch.from_numpy warns on (and can't track) read-
                    # only arrays, e.g. zero-copy views out of shm
                    arr = arr.copy()
                t = torch.from_numpy(arr)
                if dtypes is not None:
                    t = t.to(dtypes)
                return t.to(device) if device != "cpu" else t
            return v
        for batch in self._ds.iter_batches(batch_size=batch_size,
                                           batch_format="dict",
                                           drop_last=drop_last):
            yield {k: to_tensor(v) for k, v in batch.items()}

    def materialize(self) -> Dataset:
        return self._ds.materialize()

    def count(self) -> int:
        return self._ds.count()

    def __repr__(self) -> str:
        return f"DataIterator({self._ds!r})"
