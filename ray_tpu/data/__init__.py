"""ray_tpu.data — streaming, block-partitioned datasets for TPU ingest.

Capability target: the reference's Ray Data core loop (reference:
python/ray/data — Dataset at dataset.py:153, StreamingExecutor at
_internal/execution/streaming_executor.py:48), rebuilt as a linear fused
block pipeline with numpy-columnar blocks and static-shape batch iteration
(see dataset.py / block.py docstrings for the design rationale).
"""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import (ActorPoolStrategy, Dataset,
                                  GroupedData, MaterializedDataset)
from ray_tpu.data._internal.shuffle import AggregateFn
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_items, from_numpy, from_pandas, range, read_csv, read_json,
    read_npy, read_parquet, read_text)

__all__ = [
    "ActorPoolStrategy", "AggregateFn", "GroupedData",
    "Block", "BlockAccessor", "Dataset", "MaterializedDataset",
    "DataIterator", "from_items", "from_numpy", "from_pandas", "range",
    "read_csv", "read_json", "read_npy", "read_parquet", "read_text",
]
