"""Cloud node providers for the autoscaler's NodeProvider seam."""

from ray_tpu.providers.gcp_tpu import TpuVmNodeProvider

__all__ = ["TpuVmNodeProvider"]
