"""GCE TPU-VM node provider: one slice per autoscaler node.

Role-equivalent to the reference's GCP provider (reference:
python/ray/autoscaler/_private/gcp/node_provider.py + config.py bootstrap)
reshaped TPU-first: the provisioning unit is a whole TPU slice (a
queued-resource/node in the TPU API), whose worker-0 boots the node daemon
advertising the ``TPU-{pod_type}-head`` gang resource — so one pending
gang bundle scales up exactly one slice.

All HTTP goes through an injectable transport (tests use a fake; this
image has no cloud egress). Real deployments default to urllib against
``tpu.googleapis.com`` with a GCE-metadata access token.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Callable, Dict, Optional

from ray_tpu.autoscaler import NodeProvider

logger = logging.getLogger("ray_tpu.providers.gcp")

_TPU_API = "https://tpu.googleapis.com/v2"
_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

#: worker-0 startup: join the cluster as a node daemon. The daemon
#: detects TPU resources itself (accelerators/tpu.py reads the TPU VM
#: env), so the script only carries identity + head address.
_STARTUP_TEMPLATE = """#!/bin/bash
python3 -m ray_tpu.runtime.node {head_addr} {session} \
'{{"resources": null, "object_store_bytes": null, \
"node_id": "{node_id}", "config": {config}}}'
"""


class _UrllibHttp:
    """Minimal JSON-over-HTTP transport (stdlib only; no cloud SDK)."""

    def __init__(self, token_fn: Optional[Callable[[], str]] = None):
        self._token_fn = token_fn or self._metadata_token

    @staticmethod
    def _metadata_token() -> str:
        import urllib.request
        req = urllib.request.Request(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read())["access_token"]

    def request(self, method: str, url: str,
                body: Optional[dict] = None) -> dict:
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Authorization": f"Bearer {self._token_fn()}",
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else {}


class _SliceHandle:
    """Provider handle for one provisioned slice.

    ``poll()`` follows the Popen contract the autoscaler's adoption loop
    checks (None = still coming up / alive, non-None = dead): it GETs the
    TPU node resource (throttled) so an async create failure — quota,
    stockout, boot error — frees the launch slot instead of pinning
    max_workers forever.
    """

    _POLL_INTERVAL_S = 15.0

    _FAILS_BEFORE_DEAD = 3  # consecutive GET failures before declaring

    def __init__(self, name: str, node_id: str, http: Any):
        self.name = name          # fully-qualified TPU node resource name
        self.rtpu_node_id = node_id  # identity the daemon registers under
        self._http = http
        self._last_poll = 0.0
        self._dead: Optional[str] = None
        self._fails = 0

    def poll(self) -> Optional[str]:
        import time
        if self._dead is not None:
            return self._dead
        now = time.monotonic()
        if now - self._last_poll < self._POLL_INTERVAL_S:
            return None
        self._last_poll = now
        try:
            state = self._http.request("GET", self.name).get("state", "")
        except Exception:  # noqa: BLE001 — could be 404 (deleted) OR a
            # transient API hiccup: one blip must not orphan a live
            # billing slice, so only consecutive failures count
            self._fails += 1
            if self._fails >= self._FAILS_BEFORE_DEAD:
                self._dead = "GONE"
            return self._dead
        self._fails = 0
        if state in ("CREATING", "STARTING", "READY", "RESTARTING",
                     "REPAIRING", ""):
            return None
        self._dead = state  # STOPPED / PREEMPTED / TERMINATED / FAILED...
        return self._dead


class TpuVmNodeProvider(NodeProvider):
    """Provision/release TPU slices through the TPU REST API.

    Parameters mirror what a cluster config would carry (reference:
    autoscaler YAML provider section): GCP project/zone, the slice
    ``accelerator_type`` (e.g. "v5litepod-8"), the TPU ``runtime_version``
    image, and the head address new slices should join.
    """

    def __init__(self, project: str, zone: str, accelerator_type: str,
                 runtime_version: str, head_addr: str, session: str,
                 http: Optional[Any] = None,
                 name_prefix: str = "rtpu"):
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.head_addr = head_addr
        self.session = session
        self.http = http or _UrllibHttp()
        self.name_prefix = name_prefix

    @property
    def _parent(self) -> str:
        return f"{_TPU_API}/projects/{self.project}/locations/{self.zone}"

    def _name_for(self, node_id: str) -> str:
        """Deterministic resource name for a node identity — what lets a
        restarted autoscaler terminate an orphaned slice from nothing
        but the persisted instance record."""
        return f"{self.name_prefix}-{node_id[:12]}"

    def create_node(self, resources: Dict[str, float],
                    node_id: Optional[str] = None) -> _SliceHandle:
        from ray_tpu.core.ids import NodeID
        from ray_tpu.core import config as config_mod
        from ray_tpu.util.fault_injector import fire
        fire("provider.create")
        node_id = node_id or NodeID.from_random().hex()
        name = self._name_for(node_id)
        startup = _STARTUP_TEMPLATE.format(
            head_addr=self.head_addr, session=self.session,
            node_id=node_id, config=config_mod.GlobalConfig.to_json())
        body = {
            "acceleratorType": self.accelerator_type,
            "runtimeVersion": self.runtime_version,
            "metadata": {"startup-script": startup},
            "labels": {"rtpu-session": self.session,
                       "rtpu-node-id": node_id[:32]},
        }
        logger.info("provisioning TPU slice %s (%s)", name,
                    self.accelerator_type)
        self.http.request("POST", f"{self._parent}/nodes?nodeId={name}",
                          body)
        return _SliceHandle(f"{self._parent}/nodes/{name}", node_id,
                            self.http)

    def terminate_node(self, handle: _SliceHandle) -> None:
        from ray_tpu.util.fault_injector import fire
        fire("provider.terminate")
        logger.info("releasing TPU slice %s", handle.name.rsplit("/", 1)[-1])
        try:
            self.http.request("DELETE", handle.name)
        except Exception:  # noqa: BLE001 — already gone / API hiccup;
            logger.exception("slice delete failed: %s", handle.name)

    def describe(self, handle: _SliceHandle) -> Dict[str, Any]:
        return {"name": handle.name}

    def list_live(self) -> Dict[str, Dict[str, Any]]:
        """The provider's live-handle ledger: every not-yet-deleted slice
        in this session, keyed by the rtpu-node-id label it was created
        with — the substrate restart reconcile converges against."""
        try:
            nodes = self.http.request(
                "GET", f"{self._parent}/nodes").get("nodes", [])
        except Exception:  # noqa: BLE001 — API down: report nothing
            logger.exception("TPU node list failed")
            return {}
        out: Dict[str, Dict[str, Any]] = {}
        for n in nodes:
            labels = n.get("labels") or {}
            if labels.get("rtpu-session") != self.session:
                continue
            nid = labels.get("rtpu-node-id")
            if nid and n.get("state") not in ("DELETING", "TERMINATED"):
                out[nid] = {"name": n.get("name", "")}
        return out

    def terminate_orphan(self, node_id: str,
                         metadata: Dict[str, Any]) -> None:
        from ray_tpu.util.fault_injector import fire
        fire("provider.terminate")
        name = metadata.get("name") or \
            f"{self._parent}/nodes/{self._name_for(node_id)}"
        logger.info("releasing orphaned TPU slice %s",
                    name.rsplit("/", 1)[-1])
        try:
            self.http.request("DELETE", name)
        except Exception:  # noqa: BLE001 — already gone
            logger.exception("orphan slice delete failed: %s", name)

    @staticmethod
    def slice_node_type(accelerator_type: str,
                        cpus_per_host: float = 8.0) -> Dict[str, float]:
        """The resource shape the slice's WORKER-0 daemon registers — what
        the autoscaler bin-packs gang demand against. Chips are capped at
        the per-host count (accelerators/tpu.py _chips_per_host): a
        multi-host slice's other hosts register their own nodes, so
        claiming the slice TOTAL here would admit task shapes worker-0
        can never serve."""
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager
        version, _, chips = accelerator_type.rpartition("-")
        version = {"v5litepod": "v5e"}.get(version, version)
        pod = f"{version}-{chips}"
        per_host = TPUAcceleratorManager._chips_per_host(pod)
        n = float(min(int(chips), per_host))
        return {"CPU": cpus_per_host, "TPU": n, f"TPU-{version}": n,
                f"TPU-{pod}-head": 1.0}
