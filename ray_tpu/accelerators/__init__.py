"""Accelerator managers (reference: python/ray/_private/accelerators/)."""

from ray_tpu.accelerators.tpu import TPUAcceleratorManager

__all__ = ["TPUAcceleratorManager"]
