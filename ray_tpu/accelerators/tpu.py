"""TPU accelerator manager: topology detection + gang resources.

Role-equivalent to the reference's TPU manager (reference:
python/ray/_private/accelerators/tpu.py:70 — chip-count validation at
:14,143, TPU_VISIBLE_CHIPS/TPU_CHIPS_PER_HOST_BOUNDS at :31,39,
`TPU-{version}` resources at :310, `TPU-{pod_type}-head` gang resource at
:330,377) redesigned for this framework's scheduler:

 - each TPU host advertises ``TPU`` (chip count), ``TPU-{version}`` (e.g.
   TPU-v5p), and — on worker 0 of a slice — ``TPU-{pod_type}-head`` (e.g.
   TPU-v5p-16-head), the gang resource a placement-group bundle reserves to
   claim a whole ICI slice atomically;
 - leased workers get ``TPU_VISIBLE_CHIPS`` so concurrent workers on one
   host never fight over chips (the TPU runtime allows one owner per chip);
 - detection is env-driven (GKE-style TPU_* variables; the JAX fallback
   probes local devices) since a metadata server is not assumed.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

# chips per host must divide the host's physical complement
# (reference: tpu.py:14 — valid per-host chip counts)
VALID_CHIPS_PER_HOST = (1, 2, 4, 8)

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"

# single-host bounds by chip count (reference: tpu.py:31-39 constants)
_BOUNDS_BY_COUNT = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1", 8: "2,4,1"}


class TPUAcceleratorManager:
    """Static helpers; instantiated nowhere (mirrors the reference ABC)."""

    # ------------------------------------------------------------- detection

    @staticmethod
    def detect(allow_jax_probe: bool = False) -> Optional[dict]:
        """Detect this host's TPU topology.

        Returns {version, pod_type, worker_id, num_chips} or None when the
        host has no TPU. Sources, in order:
          1. explicit env (TPU_ACCELERATOR_TYPE / TPU_WORKER_ID) — the
             GKE/GCE path of the reference;
          2. only if ``allow_jax_probe``: a live JAX TPU backend. Daemons
             must NOT probe — initializing the jax TPU backend claims the
             chips, starving the workers the daemon exists to serve.
        """
        accel = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5p-16"
        if accel:
            version = accel.split("-")[0]
            worker_id = int(os.environ.get("TPU_WORKER_ID", "0"))
            num_chips = TPUAcceleratorManager._chips_per_host(accel)
            return {"version": version, "pod_type": accel,
                    "worker_id": worker_id, "num_chips": num_chips}
        if allow_jax_probe:
            return TPUAcceleratorManager._detect_via_jax()
        return None

    @staticmethod
    def _detect_via_jax() -> Optional[dict]:
        try:
            import jax
            devices = [d for d in jax.devices()
                       if d.platform not in ("cpu", "gpu")]
        except Exception:
            return None
        if not devices:
            return None
        kind = getattr(devices[0], "device_kind", "tpu").lower()
        version = "v" + "".join(
            ch for ch in kind.split("v")[-1] if ch.isalnum()) \
            if "v" in kind else "tpu"
        n = len(devices)
        return {"version": version, "pod_type": f"{version}-{n}",
                "worker_id": 0, "num_chips": n}

    # full-host chip complement per TPU generation (reference: tpu.py:143
    # topology tables — v2-v4/v5p hosts carry 4 chips, v5e/v6e up to 8)
    _PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4,
                 "v5e": 8, "v5litepod": 8, "v6e": 8}

    @staticmethod
    def _chips_per_host(pod_type: str) -> int:
        version, _, suffix = pod_type.rpartition("-")
        try:
            total = int(suffix)
        except ValueError:
            return 4
        per = TPUAcceleratorManager._PER_HOST.get(version, 4)
        return min(total, per)

    # ------------------------------------------------------------- resources

    @staticmethod
    def node_resources(info: Optional[dict] = None) -> Dict[str, float]:
        """Resources a TPU host advertises to the scheduler.

        ``TPU-{pod_type}-head`` appears only on worker 0 so a single-bundle
        PG reservation of it gang-claims the whole slice (reference:
        tpu.py:330,377).
        """
        if info is None:
            info = TPUAcceleratorManager.detect()
        if info is None:
            return {}
        res = {
            "TPU": float(info["num_chips"]),
            f"TPU-{info['version']}": float(info["num_chips"]),
        }
        if info["worker_id"] == 0:
            res[f"TPU-{info['pod_type']}-head"] = 1.0
        return res

    @staticmethod
    def validate_chip_request(n: int) -> None:
        if n not in VALID_CHIPS_PER_HOST:
            raise ValueError(
                f"requested {n} TPU chips; a worker may hold "
                f"{VALID_CHIPS_PER_HOST} (reference tpu.py chip-count rule)")

    @staticmethod
    def visibility_env(chip_ids: List[int]) -> Dict[str, str]:
        """Env for a worker that owns `chip_ids` on this host (reference:
        tpu.py:31,39 — set before the TPU runtime initializes)."""
        n = len(chip_ids)
        env = {TPU_VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chip_ids)}
        bounds = _BOUNDS_BY_COUNT.get(n)
        if bounds:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = bounds
        return env


class ChipAllocator:
    """Per-node assignment of physical chip ids to leased workers."""

    def __init__(self, num_chips: int):
        self.free: List[int] = list(range(num_chips))
        self.assigned: Dict[bytes, List[int]] = {}

    def allocate(self, worker_id: bytes, n: int) -> Optional[List[int]]:
        if len(self.free) < n:
            return None
        chips, self.free = self.free[:n], self.free[n:]
        self.assigned[worker_id] = chips
        return chips

    def release(self, worker_id: bytes) -> None:
        chips = self.assigned.pop(worker_id, None)
        if chips:
            self.release_chips(chips)

    def release_chips(self, chips: List[int]) -> None:
        """Return chips not (or no longer) tied to a worker id (e.g. a
        spawn that failed between allocation and registration)."""
        self.free.extend(chips)
        self.free.sort()
