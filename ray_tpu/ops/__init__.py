"""ray_tpu.ops — Pallas TPU kernels for the hot ops.

The reference's hot ops live in external CUDA (vLLM paged attention, NCCL);
here they are Pallas kernels compiled for the MXU/VMEM hierarchy:
flash attention (training), with blockwise-JAX fallbacks that run anywhere
(CPU mesh tests, interpret mode).
"""

from ray_tpu.ops.flash_attention import (blockwise_attention,
                                         flash_attention,
                                         flash_attention_sharded,
                                         kernels_supported)

__all__ = ["flash_attention", "flash_attention_sharded",
           "blockwise_attention", "kernels_supported"]
