"""ray_tpu.ops — Pallas TPU kernels for the hot ops.

The reference's hot ops live in external CUDA (vLLM paged attention, NCCL);
here they are Pallas kernels compiled for the MXU/VMEM hierarchy:
flash attention (training), with blockwise-JAX fallbacks that run anywhere
(CPU mesh tests, interpret mode).
"""

from ray_tpu.ops.flash_attention import (autotune_blocks,
                                         blockwise_attention,
                                         flash_attention,
                                         flash_attention_sharded,
                                         get_tuned_blocks,
                                         kernels_supported)
from ray_tpu.ops.int8 import int8_matmul

__all__ = ["flash_attention", "flash_attention_sharded",
           "blockwise_attention", "kernels_supported",
           "autotune_blocks", "get_tuned_blocks", "int8_matmul"]
