"""Paged-KV attention — Pallas TPU kernels + JAX references.

No equivalent exists in the reference tree (serving delegates to vLLM's
CUDA PagedAttention — reference: python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py); built from the paged/ragged
attention recipe (PAPERS.md "Ragged Paged Attention") on the Pallas
scalar-prefetch pattern:

  - the KV cache lives in HBM as fixed-size pages
    ``[total_pages, kv_heads, page_size, head_dim]``; a sequence's cache
    is the pages named by its row of ``page_table`` — no per-sequence
    contiguous allocation, so fragmentation-free continuous batching;
  - ``paged_attention``: one decode token per sequence
    ``[B, q_heads, head_dim]``, grid (B, max_pages) — the original
    decode-only kernel, kept as the single-token oracle;
  - ``ragged_paged_attention``: a RAGGED token batch ``[T, Hq, D]`` —
    concatenated query tokens from R sequences described by
    ``(q_start, q_len, kv_len)`` rows, where q_len is a prefill chunk
    for some rows and 1 for decode rows. Grid (T, max_pages): the
    scalar-prefetched page table (plus per-token row/visibility vectors
    derived from the descriptors in-program) drives the BlockSpec
    index_map, each grid step DMAs exactly one page, causal masking is
    a per-token visible-length compare, and online-softmax scratch
    carries across the page axis. One dispatch serves mixed
    prefill+decode — the engine's whole step program;
  - int8 KV pages: both ragged paths take optional per-(page, head,
    slot) scale arrays ``[P, Hkv, ps]`` and dequantize in-kernel
    (k_f32 = k_int8 * scale), halving KV HBM per token;
  - GQA: q is grouped [kv_heads, q_per_kv, head_dim] and the score matmul
    batches over kv_heads on the MXU.

The ``*_reference`` functions are the pure-JAX gather equivalents — the
numerics oracles and the portable fallbacks on CPU test meshes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = float("-inf")


# --------------------------------------------------------------------------
# Pure-JAX reference (portable fallback + numerics oracle)
# --------------------------------------------------------------------------

def paged_attention_reference(q, k_pages, v_pages, page_table, seq_lens, *,
                              sm_scale: Optional[float] = None) -> jax.Array:
    """Gather-based paged attention.

    q:          [B, Hq, D]       one decode token per sequence
    k/v_pages:  [P, Hkv, ps, D]  the shared page pool
    page_table: [B, max_pages]   page ids per sequence (unused tail: any)
    seq_lens:   [B]              valid KV tokens (incl. the current one)
    returns     [B, Hq, D]
    """
    B, Hq, D = q.shape
    P_, Hkv, ps, _ = k_pages.shape
    max_pages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    # gather pages -> [B, Hkv, max_pages*ps, D]
    k = k_pages[page_table]  # [B, max_pages, Hkv, ps, D]
    v = v_pages[page_table]
    k = k.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, max_pages * ps, D)
    v = v.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, max_pages * ps, D)
    qg = q.reshape(B, Hkv, Hq // Hkv, D).astype(jnp.float32)
    s = jnp.einsum("bgqd,bgtd->bgqt", qg, k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(max_pages * ps)[None, None, None, :]
    s = jnp.where(pos < seq_lens[:, None, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgqt,bgtd->bgqd", p, v.astype(jnp.float32))
    return o.reshape(B, Hq, D).astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas kernel
# --------------------------------------------------------------------------

def _decode_kernel(page_table_ref, seq_lens_ref,  # scalar prefetch
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, sm_scale, page_size,
                   q_per_kv):
    b, pi = pl.program_id(0), pl.program_id(1)
    n_pages = pl.num_programs(1)
    seq_len = seq_lens_ref[b]

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # tokens this page holds for this sequence: (0, page_size]
    page_start = pi * page_size
    valid = seq_len - page_start

    @pl.when(valid > 0)
    def _page():
        q = q_ref[0].astype(jnp.float32)         # [Hq, D]
        k = k_ref[0]                              # [Hkv, ps, D]
        v = v_ref[0]
        Hq = q.shape[0]
        Hkv = k.shape[0]
        qg = q.reshape(Hkv, q_per_kv, q.shape[-1])
        # batched over kv heads on the MXU: [Hkv, qpk, ps]
        s = lax.dot_general(
            qg, k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,)))) * sm_scale
        col = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < valid, s, _NEG_INF)
        m_prev = m_ref[:, :1]                     # [Hq, 1]
        l_prev = l_ref[:, :1]
        s2 = s.reshape(Hq, page_size)
        m_new = jnp.maximum(m_prev, s2.max(axis=-1, keepdims=True))
        p = jnp.where(jnp.isneginf(s2), 0.0, jnp.exp(s2 - m_new))
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_new))
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(                      # [Hkv, qpk, D]
            p.reshape(Hkv, q_per_kv, page_size).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(Hq, -1)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(pi == n_pages - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_attention_pallas(q, k_pages, v_pages, page_table, seq_lens,
                            sm_scale: float, interpret: bool = False):
    B, Hq, D = q.shape
    P_, Hkv, ps, _ = k_pages.shape
    max_pages = page_table.shape[1]
    q_per_kv = Hq // Hkv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, p, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, Hkv, ps, D),
                         lambda b, p, pt, sl: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, Hkv, ps, D),
                         lambda b, p, pt, sl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, p, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               page_size=ps, q_per_kv=q_per_kv)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, k_pages, v_pages)


def kernels_supported(device: Optional[jax.Device] = None) -> bool:
    if not _HAS_PALLAS:
        return False
    dev = device if device is not None else jax.devices()[0]
    return dev.platform == "tpu" or getattr(dev, "device_kind",
                                            "").startswith("TPU")


def paged_attention(q, k_pages, v_pages, page_table, seq_lens, *,
                    sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    impl: Optional[str] = None) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, gather reference elsewhere.

    ``interpret=True`` forces the kernel through the Pallas interpreter
    (CPU) — used by tests to validate the kernel itself off-TPU.
    ``impl`` pins the implementation outright ("kernel" | "reference"):
    code that compiles for a SPECIFIC mesh (the tp serving engine) must
    choose by the mesh's platform, because the process's default backend
    (what the interpret=None autodetect sees) can be a different
    accelerator than the mesh the program runs on.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if q.shape[1] % k_pages.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads "
            f"{k_pages.shape[1]}")
    if impl == "reference":
        return paged_attention_reference(
            q, k_pages, v_pages, page_table, seq_lens, sm_scale=sm_scale)
    if impl is not None and impl != "kernel":
        raise ValueError(f"impl must be 'kernel' or 'reference', "
                         f"got {impl!r}")
    if interpret is None:
        if impl is None and not kernels_supported():
            return paged_attention_reference(
                q, k_pages, v_pages, page_table, seq_lens,
                sm_scale=sm_scale)
        interpret = False
    return _paged_attention_pallas(
        q, k_pages, v_pages, page_table,
        seq_lens.astype(jnp.int32), sm_scale, interpret)


# --------------------------------------------------------------------------
# Ragged paged attention: mixed prefill chunks + decode rows, one dispatch
# --------------------------------------------------------------------------
#
# Ragged batch layout (the engine's step program):
#   q [T, Hq, D] holds R sequences' query tokens concatenated; row r owns
#   tokens q_start[r] .. q_start[r]+q_len[r]-1 (disjoint spans; q_len 0 =
#   inactive row; tokens owned by no row are padding and produce zeros).
#   Token j of row r sits at absolute position kv_len[r]-q_len[r]+j and
#   causally sees kv positions <= that, i.e. the first
#   kv_len[r]-q_len[r]+j+1 slots of the row's pages (the row's OWN chunk
#   K/V included — the caller scatters the chunk into the pages before
#   attending, exactly like the decode step writes-then-attends).


def _token_descriptors(q_start, q_len, kv_len, T: int):
    """Per-token (owning row, visible kv length) from per-row descriptors.

    O(R*T) int compare — noise next to attention; runs inside the jitted
    wrapper so the host never materializes per-token metadata.
    """
    tvec = jnp.arange(T, dtype=jnp.int32)
    in_row = (tvec[None, :] >= q_start[:, None]) & \
             (tvec[None, :] < (q_start + q_len)[:, None])       # [R, T]
    token_row = jnp.argmax(in_row, axis=0).astype(jnp.int32)
    owned = jnp.any(in_row, axis=0)
    vis = kv_len[token_row] - q_len[token_row] \
        + (tvec - q_start[token_row]) + 1
    token_vis = jnp.where(owned, vis, 0).astype(jnp.int32)
    return token_row, token_vis


def ragged_paged_attention_reference(q, k_pages, v_pages, page_table,
                                     q_start, q_len, kv_len, *,
                                     k_scale=None, v_scale=None,
                                     sm_scale: Optional[float] = None,
                                     max_q_len: Optional[int] = None,
                                     decode_rows: int = 0) -> jax.Array:
    """Gather-based ragged paged attention (oracle + CPU fallback).

    q: [T, Hq, D]; k/v_pages: [P, Hkv, ps, D] (int8 when scales given);
    k/v_scale: [P, Hkv, ps] per-(page, head, slot) dequant scales or
    None; page_table: [R, max_pages]; q_start/q_len/kv_len: [R].

    ``decode_rows``/``max_q_len`` are STATIC cost hints, not semantics:
    the first ``decode_rows`` rows must have q_len <= 1 and are computed
    decode-style (one gathered score row each); the rest are prefill
    rows computed on ``max_q_len``-sized blocks (default T). Wrong hints
    that still satisfy the q_len bounds only cost time, never accuracy.
    """
    T, Hq, D = q.shape
    R, max_pages = page_table.shape
    _, Hkv, ps, _ = k_pages.shape
    if sm_scale is None:
        sm_scale = D ** -0.5
    max_kv = max_pages * ps
    qpk = Hq // Hkv

    # one page gather per row -> [R, Hkv, max_kv, D] fp32 (dequantized)
    kr = k_pages[page_table]                     # [R, mp, Hkv, ps, D]
    vr = v_pages[page_table]
    kr = kr.astype(jnp.float32)
    vr = vr.astype(jnp.float32)
    if k_scale is not None:
        kr = kr * k_scale[page_table].astype(jnp.float32)[..., None]
        vr = vr * v_scale[page_table].astype(jnp.float32)[..., None]
    kr = kr.transpose(0, 2, 1, 3, 4).reshape(R, Hkv, max_kv, D)
    vr = vr.transpose(0, 2, 1, 3, 4).reshape(R, Hkv, max_kv, D)

    out = jnp.zeros((T, Hq, D), jnp.float32)
    tkv = jnp.arange(max_kv)

    def _safe_softmax(s):
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.where(jnp.isneginf(s), 0.0,
                      jnp.exp(s - jnp.where(jnp.isneginf(m), 0.0, m)))
        return p / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)

    Rd = decode_rows
    if Rd:
        idx = jnp.clip(q_start[:Rd], 0, T - 1)
        qd = q[idx].reshape(Rd, Hkv, qpk, D).astype(jnp.float32)
        s = jnp.einsum("rgqd,rgtd->rgqt", qd, kr[:Rd]) * sm_scale
        vis = jnp.where(q_len[:Rd] > 0, kv_len[:Rd], 0)
        s = jnp.where(tkv[None, None, None, :] < vis[:, None, None, None],
                      s, _NEG_INF)
        od = jnp.einsum("rgqt,rgtd->rgqd", _safe_softmax(s), vr[:Rd])
        od = od.reshape(Rd, Hq, D)
        od = jnp.where((q_len[:Rd] > 0)[:, None, None], od, 0.0)
        out = out.at[idx].add(od)

    if R - Rd:
        C = min(max_q_len if max_q_len is not None else T, T)
        qpad = jnp.pad(q.astype(jnp.float32), ((0, C), (0, 0), (0, 0)))
        starts = jnp.clip(q_start[Rd:], 0, T)

        qc = jax.vmap(lambda s0: lax.dynamic_slice(
            qpad, (s0, 0, 0), (C, Hq, D)))(starts)   # [Rp, C, Hq, D]
        qc = qc.reshape(-1, C, Hkv, qpk, D)
        s = jnp.einsum("rcgqd,rgtd->rcgqt", qc, kr[Rd:]) * sm_scale
        cvec = jnp.arange(C)
        vis = kv_len[Rd:, None] - q_len[Rd:, None] + cvec[None, :] + 1
        vis = jnp.where(cvec[None, :] < q_len[Rd:, None], vis, 0)
        s = jnp.where(tkv[None, None, None, None, :]
                      < vis[:, :, None, None, None], s, _NEG_INF)
        oc = jnp.einsum("rcgqt,rgtd->rcgqd", _safe_softmax(s), vr[Rd:])
        oc = oc.reshape(-1, C, Hq, D)
        oc = jnp.where((cvec[None, :] < q_len[Rd:, None])[:, :, None, None],
                       oc, 0.0)
        dest = starts[:, None] + cvec[None, :]        # [Rp, C] < T + C
        out = out + jnp.zeros((T + C, Hq, D),
                              jnp.float32).at[dest].add(oc)[:T]
    return out.astype(q.dtype)


def _ragged_kernel(tr_ref, vis_ref, pt_ref,          # scalar prefetch
                   q_ref, k_ref, v_ref, *rest, sm_scale, page_size,
                   q_per_kv, has_scales):
    if has_scales:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    t, pi = pl.program_id(0), pl.program_id(1)
    n_pages = pl.num_programs(1)
    vis = vis_ref[t]          # visible kv length of THIS token (0 = pad)

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    page_start = pi * page_size
    valid = vis - page_start

    @pl.when(valid > 0)
    def _page():
        q = q_ref[0].astype(jnp.float32)          # [Hq, D]
        k = k_ref[0].astype(jnp.float32)          # [Hkv, ps, D]
        v = v_ref[0].astype(jnp.float32)
        if has_scales:
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        Hq = q.shape[0]
        Hkv = k.shape[0]
        qg = q.reshape(Hkv, q_per_kv, q.shape[-1])
        s = lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (0,)))) * sm_scale
        col = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < valid, s, _NEG_INF)
        m_prev = m_ref[:, :1]                     # [Hq, 1]
        l_prev = l_ref[:, :1]
        s2 = s.reshape(Hq, page_size)
        m_new = jnp.maximum(m_prev, s2.max(axis=-1, keepdims=True))
        p = jnp.where(jnp.isneginf(s2), 0.0, jnp.exp(s2 - m_new))
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_new))
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(                      # [Hkv, qpk, D]
            p.reshape(Hkv, q_per_kv, page_size), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(Hq, -1)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(pi == n_pages - 1)
    def _finish():
        # padding tokens never accumulate: l stays 0 -> output 0
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _ragged_attention_pallas(q, k_pages, v_pages, page_table,
                             q_start, q_len, kv_len, k_scale, v_scale,
                             sm_scale: float, interpret: bool = False):
    T, Hq, D = q.shape
    _, Hkv, ps, _ = k_pages.shape
    max_pages = page_table.shape[1]
    q_per_kv = Hq // Hkv
    token_row, token_vis = _token_descriptors(
        q_start.astype(jnp.int32), q_len.astype(jnp.int32),
        kv_len.astype(jnp.int32), T)

    has_scales = k_scale is not None
    kv_spec = pl.BlockSpec(
        (1, Hkv, ps, D), lambda t, p, tr, vis, pt: (pt[tr[t], p], 0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, Hq, D), lambda t, p, tr, vis, pt: (t, 0, 0)),
        kv_spec, kv_spec,
    ]
    operands = [q, k_pages, v_pages]
    if has_scales:
        sc_spec = pl.BlockSpec(
            (1, Hkv, ps), lambda t, p, tr, vis, pt: (pt[tr[t], p], 0, 0))
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(T, max_pages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hq, D),
                               lambda t, p, tr, vis, pt: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_ragged_kernel, sm_scale=sm_scale,
                               page_size=ps, q_per_kv=q_per_kv,
                               has_scales=has_scales)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        interpret=interpret,
    )(token_row, token_vis, page_table, *operands)


def ragged_paged_attention(q, k_pages, v_pages, page_table, q_start,
                           q_len, kv_len, *, k_scale=None, v_scale=None,
                           sm_scale: Optional[float] = None,
                           max_q_len: Optional[int] = None,
                           decode_rows: int = 0,
                           interpret: Optional[bool] = None,
                           impl: Optional[str] = None) -> jax.Array:
    """Mixed prefill+decode attention over a ragged token batch in ONE
    dispatch. Dispatch rules identical to ``paged_attention``: Pallas
    kernel on TPU, gather reference elsewhere; ``impl`` pins the choice
    for mesh-specific programs, ``interpret=True`` runs the kernel
    through the Pallas interpreter on CPU (the tier-1 kernel tests).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if q.shape[1] % k_pages.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads "
            f"{k_pages.shape[1]}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if impl == "reference":
        return ragged_paged_attention_reference(
            q, k_pages, v_pages, page_table, q_start, q_len, kv_len,
            k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale,
            max_q_len=max_q_len, decode_rows=decode_rows)
    if impl is not None and impl != "kernel":
        raise ValueError(f"impl must be 'kernel' or 'reference', "
                         f"got {impl!r}")
    if interpret is None:
        if impl is None and not kernels_supported():
            return ragged_paged_attention_reference(
                q, k_pages, v_pages, page_table, q_start, q_len, kv_len,
                k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale,
                max_q_len=max_q_len, decode_rows=decode_rows)
        interpret = False
    return _ragged_attention_pallas(
        q, k_pages, v_pages, page_table, q_start.astype(jnp.int32),
        q_len.astype(jnp.int32), kv_len.astype(jnp.int32),
        k_scale, v_scale, sm_scale, interpret)


# --------------------------------------------------------------------------
# Page-cache update helper (the ragged step's one scatter per layer)
# --------------------------------------------------------------------------

def write_ragged_kv(k_pages, v_pages, k_t, v_t, token_page, token_slot,
                    k_scale=None, v_scale=None):
    """Scatter a ragged batch's per-token K/V into the page pool.

    k_t/v_t: [T, Hkv, D] this layer's roped K/V for every ragged token
    (decode rows and prefill chunks alike); token_page/token_slot: [T]
    destination page id and in-page slot — padding tokens point at page
    0 (the scratch page, garbage by contract). When the pool is int8
    (``k_scale``/``v_scale`` [P, Hkv, ps] given), rows quantize with
    per-token/per-head scales (ops.int8.quantize_kv) and the scales
    scatter alongside — every write stays local, nothing requantizes.
    Returns (k_pages, v_pages, k_scale, v_scale); scales pass through as
    None on fp pools.
    """
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None:
        from ray_tpu.ops.int8 import quantize_kv
        kq, ks = quantize_kv(k_t)                 # [T, Hkv, D], [T, Hkv]
        vq, vs = quantize_kv(v_t)
        k_pages = k_pages.at[token_page, :, token_slot, :].set(kq)
        v_pages = v_pages.at[token_page, :, token_slot, :].set(vq)
        k_scale = k_scale.at[token_page, :, token_slot].set(
            ks.astype(k_scale.dtype))
        v_scale = v_scale.at[token_page, :, token_slot].set(
            vs.astype(v_scale.dtype))
    else:
        # advanced indices at axes 0 and 2 are separated by a basic
        # slice, so the indexed result is [T, Hkv, D]
        k_pages = k_pages.at[token_page, :, token_slot, :].set(
            k_t.astype(k_pages.dtype))
        v_pages = v_pages.at[token_page, :, token_slot, :].set(
            v_t.astype(v_pages.dtype))
    return k_pages, v_pages, k_scale, v_scale
