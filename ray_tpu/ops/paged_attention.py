"""Paged-KV attention for LLM decode — Pallas TPU kernel + JAX reference.

No equivalent exists in the reference tree (serving delegates to vLLM's
CUDA PagedAttention — reference: python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py); built from the paged/ragged
attention recipe (PAPERS.md "Ragged Paged Attention") on the Pallas
scalar-prefetch pattern:

  - the KV cache lives in HBM as fixed-size pages
    ``[total_pages, kv_heads, page_size, head_dim]``; a sequence's cache
    is the pages named by its row of ``page_table`` — no per-sequence
    contiguous allocation, so fragmentation-free continuous batching;
  - the decode query is one token per sequence ``[B, q_heads, head_dim]``;
  - grid (B, max_pages): scalar-prefetched page_table drives the
    BlockSpec index_map, so each grid step DMAs exactly one page from HBM
    into VMEM (the pages a sequence doesn't use are never touched — the
    @pl.when skip also skips the FLOPs, and online-softmax scratch
    carries across the page axis exactly like flash attention);
  - GQA: q is grouped [kv_heads, q_per_kv, head_dim] and the score matmul
    batches over kv_heads on the MXU.

``paged_attention_reference`` is the pure-JAX gather equivalent — the
numerics oracle and the portable fallback on CPU test meshes.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = float("-inf")


# --------------------------------------------------------------------------
# Pure-JAX reference (portable fallback + numerics oracle)
# --------------------------------------------------------------------------

def paged_attention_reference(q, k_pages, v_pages, page_table, seq_lens, *,
                              sm_scale: Optional[float] = None) -> jax.Array:
    """Gather-based paged attention.

    q:          [B, Hq, D]       one decode token per sequence
    k/v_pages:  [P, Hkv, ps, D]  the shared page pool
    page_table: [B, max_pages]   page ids per sequence (unused tail: any)
    seq_lens:   [B]              valid KV tokens (incl. the current one)
    returns     [B, Hq, D]
    """
    B, Hq, D = q.shape
    P_, Hkv, ps, _ = k_pages.shape
    max_pages = page_table.shape[1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    # gather pages -> [B, Hkv, max_pages*ps, D]
    k = k_pages[page_table]  # [B, max_pages, Hkv, ps, D]
    v = v_pages[page_table]
    k = k.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, max_pages * ps, D)
    v = v.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, max_pages * ps, D)
    qg = q.reshape(B, Hkv, Hq // Hkv, D).astype(jnp.float32)
    s = jnp.einsum("bgqd,bgtd->bgqt", qg, k.astype(jnp.float32)) * sm_scale
    pos = jnp.arange(max_pages * ps)[None, None, None, :]
    s = jnp.where(pos < seq_lens[:, None, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgqt,bgtd->bgqd", p, v.astype(jnp.float32))
    return o.reshape(B, Hq, D).astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas kernel
# --------------------------------------------------------------------------

def _decode_kernel(page_table_ref, seq_lens_ref,  # scalar prefetch
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, sm_scale, page_size,
                   q_per_kv):
    b, pi = pl.program_id(0), pl.program_id(1)
    n_pages = pl.num_programs(1)
    seq_len = seq_lens_ref[b]

    @pl.when(pi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # tokens this page holds for this sequence: (0, page_size]
    page_start = pi * page_size
    valid = seq_len - page_start

    @pl.when(valid > 0)
    def _page():
        q = q_ref[0].astype(jnp.float32)         # [Hq, D]
        k = k_ref[0]                              # [Hkv, ps, D]
        v = v_ref[0]
        Hq = q.shape[0]
        Hkv = k.shape[0]
        qg = q.reshape(Hkv, q_per_kv, q.shape[-1])
        # batched over kv heads on the MXU: [Hkv, qpk, ps]
        s = lax.dot_general(
            qg, k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,)))) * sm_scale
        col = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < valid, s, _NEG_INF)
        m_prev = m_ref[:, :1]                     # [Hq, 1]
        l_prev = l_ref[:, :1]
        s2 = s.reshape(Hq, page_size)
        m_new = jnp.maximum(m_prev, s2.max(axis=-1, keepdims=True))
        p = jnp.where(jnp.isneginf(s2), 0.0, jnp.exp(s2 - m_new))
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_new))
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        pv = lax.dot_general(                      # [Hkv, qpk, D]
            p.reshape(Hkv, q_per_kv, page_size).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(Hq, -1)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(pi == n_pages - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("sm_scale", "interpret"))
def _paged_attention_pallas(q, k_pages, v_pages, page_table, seq_lens,
                            sm_scale: float, interpret: bool = False):
    B, Hq, D = q.shape
    P_, Hkv, ps, _ = k_pages.shape
    max_pages = page_table.shape[1]
    q_per_kv = Hq // Hkv

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, p, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, Hkv, ps, D),
                         lambda b, p, pt, sl: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, Hkv, ps, D),
                         lambda b, p, pt, sl: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, p, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, D), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
            pltpu.VMEM((Hq, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, sm_scale=sm_scale,
                               page_size=ps, q_per_kv=q_per_kv)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q, k_pages, v_pages)


def kernels_supported(device: Optional[jax.Device] = None) -> bool:
    if not _HAS_PALLAS:
        return False
    dev = device if device is not None else jax.devices()[0]
    return dev.platform == "tpu" or getattr(dev, "device_kind",
                                            "").startswith("TPU")


def paged_attention(q, k_pages, v_pages, page_table, seq_lens, *,
                    sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None,
                    impl: Optional[str] = None) -> jax.Array:
    """Dispatch: Pallas kernel on TPU, gather reference elsewhere.

    ``interpret=True`` forces the kernel through the Pallas interpreter
    (CPU) — used by tests to validate the kernel itself off-TPU.
    ``impl`` pins the implementation outright ("kernel" | "reference"):
    code that compiles for a SPECIFIC mesh (the tp serving engine) must
    choose by the mesh's platform, because the process's default backend
    (what the interpret=None autodetect sees) can be a different
    accelerator than the mesh the program runs on.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if q.shape[1] % k_pages.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads "
            f"{k_pages.shape[1]}")
    if impl == "reference":
        return paged_attention_reference(
            q, k_pages, v_pages, page_table, seq_lens, sm_scale=sm_scale)
    if impl is not None and impl != "kernel":
        raise ValueError(f"impl must be 'kernel' or 'reference', "
                         f"got {impl!r}")
    if interpret is None:
        if impl is None and not kernels_supported():
            return paged_attention_reference(
                q, k_pages, v_pages, page_table, seq_lens,
                sm_scale=sm_scale)
        interpret = False
    return _paged_attention_pallas(
        q, k_pages, v_pages, page_table,
        seq_lens.astype(jnp.int32), sm_scale, interpret)


# --------------------------------------------------------------------------
# Page-cache update helpers (used by the decode step / prefill)
# --------------------------------------------------------------------------

def write_decode_kv(k_pages, v_pages, k_new, v_new, page_table,
                    positions) -> Tuple[jax.Array, jax.Array]:
    """Scatter one token's K/V per sequence into the page pool.

    k_new/v_new: [B, Hkv, D]; positions: [B] slot of the token (0-based).
    """
    ps = k_pages.shape[2]
    page_ids = page_table[jnp.arange(page_table.shape[0]),
                          positions // ps]                       # [B]
    slots = positions % ps                                       # [B]
    k_pages = k_pages.at[page_ids, :, slots, :].set(
        k_new.astype(k_pages.dtype))
    v_pages = v_pages.at[page_ids, :, slots, :].set(
        v_new.astype(v_pages.dtype))
    return k_pages, v_pages


def write_chunk_kv(k_pages, v_pages, k_c, v_c, pages, start, valid_len,
                   ) -> Tuple[jax.Array, jax.Array]:
    """Scatter one prefill CHUNK's K/V — all layers at once — into one
    sequence's pages.

    k_c/v_c: [n_layers, C, Hkv, D] (C may be padded past the real
    chunk); k/v_pages: [n_layers, P, Hkv, ps, D]; pages: [max_pages]
    page ids (scratch-padded); start: absolute position of the chunk's
    first token (cached prefix + earlier chunks already occupy positions
    < start). Rows >= valid_len redirect to page 0 (the scratch page —
    garbage by contract), so padding never corrupts live pages.

    ONE scatter per chunk dispatch by design: threading the pool through
    the per-layer scan (the obvious structure) stacks it as scan
    carries/ys and degenerates into full-pool copies per layer — the
    chunk program went pool-size-proportional, ~7x slower than a whole
    128-token prefill on a 1024-page pool. Same discipline as
    write_prefill_kv/stage_prefill_kv.
    """
    ps = k_pages.shape[3]
    C = k_c.shape[1]
    idx = jnp.arange(C)
    pos = start + idx
    real = idx < valid_len
    page_idx = jnp.clip(pos // ps, 0, pages.shape[0] - 1)
    page_ids = jnp.where(real, pages[page_idx], 0)
    slots = jnp.where(real, pos % ps, 0)
    # advanced indices (page_ids, slots) at axes 1 and 3 are separated by
    # basic slices, so the indexed result is [C, n_layers, Hkv, D]
    k_pages = k_pages.at[:, page_ids, :, slots, :].set(
        k_c.transpose(1, 0, 2, 3).astype(k_pages.dtype))
    v_pages = v_pages.at[:, page_ids, :, slots, :].set(
        v_c.transpose(1, 0, 2, 3).astype(v_pages.dtype))
    return k_pages, v_pages


def paged_chunk_attention(q, k_prior, v_prior, k_c, v_c, prior_len, *,
                          sm_scale: Optional[float] = None) -> jax.Array:
    """Prefill-chunk attention: cached prefix + the chunk's own K/V.

    q: [C, Hq, D] chunk queries at absolute positions
    prior_len + arange(C); k/v_prior: [n, Hkv, ps, D] ONE layer's pages
    for this sequence, already gathered from the pool (positions
    >= prior_len in them are stale — masked here, overwritten by
    write_chunk_kv after the layer scan); k_c/v_c: [C, Hkv, D] the
    chunk's roped K/V computed this call. Query i sees prior positions
    t < prior_len plus chunk positions j <= i, so the chunk never has to
    round-trip through the pool before attending. Gather-based: the
    chunk path is dispatch-bound, not FLOP-bound, at serving chunk
    sizes, and runs on every backend (the Pallas decode kernel is
    single-query).
    """
    C, Hq, D = q.shape
    n, Hkv, ps, _ = k_prior.shape
    if sm_scale is None:
        sm_scale = D ** -0.5
    T = n * ps
    k = jnp.concatenate(
        [k_prior.transpose(1, 0, 2, 3).reshape(Hkv, T, D),
         k_c.transpose(1, 0, 2)], axis=1)                  # [Hkv, T+C, D]
    v = jnp.concatenate(
        [v_prior.transpose(1, 0, 2, 3).reshape(Hkv, T, D),
         v_c.transpose(1, 0, 2)], axis=1)
    qg = q.reshape(C, Hkv, Hq // Hkv, D).astype(jnp.float32)
    s = jnp.einsum("cgqd,gtd->cgqt", qg, k.astype(jnp.float32)) * sm_scale
    i = jnp.arange(C)[:, None, None, None]
    t = jnp.arange(T + C)[None, None, None, :]
    visible = jnp.where(t < T, t < prior_len, (t - T) <= i)
    s = jnp.where(visible, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("cgqt,gtd->cgqd", p, v.astype(jnp.float32))
    return o.reshape(C, Hq, D).astype(q.dtype)


def write_prefill_kv(k_pages, v_pages, k_seq, v_seq, pages,
                     ) -> Tuple[jax.Array, jax.Array]:
    """Write a whole prompt's K/V into its pages.

    k_seq/v_seq: [T, Hkv, D] with T == len(pages) * page_size (pad the
    prompt KV to a page multiple first); pages: [n] page ids.
    """
    ps = k_pages.shape[2]
    n = pages.shape[0]
    kp = k_seq.reshape(n, ps, *k_seq.shape[1:]).transpose(0, 2, 1, 3)
    vp = v_seq.reshape(n, ps, *v_seq.shape[1:]).transpose(0, 2, 1, 3)
    k_pages = k_pages.at[pages].set(kp.astype(k_pages.dtype))
    v_pages = v_pages.at[pages].set(vp.astype(v_pages.dtype))
    return k_pages, v_pages
