"""Opt-in int8 matmul for MLP blocks (dynamic symmetric quantization).

The TPU MXU runs int8×int8→int32 at 2x the bf16 rate (public spec
sheets for v5e/v5p list doubled INT8 TOPS), so quantizing the big MLP
matmuls is a direct MFU lever when the ~1% activation-scale error is
acceptable. Scheme: per-row activation scales (max-abs over the
contraction axis) × per-column weight scales — the standard "dynamic
W8A8" recipe; accumulation stays int32 and the rescale runs in fp32.

No calibration state: scales are recomputed from the live tensors every
call, so the path is a drop-in inside jit. Training still works: the
backward is a straight-through estimator at the matmul level — the
forward runs quantized on the MXU int8 path, gradients flow through the
exact fp matmul transpose (dx = g·wᵀ, dw = xᵀ·g in fp32), the same
trick quantization-aware training uses for the rounding nonlinearity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _quantize(x: jax.Array, axis: int):
    """Symmetric int8 quantization along `axis`: returns (q_int8, scale)
    with scale shaped like x but size-1 on `axis` (broadcastable)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127)
    return q.astype(jnp.int8), scale


#: dtype of the KV-page scale arrays. bf16, not fp32: scales are loaded
#: once per (token, head) and multiplied into a whole head_dim vector, so
#: their quantization error is second-order — but their FOOTPRINT decides
#: the int8 capacity win. Per page-slot-head bytes: D int8 + 2 scale vs
#: 2D fp16 ⇒ ratio 2D/(D+2) (1.94x at D=64); fp32 scales would give
#: 2D/(D+4) (1.88x) and lose the ≥1.9x capacity target.
KV_SCALE_DTYPE = jnp.bfloat16


def quantize_kv(x: jax.Array):
    """Quantize K or V rows to int8 with PER-TOKEN, PER-HEAD scales.

    x: [..., D] fp rows (last axis = head_dim). Returns (q, scale) with
    q int8 [..., D] and scale KV_SCALE_DTYPE [...] (no head_dim axis).
    Per-token granularity keeps every pool write LOCAL — decode appends,
    ragged chunk scatters and COW page copies never have to requantize
    neighbours the way a true per-page amax would.
    """
    q, scale = _quantize(x, axis=-1)
    return q, scale[..., 0].astype(KV_SCALE_DTYPE)


def dequantize_kv(q: jax.Array, scale: jax.Array,
                  dtype=jnp.float32) -> jax.Array:
    """Inverse of quantize_kv: q int8 [..., D], scale [...] -> fp [..., D]."""
    return q.astype(dtype) * scale.astype(dtype)[..., None]


def _int8_matmul_impl(x: jax.Array, w: jax.Array) -> jax.Array:
    xq, xs = _quantize(x, axis=-1)           # xs: [..., 1]
    wq, ws = _quantize(w, axis=0)            # ws: [1, N]
    out = lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = out.astype(jnp.float32) * xs * ws.reshape(
        (1,) * (x.ndim - 1) + (w.shape[1],))
    return out.astype(x.dtype)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """x [..., K] @ w [K, N] with both operands dynamically quantized to
    int8, int32 MXU accumulation, fp32 rescale; returns x.dtype.

    Per-row scales for x (over K), per-column scales for w (over K) keep
    the rescale rank-1 — one multiply per output element. Differentiable
    via a straight-through backward (exact fp transpose matmuls).
    """
    return _int8_matmul_impl(x, w)


def _int8_vjp_fwd(x, w):
    return _int8_matmul_impl(x, w), (x, w)


def _int8_vjp_bwd(res, g):
    x, w = res
    gf = g.astype(jnp.float32)
    # dx = g · wᵀ : contract g's last dim with w's output dim
    dx = lax.dot_general(gf, w.astype(jnp.float32),
                         (((g.ndim - 1,), (1,)), ((), ())))
    # dw = xᵀ · g : contract every leading (batch/seq) dim
    x2 = x.astype(jnp.float32).reshape(-1, x.shape[-1])
    g2 = gf.reshape(-1, g.shape[-1])
    dw = lax.dot_general(x2, g2, (((0,), (0,)), ((), ())))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_int8_vjp_fwd, _int8_vjp_bwd)
