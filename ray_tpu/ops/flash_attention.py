"""Flash attention: Pallas forward AND backward kernels.

No reference implementation exists in-tree (the reference delegates to
vLLM/CUDA — SURVEY.md §5 long-context); built from the public flash/
blockwise-attention recipes (PAPERS.md) on the Pallas TPU pattern:
stream KV blocks through VMEM with online-softmax accumulators in scratch,
never materializing the [L, L] score matrix in HBM — in either pass.

  flash_attention(q, k, v)  [B, L, H, D] → [B, L, H, D]
    fwd:  grid (B·H, Lq/blkq, Lk/blkk); saves per-row logsumexp.
    bwd:  two kernels — dq over (B·H, nq, nk) and dk/dv over (B·H, nk, nq)
          — recompute p = exp(s − lse) blockwise from the saved lse.
    causal blocks above the diagonal are skipped in all three kernels.

`blockwise_attention` is the pure-JAX (lax.scan) equivalent: same online
softmax, differentiable by autodiff, used as the numerics reference and as
a portable fallback.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = float("-inf")


# --------------------------------------------------------------------------
# Blockwise attention in pure JAX (reference numerics + portable fallback)
# --------------------------------------------------------------------------

def blockwise_attention(q, k, v, *, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        block_k: int = 256) -> jax.Array:
    """Online-softmax attention, scanning KV blocks; [B, L, H, D] layout."""
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    if sm_scale is None:
        sm_scale = D ** -0.5
    blk = min(block_k, Lk)
    if Lk % blk:
        raise ValueError(f"seq len {Lk} not divisible by block_k {blk}")
    nk = Lk // blk
    kb = k.reshape(B, nk, blk, H, D)
    vb = v.reshape(B, nk, blk, H, D)
    qpos = jnp.arange(Lq)
    qs = q * q.dtype.type(sm_scale)

    o0 = jnp.zeros((B, Lq, H, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)

    def step(carry, blk_idx):
        o, m, l = carry
        kt, vt = kb[:, blk_idx], vb[:, blk_idx]
        s = jnp.einsum("bqhd,bkhd->bhqk", qs, kt,
                       preferred_element_type=jnp.float32)
        if causal:
            kpos = blk_idx * blk + jnp.arange(blk)
            s = jnp.where((qpos[:, None] >= kpos[None, :])[None, None],
                          s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - m_new[..., None]))
        corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_new))
        l = l * corr + p.sum(axis=-1)
        o = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(vt.dtype), vt,
            preferred_element_type=jnp.float32)
        return (o, m_new, l), None

    (o, m, l), _ = lax.scan(step, (o0, m0, l0), jnp.arange(nk))
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


# --------------------------------------------------------------------------
# Pallas kernels ([BH, L, D] layout inside)
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal, sm_scale, blk_q, blk_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = True
    if causal:
        run = ki * blk_k <= qi * blk_q + blk_q - 1

    @pl.when(run)
    def _block():
        q = q_ref[0]
        s = lax.dot_general(  # bf16×bf16 → f32 accumulate on the MXU
            q, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * blk_q + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = ki * blk_k + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev, l_prev = m_ref[:, :1], l_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.where(jnp.isneginf(s), 0.0, jnp.exp(s - m_new))
        corr = jnp.where(jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - m_new))
        l_new = l_prev * corr + p.sum(axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        lse = m_ref[:, 0] + jnp.log(l[:, 0])
        # lse is materialized [8, blk_q] (sublane-replicated) to satisfy
        # the TPU (8, 128) tiling floor for output blocks.
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, causal, sm_scale, blk_q, blk_k):
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = ki * blk_k <= qi * blk_q + blk_q - 1

    @pl.when(run)
    def _block():
        s = lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * blk_q + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = ki * blk_k + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])     # masked rows → exp(-inf)=0
        dp = lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dq_acc[:] += lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                causal, sm_scale, blk_q, blk_k):
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = qi * blk_q + blk_q - 1 >= ki * blk_k

    @pl.when(run)
    def _block():
        s = lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * blk_q + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            kpos = ki * blk_k + lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dv_acc[:] += lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dk_acc[:] += lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# --------------------------------------------------------------------------
# pallas_call wrappers
# --------------------------------------------------------------------------

def _fwd_call(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    blk_q, blk_k = min(blk_q, Lq), min(blk_k, Lk)
    if Lq % blk_q or Lk % blk_k:
        raise ValueError(f"L ({Lq},{Lk}) must divide blocks ({blk_q},{blk_k})")
    kernel = functools.partial(_fwd_kernel, causal=causal, sm_scale=sm_scale,
                               blk_q=blk_q, blk_k=blk_k)
    return pl.pallas_call(
        kernel,
        grid=(BH, Lq // blk_q, Lk // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, blk_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, Lq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
            pltpu.VMEM((blk_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _bwd_call(q, k, v, o, lse, do, causal, sm_scale, blk_q, blk_k,
              interpret, dlse=None):
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    blk_q, blk_k = min(blk_q, Lq), min(blk_k, Lk)
    delta = jnp.einsum("bld,bld->bl", do.astype(jnp.float32),
                       o.astype(jnp.float32))
    if dlse is not None:
        # lse cotangent folds into delta: ds = p∘(dP − delta + dlse)
        # because d lse/d s = p — so the kernels run unchanged with
        # delta' = delta − dlse (the flash_attention_block merge path)
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[:, None, :], (BH, 8, Lq))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, sm_scale=sm_scale,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(BH, Lq // blk_q, Lk // blk_k),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, blk_q), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, 8, blk_q), lambda b, i, j: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          blk_q=blk_q, blk_k=blk_k),
        grid=(BH, Lk // blk_k, Lq // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 8, blk_q), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((1, 8, blk_q), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# Public API with custom VJP
# --------------------------------------------------------------------------

def _bhl(x):
    B, L, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, L, D)


def _blhd(x, B, H):
    BH, L, D = x.shape
    return x.reshape(B, H, L, D).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    blk_q: Optional[int] = 256, blk_k: Optional[int] = 256,
                    interpret: bool = False) -> jax.Array:
    """[B, L, H, D] flash attention; Pallas fwd+bwd, O(L·blk) memory.

    blk_q/blk_k None → use the autotuned block for this (L, head_dim,
    dtype, platform) when one is cached (see autotune_blocks), else the
    classic 256. Thin facade over flash_attention_block (which also
    exposes lse for the ring-attention merge); the discarded lse output
    contributes a zero cotangent that the shared backward folds away."""
    if blk_q is None or blk_k is None:
        tuned = get_tuned_blocks(q.shape[1], k.shape[1], q.shape[-1],
                                 q.dtype) or (256, 256)
        blk_q = tuned[0] if blk_q is None else blk_q
        blk_k = tuned[1] if blk_k is None else blk_k
    return flash_attention_block(q, k, v, causal, sm_scale, blk_q, blk_k,
                                 interpret)[0]


# --------------------------------------------------------------------------
# Block API: (o, lse) with differentiable lse — the ring-attention inner
# kernel (per-rotation fused block whose results merge by log-sum-exp)
# --------------------------------------------------------------------------

def pick_block(L: int, preferred: int = 256, min_block: int = 8
               ) -> Optional[int]:
    """Largest kernel block size <= preferred that divides L (Pallas grid
    constraint); None when no divisor >= min_block exists. The default
    floor of 8 matches the Mosaic sublane tiling — COMPILED kernels must
    never run below it (callers fall back to the einsum/blockwise path
    instead); only interpret-mode callers, where no Mosaic tiling exists,
    may pass min_block=1 for tiny shards."""
    for b in (preferred, 128, 64, 32, 16, 8, 4, 2, 1):
        if min_block <= b <= preferred and L % b == 0:
            return min(b, L)
    return None


# --------------------------------------------------------------------------
# Block-size autotuning: sweep + cache per (Lq, Lk, head_dim, dtype,
# platform). The fixed 256 default is tuned for long sequences; at bench
# shapes (L=2048, head_dim 128) the best (blk_q, blk_k) depends on VMEM
# pressure and MXU occupancy, so measure instead of guessing. CPU hosts
# (tests) never measure — the heuristic ranking alone picks the block.
# --------------------------------------------------------------------------

_BLOCK_CACHE: dict = {}
_BLOCK_SIZES = (512, 256, 128, 64, 32, 16, 8)
_VMEM_BUDGET = 12 * 1024 * 1024  # conservative per-core VMEM budget


def clear_block_cache() -> None:
    _BLOCK_CACHE.clear()


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:  # pragma: no cover — no backend at all
        return "cpu"


def _block_cache_key(Lq, Lk, head_dim, dtype):
    return (int(Lq), int(Lk), int(head_dim), jnp.dtype(dtype).name,
            _platform())


def get_tuned_blocks(Lq, Lk, head_dim, dtype) -> Optional[tuple]:
    """Cache-only lookup of a tuned (blk_q, blk_k) — safe at trace time
    (no sweep). None when this shape was never autotuned."""
    return _BLOCK_CACHE.get(_block_cache_key(Lq, Lk, head_dim, dtype))


def _est_vmem_bytes(blk_q: int, blk_k: int, D: int, itemsize: int) -> int:
    """Rough resident-VMEM model of the fwd/bwd kernels: operand blocks in
    their dtype + f32 accumulators/score tiles."""
    operand = itemsize * (2 * blk_q * D + 2 * blk_k * D)
    accum = 4 * (3 * blk_q * D + 2 * blk_q * 128 + 2 * blk_q * blk_k)
    return operand + accum


def block_candidates(Lq: int, Lk: int, head_dim: int,
                     dtype=jnp.bfloat16) -> list:
    """(blk_q, blk_k) pairs that divide the sequence lengths, respect the
    Mosaic >= 8 floor, and fit the VMEM model — heuristic-best first
    (closest to the classic 256x256 flash block)."""
    itemsize = jnp.dtype(dtype).itemsize
    qs = [b for b in _BLOCK_SIZES if b <= Lq and Lq % b == 0]
    ks = [b for b in _BLOCK_SIZES if b <= Lk and Lk % b == 0]
    pairs = [(bq, bk) for bq in qs for bk in ks
             if _est_vmem_bytes(bq, bk, head_dim, itemsize) <= _VMEM_BUDGET]
    return sorted(pairs, key=lambda p: (abs(p[0] - 256) + abs(p[1] - 256),
                                        -(p[0] * p[1])))


def _time_blocks(Lq, Lk, D, dtype, blk_q, blk_k, *, bh: int = 8,
                 reps: int = 3) -> float:
    """Wall-time one candidate: fwd kernel + both bwd kernels, jitted,
    median-of-reps. Returns +inf when the candidate fails to compile."""
    import time as _time
    try:
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        q = jax.random.normal(ks[0], (bh, Lq, D), dtype)
        k = jax.random.normal(ks[1], (bh, Lk, D), dtype)
        v = jax.random.normal(ks[2], (bh, Lk, D), dtype)
        do = jax.random.normal(ks[3], (bh, Lq, D), dtype)
        scale = D ** -0.5
        fwd = jax.jit(lambda q, k, v: _fwd_call(
            q, k, v, True, scale, blk_q, blk_k, False))
        bwd = jax.jit(lambda q, k, v, o, lse, do: _bwd_call(
            q, k, v, o, lse, do, True, scale, blk_q, blk_k, False))
        o, lse = fwd(q, k, v)
        jax.block_until_ready(bwd(q, k, v, o, lse, do))  # warm both
        times = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            o, lse = fwd(q, k, v)
            jax.block_until_ready(bwd(q, k, v, o, lse, do))
            times.append(_time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]
    except Exception:  # noqa: BLE001 — a failing candidate just loses
        return float("inf")


def autotune_blocks(Lq: int, Lk: Optional[int] = None, head_dim: int = 64,
                    dtype=jnp.bfloat16, *,
                    measure: Optional[bool] = None) -> Optional[tuple]:
    """Pick (blk_q, blk_k) for the flash kernels at this shape and cache
    it per (Lq, Lk, head_dim, dtype, platform).

    measure=None → sweep-and-time only where the Mosaic kernels actually
    lower (real TPU; CPU hosts rank heuristically — timing interpret mode
    would measure the emulator, not the kernel). Returns None when no
    block >= the Mosaic floor divides the lengths (callers fall back to
    the einsum/blockwise path). Call this EAGERLY (e.g. bench warm-up)
    so jit traces hit the cache via get_tuned_blocks."""
    Lk = Lq if Lk is None else Lk
    key = _block_cache_key(Lq, Lk, head_dim, dtype)
    if key in _BLOCK_CACHE:
        return _BLOCK_CACHE[key]
    cands = block_candidates(Lq, Lk, head_dim, dtype)
    if not cands:
        return None
    if measure is None:
        measure = kernels_supported()
    best = cands[0]
    if measure and len(cands) > 1:
        best = min(cands, key=lambda bk: _time_blocks(
            Lq, Lk, head_dim, dtype, *bk))
    _BLOCK_CACHE[key] = best
    return best


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention_block(q, k, v, causal: bool = True,
                          sm_scale: Optional[float] = None,
                          blk_q: int = 256, blk_k: int = 256,
                          interpret: bool = False):
    """Fused attention of q against ONE KV block: returns (o [B,L,H,D],
    lse [B,H,Lq]). lse is differentiable — its cotangent (nonzero when
    block results are merged across ring rotations) folds into the
    backward kernels' delta term, so the same Pallas kernels serve both
    the standalone and the ring-merged case."""
    out, _ = _block_vjp_fwd(q, k, v, causal, sm_scale, blk_q, blk_k,
                            interpret)
    return out


def _block_vjp_fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    B, Lq, H, D = q.shape
    scale = sm_scale if sm_scale is not None else D ** -0.5
    o, lse = _fwd_call(_bhl(q), _bhl(k), _bhl(v), causal, scale,
                       blk_q, blk_k, interpret)
    lse_bhl = lse[:, 0, :].reshape(B, H, Lq)
    return (_blhd(o, B, H), lse_bhl), (q, k, v, o, lse)


def _block_vjp_bwd(causal, sm_scale, blk_q, blk_k, interpret, res, g):
    do, dlse = g
    q, k, v, o, lse = res
    B, Lq, H, D = q.shape
    scale = sm_scale if sm_scale is not None else D ** -0.5
    dq, dk, dv = _bwd_call(_bhl(q), _bhl(k), _bhl(v), o, lse, _bhl(do),
                           causal, scale, blk_q, blk_k, interpret,
                           dlse=dlse.reshape(B * H, Lq))
    return _blhd(dq, B, H), _blhd(dk, B, H), _blhd(dv, B, H)


flash_attention_block.defvjp(_block_vjp_fwd, _block_vjp_bwd)


def kernels_supported() -> bool:
    """True when the Mosaic TPU kernels can actually lower here."""
    if not _HAS_PALLAS:
        return False
    dev = jax.devices()[0]
    return dev.platform == "tpu" or getattr(dev, "device_kind",
                                            "").startswith("TPU")


def flash_attention_sharded(q, k, v, mesh, *, causal: bool = True,
                            head_axis: str = "tp",
                            batch_axes=("dp", "fsdp")) -> jax.Array:
    """shard_map wrapper: pallas_call is a Mosaic custom call that GSPMD
    cannot auto-partition, so run the kernel per-shard (batch over dp/fsdp,
    heads over tp; seq must NOT be sharded — use ring attention for sp)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    if mesh.shape.get("sp", 1) > 1:
        raise ValueError("flash_attention_sharded cannot shard the sequence "
                         "axis; use attention='ring' when sp > 1")
    spec = P(batch_axes, None, head_axis, None)
    fn = shard_map_compat(
        functools.partial(flash_attention, causal=causal,
                          blk_q=None, blk_k=None),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
