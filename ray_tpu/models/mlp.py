"""Minimal MLP classifier — the MNIST-scale model of the Train MVP slice
(SURVEY.md §7 minimum end-to-end slice; reference equivalent: the torch MLP
configs driven through DataParallelTrainer, train/data_parallel_trainer.py:26).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: int = 256
    n_hidden: int = 2
    out_dim: int = 10
    dtype: Any = jnp.float32


def mlp_init(cfg: MLPConfig, key: jax.Array):
    dims = [cfg.in_dim] + [cfg.hidden] * cfg.n_hidden + [cfg.out_dim]
    keys = jax.random.split(key, len(dims) - 1)
    params = []
    for k, (din, dout) in zip(keys, zip(dims[:-1], dims[1:])):
        params.append({
            "w": (jax.random.normal(k, (din, dout)) * din ** -0.5
                  ).astype(cfg.dtype),
            "b": jnp.zeros((dout,), cfg.dtype),
        })
    return params


def mlp_specs(cfg: MLPConfig):
    """Hidden dims shard over tp; replicate the rest (dp/fsdp shard data)."""
    specs = []
    dims = [cfg.in_dim] + [cfg.hidden] * cfg.n_hidden + [cfg.out_dim]
    for din, dout in zip(dims[:-1], dims[1:]):
        specs.append({"w": P(None, "tp"), "b": P("tp")})
    specs[-1] = {"w": P(None, None), "b": P(None)}
    return specs


def mlp_apply(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params, batch):
    x, y = batch
    logits = mlp_apply(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
