"""Llama-family decoder-only transformer, TPU-first.

Design (contrast reference: models live outside the tree in torch/vLLM —
SURVEY.md §2.5 Ray LLM row):
  * pure functions: `init_params` → pytree, `forward(params, tokens)` → logits
  * `param_specs` returns a PartitionSpec pytree aligned leaf-for-leaf with
    params — fsdp shards the embed/ffn input dims, tp shards heads/ffn
    hidden, pp shards the stacked layer dim
  * layers are STACKED on axis 0 and applied with `lax.scan` + remat: one
    compiled layer body regardless of depth (XLA-friendly, constant compile
    time), and the stack shards over `pp` for pipeline parallelism
  * attention: "full" (GSPMD auto-sharded), "ring" (manual `sp` ring over
    ICI — ray_tpu.parallel.ring_attention), or "ulysses" (all-to-all)
  * bf16 activations/compute, fp32 params & softmax/logit accumulators
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.attention import causal_attention
from ray_tpu.parallel.mesh import shard_map_compat
from ray_tpu.parallel.pipeline import pipeline_apply
from ray_tpu.parallel.ring_attention import (ring_attention,
                                             ring_attention_sharded)
from ray_tpu.parallel.ulysses import ulysses_attention_sharded

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    attention: str = "full"          # full | flash | ring | ulysses
    dtype: Any = jnp.bfloat16        # activation/compute dtype
    param_dtype: Any = jnp.float32   # master weights
    remat: bool = True
    remat_policy: str = "full"       # full | dots | dots_no_batch | selective
    pp_microbatches: int = 4         # microbatch count when pp > 1
    fsdp_overlap: bool = False       # explicit prefetch-scheduled fsdp step
    int8_mlp: bool = False           # dynamic-W8A8 MLP matmuls (ops.int8)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Test-scale config for the virtual CPU mesh."""
        base = dict(vocab_size=256, dim=64, n_layers=4, n_heads=8,
                    n_kv_heads=4, ffn_dim=128, rope_theta=10000.0)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        base = dict(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, ffn_dim=14336)
        base.update(kw)
        return LlamaConfig(**base)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    ks = jax.random.split(key, 8)
    d, L = cfg.dim, cfg.n_layers
    hq, hkv, hd, f = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim
    pd = cfg.param_dtype

    def norm_init(*shape):
        return jnp.ones(shape, pd)

    def dense(k, *shape, fan_in=None):
        fan_in = fan_in if fan_in is not None else shape[-2]
        return (jax.random.normal(k, shape) * (fan_in ** -0.5)).astype(pd)

    return {
        "embed": dense(ks[0], cfg.vocab_size, d, fan_in=d),
        "layers": {
            "attn_norm": norm_init(L, d),
            "wq": dense(ks[1], L, d, hq * hd),
            "wk": dense(ks[2], L, d, hkv * hd),
            "wv": dense(ks[3], L, d, hkv * hd),
            "wo": dense(ks[4], L, hq * hd, d),
            "mlp_norm": norm_init(L, d),
            "w_gate": dense(ks[5], L, d, f),
            "w_up": dense(ks[6], L, d, f),
            "w_down": dense(ks[7], L, f, d),
        },
        "final_norm": norm_init(d),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpec pytree aligned with init_params' output.

    Stacked layer dim shards over pp; matmul input dims over fsdp (ZeRO-3
    gather), head/ffn-hidden dims over tp (Megatron) — the §2.6 inventory's
    TPU-native equivalents.
    """
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "attn_norm": P("pp", None),
            "wq": P("pp", "fsdp", "tp"),
            "wk": P("pp", "fsdp", "tp"),
            "wv": P("pp", "fsdp", "tp"),
            "wo": P("pp", "tp", "fsdp"),
            "mlp_norm": P("pp", None),
            "w_gate": P("pp", "fsdp", "tp"),
            "w_up": P("pp", "fsdp", "tp"),
            "w_down": P("pp", "tp", "fsdp"),
        },
        "final_norm": P(None),
    }


def _rmsnorm(x, w, eps):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * rms).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding; x: [B, L, H, D_even], positions: [L] or [B, L]."""
    d2 = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d2, dtype=jnp.float32) / d2)
    ang = positions[..., None].astype(jnp.float32) * freqs  # [..., L, d2]
    if ang.ndim == 2:  # [L, d2] → broadcast over batch
        ang = ang[None]
    cos, sin = jnp.cos(ang)[:, :, None, :], jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :d2].astype(jnp.float32), x[..., d2:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _full_attention(q, k, v):
    """Causal attention (shared fp32 kernel), output in q's dtype."""
    return causal_attention(q, k, v).astype(q.dtype)


#: checkpoint_name tags on the 7 projection-matmul outputs per layer —
#: what remat_policy="selective" saves (and nothing else)
SELECTIVE_SAVE_NAMES = ("attn_q", "attn_k", "attn_v", "attn_o",
                        "mlp_gate", "mlp_up", "mlp_down",
                        "moe_out")  # mixtral's combined expert output


def remat_policy_fn(name: str):
    """Config string → jax.checkpoint policy (shared with mixtral).

    "dots" saves EVERY dot output — including the [B, H, L, L] attention
    scores, whose save cost scales L²; "selective" saves only the 7 named
    projection outputs per layer (all [B, L, ·]), recomputing norms/rope/
    attention — the TorchTitan-style middle ground between full remat
    (max recompute) and dots (max residual memory)."""
    if name == "full":
        return None
    if name == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if name == "dots_no_batch":
        return jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    if name == "selective":
        return jax.checkpoint_policies.save_only_these_names(
            *SELECTIVE_SAVE_NAMES)
    raise ValueError(f"unknown remat_policy {name!r}")


def _layer(lp: Params, x, cfg: LlamaConfig, positions, attn_fn):
    """One transformer block; lp leaves have the layer axis removed."""
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    B, L, _ = x.shape
    cd = cfg.dtype

    if cfg.int8_mlp:
        from ray_tpu.ops.int8 import int8_matmul

        def mlp_mm(a, w):
            return int8_matmul(a, w.astype(cd))
    else:
        def mlp_mm(a, w):
            return a @ w.astype(cd)

    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = checkpoint_name(h @ lp["wq"].astype(cd), "attn_q")
    k = checkpoint_name(h @ lp["wk"].astype(cd), "attn_k")
    v = checkpoint_name(h @ lp["wv"].astype(cd), "attn_v")
    q = _rope(q.reshape(B, L, hq, hd), positions, cfg.rope_theta)
    k = _rope(k.reshape(B, L, hkv, hd), positions, cfg.rope_theta)
    v = v.reshape(B, L, hkv, hd)
    if hkv != hq:  # GQA: repeat KV groups to full head count
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    o = attn_fn(q, k, v).reshape(B, L, hq * hd)
    x = x + checkpoint_name(o @ lp["wo"].astype(cd), "attn_o")

    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(checkpoint_name(mlp_mm(h, lp["w_gate"]), "mlp_gate"))
    up = checkpoint_name(mlp_mm(h, lp["w_up"]), "mlp_up")
    x = x + checkpoint_name(mlp_mm(gate * up, lp["w_down"]), "mlp_down")
    return x


def _scan_layers(layers: Params, x, cfg: LlamaConfig, positions, attn_fn):
    body = functools.partial(_layer, cfg=cfg, positions=positions,
                             attn_fn=attn_fn)
    if cfg.remat:
        body = jax.checkpoint(body, policy=remat_policy_fn(cfg.remat_policy))

    def step(x, lp):
        return body(lp, x), None

    x, _ = lax.scan(step, x, layers)
    return x


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig,
            mesh=None) -> jax.Array:
    """tokens [B, L] int32 → logits [B, L, vocab] (fp32).

    mesh is required for ring/ulysses attention and for pp > 1 (the stacked
    layer axis sharded over 'pp'); with attention='full' and pp==1 the whole
    forward is a single GSPMD program.
    """
    B, L = tokens.shape
    cd = cfg.dtype
    x = params["embed"].astype(cd)[tokens]
    positions = jnp.arange(L)

    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1:
        x = _forward_pipelined(params, x, cfg, mesh, positions)
    else:
        attn_fn = _make_attn_fn(cfg, mesh)
        x = _scan_layers(params["layers"], x, cfg, positions, attn_fn)

    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    # Tied embeddings: logits = x · embedᵀ. bf16 operands on the MXU with
    # fp32 ACCUMULATION (f32 operands would leave the MXU fast path).
    return jnp.einsum("bld,vd->blv", x.astype(cd),
                      params["embed"].astype(cd),
                      preferred_element_type=jnp.float32)


def _make_attn_fn(cfg: LlamaConfig, mesh):
    if cfg.attention == "full":
        return _full_attention
    if cfg.attention == "flash":
        from ray_tpu.ops import flash_attention
        from ray_tpu.ops.flash_attention import (blockwise_attention,
                                                 flash_attention_sharded,
                                                 kernels_supported)
        if not kernels_supported():
            # Portable fallback (CPU test meshes): same blockwise numerics.
            return lambda q, k, v: blockwise_attention(q, k, v).astype(q.dtype)
        if mesh is not None:
            return functools.partial(flash_attention_sharded, mesh=mesh)
        # blk=None: use the autotuned block for this shape when one is
        # cached (bench warms the cache eagerly), else the classic 256
        return functools.partial(flash_attention, blk_q=None, blk_k=None)
    if mesh is None:
        raise ValueError(f"attention={cfg.attention!r} needs a mesh")
    if cfg.attention == "ring":
        # pp > 1 runs attention inside the PARTIAL-manual pipeline
        # shard_map where fsdp/tp stay GSPMD-auto — a Mosaic pallas_call
        # cannot be auto-partitioned there, so force the einsum ring path
        # (full-manual single-stage meshes keep the fused auto-default)
        use_kernel = False if mesh.shape.get("pp", 1) > 1 else None
        return functools.partial(ring_attention_sharded, mesh=mesh,
                                 use_kernel=use_kernel)
    if cfg.attention == "ulysses":
        return functools.partial(ulysses_attention_sharded, mesh=mesh)
    raise ValueError(f"unknown attention {cfg.attention!r}")


def _forward_pipelined(params: Params, x, cfg: LlamaConfig, mesh, positions):
    """pp > 1: microbatch the batch dim, run stages over the 'pp' axis.

    The stacked layer axis is ALREADY sharded over pp (param_specs), so each
    stage's shard_map block holds n_layers/pp layers; activations hop via
    ppermute inside pipeline_apply. Embedding/head stay outside the pipeline
    (they are not stage-shaped — same trick as classic GPipe embeddings).
    Manual axes: {'pp'} (+'sp' for ring attention); fsdp/tp stay GSPMD-auto.
    """
    B, L, D = x.shape
    M = min(cfg.pp_microbatches, B)
    if B % M:
        raise ValueError(f"batch {B} not divisible by pp_microbatches {M}")
    xm = x.reshape(M, B // M, L, D)

    manual = {"pp"}
    if cfg.attention == "ring":
        manual.add("sp")

        def attn_fn(q, k, v):
            return ring_attention(q, k, v, axis_name="sp")
    elif cfg.attention == "full":
        attn_fn = _full_attention
    else:
        raise ValueError("pp>1 supports attention in {'full','ring'}")

    seq_dim_spec = "sp" if "sp" in manual else None

    def run(layers, xm):
        def stage_fn(layers, xb):
            Lloc = xb.shape[1]
            if "sp" in manual:
                off = lax.axis_index("sp") * Lloc
            else:
                off = 0
            pos = off + jnp.arange(Lloc)
            return _scan_layers(layers, xb, cfg, pos, attn_fn)

        return pipeline_apply(stage_fn, layers, xm, axis_name="pp")

    # Partial-manual shard_map: specs may ONLY name the manual axes; the
    # dp/fsdp batch sharding stays GSPMD-auto and flows through untouched.
    xspec = P(None, None, seq_dim_spec, None)
    lspec = jax.tree.map(lambda _: P("pp"), params["layers"])
    out = shard_map_compat(run, mesh=mesh,
                           in_specs=(lspec, xspec), out_specs=xspec,
                           axis_names=manual)(params["layers"], xm)
    return out.reshape(B, L, D)


def _nll_mean(logits, tokens):
    """Shifted next-token NLL mean; logits [B, L, V] fp32, tokens [B, L]."""
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


def _loss_overlap(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                  mesh) -> jax.Array:
    """fsdp_overlap=True loss: full-manual shard_map over (dp, fsdp) with
    the prefetch-scheduled layer scan (parallel.fsdp_overlap) instead of
    GSPMD-placed gathers. Numerics match loss_fn exactly (parity-tested);
    only the collective schedule differs. Requires pp == sp == tp == 1 —
    jax 0.4.x shard_map_compat degrades partial-manual to full manual,
    so every other parallelism axis must be trivial here.
    """
    from ray_tpu.parallel.fsdp_overlap import (drop_leading_dim,
                                               gather_params, overlap_scan,
                                               project_specs)

    for ax in ("pp", "sp", "tp"):
        if mesh.shape.get(ax, 1) > 1:
            raise ValueError(
                f"fsdp_overlap runs full-manual over (dp, fsdp); mesh axis "
                f"{ax!r} has size {mesh.shape[ax]} > 1")
    if cfg.attention not in ("full", "flash"):
        raise ValueError(
            f"fsdp_overlap supports attention in {{'full','flash'}}, got "
            f"{cfg.attention!r}")
    attn_fn = _make_attn_fn(cfg, None)  # per-shard, batch-only sharding
    specs = project_specs(param_specs(cfg), ("fsdp",))
    lspecs = drop_leading_dim(specs["layers"])
    cd = cfg.dtype

    def block(params, tokens):
        L = tokens.shape[1]
        positions = jnp.arange(L)
        embed = gather_params(params["embed"], specs["embed"], "fsdp")
        x = embed.astype(cd)[tokens]
        body = functools.partial(_layer, cfg=cfg, positions=positions,
                                 attn_fn=attn_fn)
        if cfg.remat:
            body = jax.checkpoint(body,
                                  policy=remat_policy_fn(cfg.remat_policy))
        x = overlap_scan(params["layers"], lspecs, x, body, cfg.n_layers,
                         axis_name="fsdp")
        x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
        logits = jnp.einsum("bld,vd->blv", x.astype(cd), embed.astype(cd),
                            preferred_element_type=jnp.float32)
        # equal-size batch shards → pmean of shard means == global mean
        return lax.pmean(_nll_mean(logits, tokens), ("dp", "fsdp"))

    fn = shard_map_compat(block, mesh=mesh,
                          in_specs=(specs, P(("dp", "fsdp"), None)),
                          out_specs=P())
    return fn(params, tokens)


def loss_fn(params: Params, tokens: jax.Array, cfg: LlamaConfig,
            mesh=None) -> jax.Array:
    """Next-token cross-entropy (mean over B×(L-1) positions), fp32.

    The FULL sequence goes through forward (keeps L divisible by the sp
    axis for ring/ulysses); the shift happens on logits afterwards.

    cfg.fsdp_overlap routes to the explicit prefetch-scheduled manual
    step (same numerics, overlap-friendly collective placement) whenever
    the mesh actually shards fsdp.
    """
    if cfg.fsdp_overlap and mesh is not None \
            and mesh.shape.get("fsdp", 1) > 1:
        return _loss_overlap(params, tokens, cfg, mesh)
    logits = forward(params, tokens, cfg, mesh)
    return _nll_mean(logits, tokens)


def num_params(cfg: LlamaConfig) -> int:
    d, L, f = cfg.dim, cfg.n_layers, cfg.ffn_dim
    hd = cfg.head_dim
    per_layer = (d * cfg.n_heads * hd + 2 * d * cfg.n_kv_heads * hd
                 + cfg.n_heads * hd * d + 3 * d * f + 2 * d)
    return cfg.vocab_size * d + L * per_layer + d


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approx training FLOPs/token: 6·N_params + attention score term.

    The embed matrix counts: it is tied as the LM head, so its matmul runs.
    The attention term uses the full (non-causal) 12·L·d·s convention
    (PaLM appendix B); causal kernels do ~half that score work.
    """
    attn = 12 * cfg.n_layers * cfg.dim * seq_len  # fwd+bwd qk+pv scores
    return 6.0 * num_params(cfg) + attn
