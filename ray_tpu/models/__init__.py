"""ray_tpu.models — flagship model family (functional JAX, mesh-shardable).

The reference delegates model code to torch/vLLM; here models are first-class
TPU citizens: pure functions over parameter pytrees with matching
PartitionSpec pytrees, scan-over-layers + remat, bf16 compute / fp32 master
params, and attention selectable between full, ring (sequence-parallel over
ICI) and Ulysses all-to-all.
"""

from ray_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_specs,
    forward,
    loss_fn,
)
from ray_tpu.models.mlp import MLPConfig, mlp_init, mlp_apply

__all__ = [
    "LlamaConfig", "init_params", "param_specs", "forward", "loss_fn",
    "MLPConfig", "mlp_init", "mlp_apply",
]
