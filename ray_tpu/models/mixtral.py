"""Mixtral-family sparse-MoE decoder transformer, TPU-first.

Second model family next to models/llama.py (reference scope: Ray serves
Mixtral through vLLM out-of-tree — SURVEY.md §2.5 Ray LLM row; the
architecture here follows the public Mixtral-8x7B description: Llama-style
GQA attention + top-2 routed expert FFN per layer).

Same design rules as llama.py: pure init/forward functions, stacked layers
applied with `lax.scan` + remat (one compiled layer body), `param_specs`
aligned leaf-for-leaf for pjit — with the expert dimension sharded over the
`ep` mesh axis (parallel/moe.py all_to_all dispatch) on top of llama's
fsdp/tp/pp axes. The router's load-balancing auxiliary loss (Switch-style
f·P term) accumulates through the scan carry.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ray_tpu.models.llama import (_full_attention, _nll_mean, _rmsnorm,
                                  _rope, remat_policy_fn)
from ray_tpu.parallel.mesh import shard_map_compat
from ray_tpu.parallel.moe import _routing, moe_ffn, moe_ffn_sharded

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    top_k: int = 2
    rope_theta: float = 1000000.0
    norm_eps: float = 1e-5
    aux_loss_coef: float = 0.01
    capacity_factor: float = 1.25  # ep dispatch buckets (overflow drops)
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    remat_policy: str = "full"     # full | dots | dots_no_batch | selective
    fsdp_overlap: bool = False     # explicit prefetch-scheduled fsdp step

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def tiny(**kw) -> "MixtralConfig":
        """Test-scale config for the virtual CPU mesh."""
        base = dict(vocab_size=256, dim=64, n_layers=2, n_heads=8,
                    n_kv_heads=4, ffn_dim=96, n_experts=4, top_k=2,
                    rope_theta=10000.0)
        base.update(kw)
        return MixtralConfig(**base)

    @staticmethod
    def mixtral_8x7b(**kw) -> "MixtralConfig":
        base = dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, ffn_dim=14336, n_experts=8, top_k=2)
        base.update(kw)
        return MixtralConfig(**base)


def init_params(cfg: MixtralConfig, key: jax.Array) -> Params:
    ks = jax.random.split(key, 9)
    d, L, E, f = cfg.dim, cfg.n_layers, cfg.n_experts, cfg.ffn_dim
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    pd = cfg.param_dtype

    def dense(k, *shape, fan_in):
        return (jax.random.normal(k, shape) * (fan_in ** -0.5)).astype(pd)

    return {
        "embed": dense(ks[0], cfg.vocab_size, d, fan_in=d),
        "layers": {
            "attn_norm": jnp.ones((L, d), pd),
            "wq": dense(ks[1], L, d, hq * hd, fan_in=d),
            "wk": dense(ks[2], L, d, hkv * hd, fan_in=d),
            "wv": dense(ks[3], L, d, hkv * hd, fan_in=d),
            "wo": dense(ks[4], L, hq * hd, d, fan_in=hq * hd),
            "moe_norm": jnp.ones((L, d), pd),
            "router": dense(ks[5], L, d, E, fan_in=d),
            "w_gate": dense(ks[8], L, E, d, f, fan_in=d),
            "w_in": dense(ks[6], L, E, d, f, fan_in=d),
            "w_out": dense(ks[7], L, E, f, d, fan_in=f),
        },
        "final_norm": jnp.ones((d,), pd),
    }


def param_specs(cfg: MixtralConfig) -> Params:
    """Stacked layer dim over pp; attention matmuls over fsdp/tp exactly as
    llama; the EXPERT dim over ep (parallel/moe.py holds E/ep experts per
    device and all_to_alls tokens to them)."""
    return {
        "embed": P("tp", "fsdp"),
        "layers": {
            "attn_norm": P("pp", None),
            "wq": P("pp", "fsdp", "tp"),
            "wk": P("pp", "fsdp", "tp"),
            "wv": P("pp", "fsdp", "tp"),
            "wo": P("pp", "tp", "fsdp"),
            "moe_norm": P("pp", None),
            "router": P("pp", None, None),
            "w_gate": P("pp", "ep", "fsdp", None),
            "w_in": P("pp", "ep", "fsdp", None),
            "w_out": P("pp", "ep", None, "fsdp"),
        },
        "final_norm": P(None),
    }


def _aux_loss(router_probs: jnp.ndarray, topk_idx: jnp.ndarray,
              n_experts: int) -> jnp.ndarray:
    """Switch-transformer load-balance term: E · Σ_e f_e · P_e where f_e is
    the fraction of routed assignments to expert e and P_e the mean router
    probability — minimized when routing is uniform."""
    f = jnp.mean(
        jax.nn.one_hot(topk_idx, n_experts, dtype=jnp.float32), axis=(0, 1))
    p = jnp.mean(router_probs, axis=0)
    return n_experts * jnp.sum(f * p)


def _layer(lp: Params, x, cfg: MixtralConfig, positions, mesh):
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    B, L, d = x.shape
    cd = cfg.dtype

    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = checkpoint_name(h @ lp["wq"].astype(cd), "attn_q")
    k = checkpoint_name(h @ lp["wk"].astype(cd), "attn_k")
    v = checkpoint_name(h @ lp["wv"].astype(cd), "attn_v")
    q = _rope(q.reshape(B, L, hq, hd), positions, cfg.rope_theta)
    k = _rope(k.reshape(B, L, hkv, hd), positions, cfg.rope_theta)
    v = v.reshape(B, L, hkv, hd)
    if hkv != hq:
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    o = _full_attention(q, k, v).reshape(B, L, hq * hd)
    x = x + checkpoint_name(o @ lp["wo"].astype(cd), "attn_o")

    h = _rmsnorm(x, lp["moe_norm"], cfg.norm_eps)
    flat = h.reshape(B * L, d)
    moe_p = {"router": lp["router"], "w_gate": lp["w_gate"],
             "w_in": lp["w_in"], "w_out": lp["w_out"]}
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        y = moe_ffn_sharded(moe_p, flat, mesh, top_k=cfg.top_k,
                            capacity_factor=cfg.capacity_factor)
    else:
        y = moe_ffn(moe_p, flat, top_k=cfg.top_k)
    # aux term from the same routing the FFN used (dense math — tiny)
    logits = flat @ lp["router"].astype(flat.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topk_idx, _ = _routing(moe_p, flat, cfg.top_k)
    aux = _aux_loss(probs, topk_idx, cfg.n_experts)
    # "moe_out" rides SELECTIVE_SAVE_NAMES: selective remat saves the
    # combined expert output and recomputes the dispatch in backward
    return x + checkpoint_name(y, "moe_out").reshape(B, L, d), aux


def forward(params: Params, tokens: jax.Array, cfg: MixtralConfig,
            mesh=None, return_aux: bool = False):
    """tokens [B, L] int32 → logits [B, L, vocab] fp32 (+ mean aux loss)."""
    B, L = tokens.shape
    cd = cfg.dtype
    x = params["embed"].astype(cd)[tokens]
    positions = jnp.arange(L)

    body = functools.partial(_layer, cfg=cfg, positions=positions,
                             mesh=mesh)
    if cfg.remat:
        body = jax.checkpoint(body, policy=remat_policy_fn(cfg.remat_policy))

    def step(x, lp):
        x, aux = body(lp, x)
        return x, aux

    x, aux = lax.scan(step, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("bld,vd->blv", x.astype(cd),
                        params["embed"].astype(cd),
                        preferred_element_type=jnp.float32)
    if return_aux:
        return logits, jnp.mean(aux)
    return logits


def _loss_overlap(params: Params, tokens: jax.Array, cfg: MixtralConfig,
                  mesh) -> jax.Array:
    """fsdp_overlap=True loss: full-manual (dp, fsdp) shard_map with the
    prefetch-scheduled layer scan (see llama._loss_overlap). Experts run
    the dense moe_ffn path per shard, so ep must be 1 here."""
    from ray_tpu.parallel.fsdp_overlap import (drop_leading_dim,
                                               gather_params, overlap_scan,
                                               project_specs)

    for ax in ("pp", "sp", "tp", "ep"):
        if mesh.shape.get(ax, 1) > 1:
            raise ValueError(
                f"fsdp_overlap runs full-manual over (dp, fsdp); mesh axis "
                f"{ax!r} has size {mesh.shape[ax]} > 1")
    specs = project_specs(param_specs(cfg), ("fsdp",))
    lspecs = drop_leading_dim(specs["layers"])
    cd = cfg.dtype

    def block(params, tokens):
        L = tokens.shape[1]
        positions = jnp.arange(L)
        embed = gather_params(params["embed"], specs["embed"], "fsdp")
        x = embed.astype(cd)[tokens]
        body = functools.partial(_layer, cfg=cfg, positions=positions,
                                 mesh=None)
        if cfg.remat:
            body = jax.checkpoint(body,
                                  policy=remat_policy_fn(cfg.remat_policy))
        x, aux = overlap_scan(params["layers"], lspecs, x, body,
                              cfg.n_layers, axis_name="fsdp", has_aux=True)
        x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
        logits = jnp.einsum("bld,vd->blv", x.astype(cd), embed.astype(cd),
                            preferred_element_type=jnp.float32)
        loss = _nll_mean(logits, tokens) + cfg.aux_loss_coef * jnp.mean(aux)
        # equal-size batch shards → pmean of shard means == global mean
        return lax.pmean(loss, ("dp", "fsdp"))

    fn = shard_map_compat(block, mesh=mesh,
                          in_specs=(specs, P(("dp", "fsdp"), None)),
                          out_specs=P())
    return fn(params, tokens)


def loss_fn(params: Params, tokens: jax.Array, cfg: MixtralConfig,
            mesh=None) -> jax.Array:
    """Next-token CE + aux load-balance term (Mixtral training objective).

    cfg.fsdp_overlap routes to the explicit prefetch-scheduled manual
    step whenever the mesh actually shards fsdp (same numerics)."""
    if cfg.fsdp_overlap and mesh is not None \
            and mesh.shape.get("fsdp", 1) > 1:
        return _loss_overlap(params, tokens, cfg, mesh)
    logits, aux = forward(params, tokens, cfg, mesh, return_aux=True)
    return _nll_mean(logits, tokens) + cfg.aux_loss_coef * aux


def num_params(cfg: MixtralConfig) -> int:
    d, L, E, f = cfg.dim, cfg.n_layers, cfg.n_experts, cfg.ffn_dim
    hd = cfg.head_dim
    per_layer = (d * cfg.n_heads * hd + 2 * d * cfg.n_kv_heads * hd
                 + cfg.n_heads * hd * d          # attention
                 + d * E                          # router
                 + 3 * E * d * f                  # gated SwiGLU experts
                 + 2 * d)                         # norms
    return cfg.vocab_size * d + L * per_layer + d


def active_params(cfg: MixtralConfig) -> int:
    """Params touched per token (top-k experts only) — the MoE efficiency
    headline (Mixtral: ~13B active of ~47B total)."""
    d, L, f = cfg.dim, cfg.n_layers, cfg.ffn_dim
    hd = cfg.head_dim
    per_layer = (d * cfg.n_heads * hd + 2 * d * cfg.n_kv_heads * hd
                 + cfg.n_heads * hd * d + d * cfg.n_experts
                 + 3 * cfg.top_k * d * f + 2 * d)
    return cfg.vocab_size * d + L * per_layer + d


def flops_per_token(cfg: MixtralConfig, seq_len: int) -> float:
    """6·N_active + attention score term (same convention as llama)."""
    attn = 12 * cfg.n_layers * cfg.dim * seq_len
    return 6.0 * active_params(cfg) + attn
