"""Durable workflows: DAG execution with storage-backed step memoization,
dynamic continuations, durable events, retries, and a status API.

Role-equivalent to the reference's Workflow subsystem (reference:
workflow/workflow_executor.py:32 execution loop, workflow_storage.py:
checkpoint keys, workflow/api.py: run/resume/list/cancel surface,
workflow/event_listener.py: wait_for_event). Redesigned around this
framework's DAG nodes instead of the reference's coroutine executor:

 - every DAG node is one step; a step's value is checkpointed the moment
   it completes, keyed by graph position, so re-running (or resume()) after
   a crash replays only unfinished steps;
 - a step may return ``continuation(sub_dag)`` — the sub-graph is executed
   in the parent's place with its own checkpoint namespace (the reference's
   dynamic workflows, workflow_executor.py:32 ``_deref`` recursion);
 - ``event(name)`` nodes block the workflow until ``signal()`` delivers a
   value; delivery is durable, so a crashed workflow resumes past events
   it already received (reference: event_listener.py EventListener);
 - storage is pluggable (reference: workflow_storage.py over filesystem/S3)
   — filesystem by default, cluster-KV optional.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode

_DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"

RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
RESUMABLE = "RESUMABLE"


class WorkflowError(RuntimeError):
    pass


class WorkflowCancelledError(WorkflowError):
    pass


# ---------------------------------------------------------------------------
# storage seam (reference: workflow_storage.py — put/get over opaque keys)


class WorkflowStorage:
    """Key/value durability for workflow state. Keys are
    ``<workflow_id>/<name>``; values are opaque bytes."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        return self.get(key) is not None

    def list_ids(self) -> List[str]:
        raise NotImplementedError

    def delete_workflow(self, workflow_id: str) -> None:
        raise NotImplementedError


class FilesystemStorage(WorkflowStorage):
    """Default backend: one directory per workflow, routed through the
    shared :mod:`ray_tpu.util.filesystem` seam (atomic puts, transient-
    error retries, ``storage.*`` fault points — the same durability
    contract train checkpoints and spill use). ``fs`` accepts any
    StorageFilesystem or a ``memory://name`` spec for tests."""

    def __init__(self, root: str = _DEFAULT_STORAGE, fs=None):
        from ray_tpu.util.filesystem import storage_filesystem
        self.root = root
        self.fs = storage_filesystem(fs)

    def _path(self, key: str) -> str:
        wf, _, name = key.partition("/")
        return os.path.join(self.root, wf, name)

    def put(self, key: str, data: bytes) -> None:
        self.fs.put(self._path(key), data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self.fs.get(self._path(key))
        except FileNotFoundError:
            return None

    def list_ids(self) -> List[str]:
        # a workflow exists iff its directory has at least one object
        # (object stores have no empty directories)
        return sorted(
            d for d in self.fs.list(self.root)
            if self.fs.list(os.path.join(self.root, d)))

    def delete_workflow(self, workflow_id: str) -> None:
        self.fs.delete(os.path.join(self.root, workflow_id))


class KVStorage(WorkflowStorage):
    """Cluster-KV backend: workflow state lives in the head's KV table and
    inherits its snapshot durability (head restart keeps workflows
    resumable cluster-wide without a shared filesystem)."""

    PREFIX = "__wf__/"

    @staticmethod
    def _kv():
        from ray_tpu.core.worker import require_connected
        return require_connected().backend

    def put(self, key: str, data: bytes) -> None:
        self._kv().kv_put(self.PREFIX + key, data)

    def get(self, key: str) -> Optional[bytes]:
        return self._kv().kv_get(self.PREFIX + key)

    def list_ids(self) -> List[str]:
        ids = set()
        for k in self._kv().kv_keys(self.PREFIX):
            rest = k[len(self.PREFIX):]
            ids.add(rest.partition("/")[0])
        return sorted(ids)

    def delete_workflow(self, workflow_id: str) -> None:
        kv = self._kv()
        for k in kv.kv_keys(f"{self.PREFIX}{workflow_id}/"):
            kv.kv_del(k)


def _storage_for(storage) -> WorkflowStorage:
    if storage is None:
        return FilesystemStorage()
    if isinstance(storage, WorkflowStorage):
        return storage
    if storage == "kv":
        return KVStorage()
    return FilesystemStorage(str(storage))


# ---------------------------------------------------------------------------
# user-facing step markers


class _Continuation:
    """Returned BY a step to replace itself with a sub-graph (the
    reference's dynamic workflows)."""

    __slots__ = ("dag",)

    def __init__(self, dag: DAGNode):
        self.dag = dag


def continuation(dag: DAGNode) -> _Continuation:
    if not isinstance(dag, DAGNode):
        raise TypeError("continuation() takes a DAG node (fn.bind(...))")
    return _Continuation(dag)


class _EventNode:
    """A leaf that blocks the workflow until signal() delivers a value."""

    __slots__ = ("name", "timeout_s")

    def __init__(self, name: str, timeout_s: Optional[float]):
        self.name = name
        self.timeout_s = timeout_s


def event(name: str, timeout_s: Optional[float] = None) -> _EventNode:
    """Use as a DAG argument: ``process.bind(workflow.event("approved"))``.
    The step runs once ``signal(workflow_id, "approved", value)`` fires;
    delivery is durable (reference: event_listener.py)."""
    return _EventNode(name, timeout_s)


def signal(workflow_id: str, name: str, value: Any = None,
           storage=None) -> None:
    """Deliver an event to a (possibly not yet running) workflow."""
    st = _storage_for(storage)
    st.put(f"{workflow_id}/event_{name}",
           cloudpickle.dumps(value, protocol=5))


# ---------------------------------------------------------------------------
# executor


def _step_key(node: DAGNode, path: str) -> str:
    """Stable step identity: graph position + function name (argument
    VALUES are not hashed — the graph structure is the identity, matching
    the reference's step-id-from-DAG-position)."""
    name = getattr(node._fn, "__qualname__", None) or getattr(
        getattr(node._fn, "underlying_function", None), "__qualname__",
        "fn")
    return hashlib.sha1(f"{path}:{name}".encode()).hexdigest()[:16]


class _WorkflowRun:
    def __init__(self, workflow_id: str, storage: WorkflowStorage,
                 step_timeout_s: float, max_step_retries: int):
        self.workflow_id = workflow_id
        self.storage = storage
        self.step_timeout_s = step_timeout_s
        self.max_step_retries = max_step_retries
        self.executed: Dict[int, Any] = {}
        self.steps_run = 0
        self.steps_replayed = 0

    # -- metadata --

    def _meta(self) -> dict:
        raw = self.storage.get(f"{self.workflow_id}/meta.json")
        return json.loads(raw) if raw else {}

    def set_status(self, status: str, **extra) -> None:
        meta = self._meta()
        meta.update({"status": status, "updated_at": time.time(), **extra})
        meta.setdefault("created_at", time.time())
        self.storage.put(f"{self.workflow_id}/meta.json",
                         json.dumps(meta).encode())

    def _check_cancel(self) -> None:
        if self.storage.exists(f"{self.workflow_id}/cancel"):
            raise WorkflowCancelledError(self.workflow_id)

    # -- execution --

    def run_node(self, node: Any, path: str) -> Any:
        if isinstance(node, _EventNode):
            return self._wait_event(node)
        if not isinstance(node, DAGNode):
            return node
        if id(node) in self.executed:
            return self.executed[id(node)]
        key = _step_key(node, path)
        ckpt = f"{self.workflow_id}/step_{key}"
        raw = self.storage.get(ckpt)
        if raw is not None:
            value = cloudpickle.loads(raw)
            self.steps_replayed += 1
        else:
            self._check_cancel()
            args = [self.run_node(a, f"{path}.a{i}")
                    for i, a in enumerate(node._args)]
            kwargs = {k: self.run_node(v, f"{path}.k{k}")
                      for k, v in node._kwargs.items()}
            self._check_cancel()
            value = self._run_step(node, args, kwargs)
            if isinstance(value, _Continuation):
                # dynamic sub-graph replaces this step; its steps
                # checkpoint under the parent's namespace (reference:
                # workflow_executor.py continuation deref)
                value = self.run_node(value.dag, f"{path}.c")
            self.storage.put(ckpt, cloudpickle.dumps(value, protocol=5))
            self.steps_run += 1
        self.executed[id(node)] = value
        return value

    def _run_step(self, node: DAGNode, args: list, kwargs: dict) -> Any:
        attempts = 0
        while True:
            attempts += 1
            try:
                return ray_tpu.get(node._fn.remote(*args, **kwargs),
                                   timeout=self.step_timeout_s)
            except WorkflowCancelledError:
                raise
            except Exception:  # noqa: BLE001 — step failure
                if attempts > self.max_step_retries:
                    raise
                time.sleep(min(2.0 ** attempts * 0.1, 5.0))

    def _wait_event(self, ev: _EventNode) -> Any:
        key = f"{self.workflow_id}/event_{ev.name}"
        deadline = (None if ev.timeout_s is None
                    else time.monotonic() + ev.timeout_s)
        while True:
            raw = self.storage.get(key)
            if raw is not None:
                return cloudpickle.loads(raw)
            self._check_cancel()
            if deadline is not None and time.monotonic() >= deadline:
                raise WorkflowError(
                    f"event {ev.name!r} not delivered within "
                    f"{ev.timeout_s}s")
            time.sleep(0.05)


def run(dag: DAGNode, *, workflow_id: str,
        storage=None,
        step_timeout_s: float = 24 * 3600.0,
        max_step_retries: int = 0) -> Any:
    """Execute (or resume) a workflow; returns the final value.

    Steps run as cluster tasks; each completed step's value persists
    before the next starts, so a crash loses at most the in-flight step.
    ``step_timeout_s`` bounds one step (default a day — training-scale);
    ``max_step_retries`` re-runs a FAILED step that many times before the
    whole workflow fails (resumable where it stopped).
    """
    st = _storage_for(storage)
    wf = _WorkflowRun(workflow_id, st, step_timeout_s, max_step_retries)
    # persist the graph so resume(workflow_id) works without the caller
    # re-supplying it (reference: workflow_storage save_workflow_prerequisites)
    if not st.exists(f"{workflow_id}/dag"):
        st.put(f"{workflow_id}/dag", cloudpickle.dumps(
            {"dag": dag, "step_timeout_s": step_timeout_s,
             "max_step_retries": max_step_retries}, protocol=5))
    wf.set_status(RUNNING)
    try:
        result = wf.run_node(dag, "root")
    except WorkflowCancelledError:
        wf.set_status(CANCELLED)
        raise
    except BaseException as e:
        wf.set_status(RESUMABLE, error=repr(e))
        raise
    wf.set_status(COMPLETED)
    run.last_stats = {"steps_run": wf.steps_run,
                      "steps_replayed": wf.steps_replayed}
    return result


run.last_stats = {}


def run_async(dag: DAGNode, *, workflow_id: str, storage=None,
              **opts):
    """Run the workflow driver itself as a cluster task; returns an
    ObjectRef of the final value (reference: api.run's async path)."""
    blob = cloudpickle.dumps(
        {"dag": dag, "workflow_id": workflow_id,
         "storage_root": getattr(_storage_for(storage), "root", None),
         "opts": opts}, protocol=5)

    @ray_tpu.remote
    def _workflow_driver(payload: bytes):
        spec = cloudpickle.loads(payload)
        st = (FilesystemStorage(spec["storage_root"])
              if spec["storage_root"] else KVStorage())
        return run(spec["dag"], workflow_id=spec["workflow_id"],
                   storage=st, **spec["opts"])

    return _workflow_driver.remote(blob)


def resume(workflow_id: str, storage=None) -> Any:
    """Re-run a stored workflow: completed steps replay from checkpoints,
    the rest execute (reference: api.resume)."""
    st = _storage_for(storage)
    raw = st.get(f"{workflow_id}/dag")
    if raw is None:
        raise WorkflowError(f"no stored workflow {workflow_id!r}")
    spec = cloudpickle.loads(raw)
    return run(spec["dag"], workflow_id=workflow_id, storage=st,
               step_timeout_s=spec.get("step_timeout_s", 24 * 3600.0),
               max_step_retries=spec.get("max_step_retries", 0))


def cancel(workflow_id: str, storage=None) -> None:
    """Request cancellation: the run stops before its next step
    (reference: api.cancel — in-flight steps are not interrupted)."""
    _storage_for(storage).put(f"{workflow_id}/cancel", b"1")


def get_status(workflow_id: str, storage=None) -> Optional[str]:
    raw = _storage_for(storage).get(f"{workflow_id}/meta.json")
    return json.loads(raw).get("status") if raw else None


def list_all(storage=None) -> List[dict]:
    """[{workflow_id, status, created_at, updated_at}] for every stored
    workflow (reference: api.list_all)."""
    st = _storage_for(storage)
    out = []
    for wf in st.list_ids():
        raw = st.get(f"{wf}/meta.json")
        meta = json.loads(raw) if raw else {}
        out.append({"workflow_id": wf,
                    "status": meta.get("status"),
                    "created_at": meta.get("created_at"),
                    "updated_at": meta.get("updated_at")})
    return out


def delete(workflow_id: str, storage: Optional[str] = None) -> None:
    _storage_for(storage).delete_workflow(workflow_id)
