"""Durable workflows: DAG execution with storage-backed step memoization.

Role-equivalent to the reference's Workflow (reference:
workflow/workflow_executor.py:32 + workflow_storage.py): each DAG node is
one step; a step's result is checkpointed to storage the moment it
completes, keyed by its position in the graph, so re-running the same
workflow_id after a crash replays only the steps that never finished
(reference recovery semantics; deterministic steps assumed).
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag import DAGNode

_DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"


def _step_key(node: DAGNode, path: str) -> str:
    """Stable step identity: graph position + function name (argument
    VALUES are not hashed — the graph structure is the identity, matching
    the reference's step-id-from-DAG-position)."""
    name = getattr(node._fn, "__qualname__", None) or getattr(
        getattr(node._fn, "underlying_function", None), "__qualname__",
        "fn")
    return hashlib.sha1(f"{path}:{name}".encode()).hexdigest()[:16]


class _WorkflowRun:
    def __init__(self, workflow_id: str, storage: str,
                 step_timeout_s: float):
        self.dir = os.path.join(storage, workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.step_timeout_s = step_timeout_s
        self.executed: Dict[int, Any] = {}
        self.steps_run = 0
        self.steps_replayed = 0

    def _ckpt_path(self, key: str) -> str:
        return os.path.join(self.dir, f"step_{key}.pkl")

    def run_node(self, node: Any, path: str) -> Any:
        if not isinstance(node, DAGNode):
            return node
        if id(node) in self.executed:
            return self.executed[id(node)]
        key = _step_key(node, path)
        ckpt = self._ckpt_path(key)
        if os.path.exists(ckpt):
            with open(ckpt, "rb") as f:
                value = cloudpickle.load(f)
            self.steps_replayed += 1
            self.executed[id(node)] = value
            return value
        args = [self.run_node(a, f"{path}.a{i}")
                for i, a in enumerate(node._args)]
        kwargs = {k: self.run_node(v, f"{path}.k{k}")
                  for k, v in node._kwargs.items()}
        value = ray_tpu.get(node._fn.remote(*args, **kwargs),
                            timeout=self.step_timeout_s)
        tmp = ckpt + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, ckpt)
        self.steps_run += 1
        self.executed[id(node)] = value
        return value


def run(dag: DAGNode, *, workflow_id: str,
        storage: Optional[str] = None,
        step_timeout_s: float = 24 * 3600.0) -> Any:
    """Execute (or resume) a workflow; returns the final value.

    Steps run as cluster tasks; each completed step's value persists
    before the next starts, so a crash loses at most the in-flight step.
    ``step_timeout_s`` bounds one step (default a day — training-scale).
    """
    wf = _WorkflowRun(workflow_id, storage or _DEFAULT_STORAGE,
                      step_timeout_s)
    result = wf.run_node(dag, "root")
    run.last_stats = {"steps_run": wf.steps_run,
                      "steps_replayed": wf.steps_replayed}
    return result


run.last_stats = {}


def delete(workflow_id: str, storage: Optional[str] = None) -> None:
    import shutil
    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id)
    shutil.rmtree(path, ignore_errors=True)
