"""@ray_tpu.remote for functions.

Role-equivalent to the reference's RemoteFunction
(reference: python/ray/remote_function.py:303 `_remote`): wraps a function,
carries default options (num_returns/resources/retries/scheduling strategy),
`f.remote(...)` builds a TaskSpec and submits through the worker;
`.options(...)` returns a shallow override wrapper.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.core.worker import require_connected

_VALID_OPTIONS = {
    "num_returns", "num_cpus", "num_tpus", "num_gpus", "resources",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "memory", "_metadata",
}


def _build_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    resources: Dict[str, float] = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        resources["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus") is not None:
        resources["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus") is not None:
        resources["GPU"] = float(opts["num_gpus"])
    if opts.get("memory") is not None:
        resources["memory"] = float(opts["memory"])
    return resources


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_OPTIONS:
                raise ValueError(f"invalid option {k!r} for @remote")
        # fail-fast on unsupported/malformed envs at decoration time —
        # never silently dropped (reference: runtime_env plugin validation)
        from ray_tpu.runtime import runtime_env as rtenv
        self._options["runtime_env"] = rtenv.validate(
            self._options.get("runtime_env"))
        functools.update_wrapper(self, function)
        self._exported_key: Optional[bytes] = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            "directly — use .remote()")

    def options(self, **opts) -> "RemoteFunction":
        merged = {**self._options, **opts}
        return RemoteFunction(self._function, merged)

    def remote(self, *args, **kwargs):
        worker = require_connected()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        spec = TaskSpec(
            task_id=worker.next_task_id(),
            name=opts.get("name") or self._function.__qualname__,
            function=self._function,
            args=worker.make_task_args(args),
            kwargs=dict(kwargs),
            num_returns=0 if streaming else num_returns,
            streaming=streaming,
            resources=_build_resources(opts) or {"CPU": 1.0},
            max_retries=opts.get("max_retries", 3),
            retry_exceptions=bool(opts.get("retry_exceptions", False)),
            scheduling_strategy=opts.get("scheduling_strategy"),
            runtime_env=opts.get("runtime_env"),
        )
        pg = opts.get("placement_group")
        if pg is not None:
            spec.placement_group_id = pg.id.binary()
            spec.placement_bundle_index = opts.get(
                "placement_group_bundle_index", -1)
        refs = worker.submit_task(spec)
        if streaming:
            return refs  # an ObjectRefGenerator
        if num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray.dag dag_node.py:32) — builds the
        graph without executing; see ray_tpu.dag."""
        from ray_tpu.dag import DAGNode
        return DAGNode(self, args, kwargs)

    @property
    def underlying_function(self):
        return self._function


def remote_decorator(*args, **kwargs):
    """Implements @remote and @remote(**options) for functions and classes."""
    from ray_tpu.actor import ActorClass
    import inspect

    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target, {})
        return RemoteFunction(target, {})

    if args:
        raise TypeError("@remote takes keyword options only")

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    return wrap
