"""Lazy task DAGs: fn.bind(...) -> DAGNode -> execute().

Role-equivalent to the reference's Ray DAG layer (reference:
dag/dag_node.py:32 DAGNode, function_node.py / input_node.py): binding
builds the graph without executing; execute() walks it bottom-up, submits
each node ONCE as a task (diamond dependencies deduplicate), and wires
parent results in as ObjectRefs so the data plane moves values directly
between workers. The compiled-graph variant (experimental_compile) is the
reference's aDAG; here the XLA-compiled analog of a static compute graph
is a jitted program, so only the orchestration DAG is reproduced.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DAGNode:
    """One bound task invocation in a lazy graph."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def execute(self, _cache: Optional[Dict[int, Any]] = None):
        """Submit the whole graph; returns this node's ObjectRef."""
        cache: Dict[int, Any] = _cache if _cache is not None else {}
        return self._submit(cache)

    def _submit(self, cache: Dict[int, Any]):
        if id(self) in cache:
            return cache[id(self)]

        def resolve(v):
            if isinstance(v, DAGNode):
                return v._submit(cache)
            if isinstance(v, InputNode):
                return v._value()
            return v

        args = tuple(resolve(a) for a in self._args)
        kwargs = {k: resolve(v) for k, v in self._kwargs.items()}
        ref = self._fn.remote(*args, **kwargs)
        cache[id(self)] = ref
        return ref

    def __repr__(self):
        return f"DAGNode({getattr(self._fn, '__name__', 'fn')})"


class InputNode:
    """Placeholder for execute-time input (reference: dag/input_node.py).

    Usage:
        with InputNode() as inp:
            dag = f.bind(inp)
        ray_tpu.dag.execute_with_input(dag, 5)
    """

    def __init__(self):
        self._bound_value: Any = _UNSET

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _value(self):
        if self._bound_value is _UNSET:
            raise ValueError("InputNode used but no input supplied — "
                             "call execute_with_input(value)")
        return self._bound_value


_UNSET = object()


def execute_with_input(dag: DAGNode, value: Any):
    """Execute a DAG that contains InputNode placeholders."""
    inputs = _find_inputs(dag)
    for node in inputs:
        node._bound_value = value
    try:
        return dag.execute()
    finally:
        for node in inputs:
            node._bound_value = _UNSET


def _find_inputs(node: DAGNode) -> List[InputNode]:
    out: List[InputNode] = []
    seen: set = set()

    def walk(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, InputNode):
            if n not in out:
                out.append(n)
            return
        if isinstance(n, DAGNode):
            for v in list(n._args) + list(n._kwargs.values()):
                walk(v)
    walk(node)
    return out
