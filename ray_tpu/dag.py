"""Lazy task DAGs: fn.bind(...) -> DAGNode -> execute().

Role-equivalent to the reference's Ray DAG layer (reference:
dag/dag_node.py:32 DAGNode, function_node.py / input_node.py): binding
builds the graph without executing; execute() walks it bottom-up, submits
each node ONCE as a task (diamond dependencies deduplicate), and wires
parent results in as ObjectRefs so the data plane moves values directly
between workers.

``experimental_compile`` is the TPU answer to the reference's compiled
graphs (aDAG — dag/compiled_dag_node.py:767 + mutable-plasma/NCCL
channels): where the reference pre-allocates actor loops and moves
intermediates through zero-copy GPU channels, here the whole DAG of pure
stage functions FUSES into one jitted XLA program — intermediates never
leave HBM, stage boundaries cost nothing (XLA fuses across them), and
repeat executions skip Python orchestration entirely. The channel
machinery isn't reproduced because the compiler subsumes it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DAGNode:
    """One bound task invocation in a lazy graph."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def execute(self, _cache: Optional[Dict[int, Any]] = None):
        """Submit the whole graph; returns this node's ObjectRef."""
        cache: Dict[int, Any] = _cache if _cache is not None else {}
        return self._submit(cache)

    def _submit(self, cache: Dict[int, Any]):
        if id(self) in cache:
            return cache[id(self)]

        def resolve(v):
            if isinstance(v, DAGNode):
                return v._submit(cache)
            if isinstance(v, InputNode):
                return v._value()
            return v

        args = tuple(resolve(a) for a in self._args)
        kwargs = {k: resolve(v) for k, v in self._kwargs.items()}
        ref = self._fn.remote(*args, **kwargs)
        cache[id(self)] = ref
        return ref

    def __repr__(self):
        return f"DAGNode({getattr(self._fn, '__name__', 'fn')})"


class InputNode:
    """Placeholder for execute-time input (reference: dag/input_node.py).

    Usage:
        with InputNode() as inp:
            dag = f.bind(inp)
        ray_tpu.dag.execute_with_input(dag, 5)
    """

    def __init__(self):
        self._bound_value: Any = _UNSET

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _value(self):
        if self._bound_value is _UNSET:
            raise ValueError("InputNode used but no input supplied — "
                             "call execute_with_input(value)")
        return self._bound_value


_UNSET = object()


def execute_with_input(dag: DAGNode, value: Any):
    """Execute a DAG that contains InputNode placeholders."""
    inputs = _find_inputs(dag)
    for node in inputs:
        node._bound_value = value
    try:
        return dag.execute()
    finally:
        for node in inputs:
            node._bound_value = _UNSET


class CompiledDAG:
    """One jitted program standing in for the whole bound graph
    (reference: CompiledDAG — execute() without per-node task overhead).
    ``execute(x)`` runs on-device; intermediates stay in HBM."""

    def __init__(self, dag: DAGNode):
        import jax
        order, inputs = _topo(dag)
        if not inputs:
            raise ValueError("experimental_compile needs an InputNode "
                             "driving the graph")

        def run(x):
            values: Dict[int, Any] = {id(n): x for n in inputs}

            def resolve(v):
                if isinstance(v, (DAGNode, InputNode)):
                    return values[id(v)]
                return v
            out = None
            for node in order:
                args = tuple(resolve(a) for a in node._args)
                kwargs = {k: resolve(v)
                          for k, v in node._kwargs.items()}
                out = node._fn.underlying_function(*args, **kwargs)
                values[id(node)] = out
            return out

        self._compiled = jax.jit(run)

    def execute(self, x):
        """Run the fused program; returns the final node's value (a
        device array / pytree, not an ObjectRef — there is no task)."""
        return self._compiled(x)


class ActorMethodNode(DAGNode):
    """A bound ACTOR method call in a lazy graph (reference:
    dag/class_node.py ClassMethodNode). Created via
    ``actor_handle.method.bind(...)``."""

    def __init__(self, handle, method_name: str, args: tuple, kwargs: dict):
        self._handle = handle
        self._method_name = method_name
        self._args = args
        self._kwargs = kwargs

    def _submit(self, cache: Dict[int, Any]):
        if id(self) in cache:
            return cache[id(self)]

        def resolve(v):
            if isinstance(v, DAGNode):
                return v._submit(cache)
            if isinstance(v, InputNode):
                return v._value()
            return v

        args = tuple(resolve(a) for a in self._args)
        kwargs = {k: resolve(v) for k, v in self._kwargs.items()}
        ref = getattr(self._handle, self._method_name).remote(*args, **kwargs)
        cache[id(self)] = ref
        return ref

    def __repr__(self):
        return f"ActorMethodNode({self._method_name})"


class DagRef:
    """Result handle for one CompiledActorDAG execution. Results arrive
    on the output channel in submission order; get() drains the channel
    up to this execution's slot."""

    def __init__(self, owner: "CompiledActorDAG", seq: int):
        self._owner = owner
        self._seq = seq

    def get(self, timeout: Optional[float] = 60.0):
        return self._owner._result(self._seq, timeout)


class CompiledActorDAG:
    """Pre-launched per-actor execution loops wired by shm channel rings
    (reference: dag/compiled_dag_node.py:767 — do_exec_tasks at :188 +
    experimental/channel/): compile() starts a long-lived loop on every
    participating actor that reads its input ring, runs the bound method,
    and writes its output ring. execute(x) writes the input ring and
    returns a DagRef — no per-call task submission, scheduling, or RPC;
    ring capacity gives pipelining across executions.

    Constraints (v1, mirrors the reference's aDAG restrictions): the
    graph must be a linear chain InputNode -> a.m -> b.m -> ...; all
    actors must live on the driver's node (channels ride the node's shm
    arena — the cross-node extension is a channel proxied over the
    object plane); while compiled, eager calls to the same actors race
    the loop thread against the task queue.
    """

    def __init__(self, dag: ActorMethodNode, capacity: int = 8,
                 start_timeout: float = 60.0):
        import os

        from ray_tpu.core.worker import require_connected
        from ray_tpu.runtime.channel import ShmChannel
        from ray_tpu.runtime.protocol import RpcError

        chain = _linear_actor_chain(dag)
        worker = require_connected()
        backend = worker.backend
        store = backend.object_plane.store
        base = os.urandom(6).hex()
        names = [f"{base}-{i}" for i in range(len(chain) + 1)]
        self._backend = backend
        self._names = names
        self._store = store
        self._capacity = capacity
        self._in = ShmChannel(store, names[0], capacity)
        self._out = ShmChannel(store, names[-1], capacity)
        self._next_seq = 0
        self._done_seq = -1
        self._results: Dict[int, Any] = {}
        self._torn_down = False
        import time as _time
        for i, (handle, method) in enumerate(chain):
            deadline = _time.monotonic() + start_timeout
            addr = None
            while _time.monotonic() < deadline:
                info = backend.head.call_retrying(
                    "get_actor", {"actor_id": handle._actor_id.binary()})
                if info is None:
                    raise ValueError(f"actor {handle!r} is not registered")
                if info["state"] == "ALIVE":
                    addr = info["address"]
                    break
                if info["state"] == "DEAD":
                    raise ValueError(f"actor {handle!r} is dead: "
                                     f"{info.get('reason')}")
                _time.sleep(0.05)
            if addr is None:
                raise TimeoutError(f"actor {handle!r} never became ALIVE")
            try:
                actor_node = backend.peers.get(addr).call(
                    "dag_start_loop", {
                        "in": names[i], "out": names[i + 1],
                        "method": method, "capacity": capacity}, timeout=30)
            except RpcError as e:
                raise RuntimeError(
                    f"failed to start dag loop on {handle!r}: {e}") from e
            # channels ride the node's shm arena: a cross-node actor would
            # attach a DIFFERENT store and the pipeline would hang — fail
            # loudly at compile time instead
            if actor_node != backend.local_node_id:
                self.teardown()
                raise ValueError(
                    f"compiled actor DAGs require every actor on the "
                    f"driver's node: {handle!r} is on node "
                    f"{str(actor_node)[:12]}, driver on "
                    f"{str(backend.local_node_id)[:12]}")

    def execute(self, x) -> DagRef:
        if self._torn_down:
            raise RuntimeError("compiled dag was torn down")
        # Sliding window: when every ring is full, the single-threaded
        # driver must CONSUME a finished result to free a slot — blocking
        # in put would deadlock the pipeline against itself.
        while not self._in.try_put(("v", x)):
            if self._done_seq + 1 < self._next_seq:
                self._results[self._done_seq + 1] = self._out.get(60.0)
                self._done_seq += 1
            else:  # nothing in flight: the ring is jammed, not full
                self._in.put(("v", x), timeout=60.0)
                break
        ref = DagRef(self, self._next_seq)
        self._next_seq += 1
        return ref

    def _result(self, seq: int, timeout: Optional[float]):
        if seq in self._results:
            tag, val = self._results.pop(seq)
        elif self._done_seq >= seq:
            raise ValueError(f"DagRef #{seq} was already consumed")
        else:
            while self._done_seq < seq:
                tag_val = self._out.get(timeout)
                self._done_seq += 1
                if self._done_seq == seq:
                    tag, val = tag_val
                    break
                self._results[self._done_seq] = tag_val
        if tag == "e":
            raise val
        return val

    def teardown(self) -> None:
        """Stop the actor loops (sentinel cascades down the chain) and
        free the channel slots."""
        if self._torn_down:
            return
        self._torn_down = True
        from ray_tpu.runtime.channel import ChannelClosed, ShmChannel
        # the input ring may be full of unconsumed work: unjam by draining
        # outputs until the sentinel fits, or the loop threads never stop
        for _ in range(64):
            if self._in.close(timeout=1.0):
                break
            try:
                self._out.get(timeout=5.0)
            except (ChannelClosed, TimeoutError):
                break
        try:
            # drain until the sentinel falls out of the last channel
            while True:
                self._out.get(timeout=10.0)
        except (ChannelClosed, TimeoutError):
            pass
        for name in self._names:
            ShmChannel(self._store, name, self._capacity).drain()


def experimental_compile(dag: DAGNode, **opts):
    """Compile a bound graph for repeated execution.

    - Pure-function DAGs fuse into ONE XLA program (CompiledDAG):
      intermediates never leave HBM; stage boundaries cost nothing.
    - Actor-method chains compile into pre-launched per-actor loops fed
      by shm channel rings (CompiledActorDAG) — the multi-process
      pipeline the reference calls aDAG.
    """
    if isinstance(dag, ActorMethodNode):
        return CompiledActorDAG(dag, **opts)
    return CompiledDAG(dag)


def _linear_actor_chain(root: ActorMethodNode):
    """Validate + extract the chain [(handle, method), ...] root-last."""
    chain = []
    node: Any = root
    while isinstance(node, ActorMethodNode):
        deps = [a for a in list(node._args) + list(node._kwargs.values())
                if isinstance(a, (DAGNode, InputNode))]
        if len(deps) != 1:
            raise ValueError(
                "CompiledActorDAG v1 supports linear chains: each actor "
                f"node needs exactly one upstream, got {len(deps)}")
        chain.append((node._handle, node._method_name))
        node = deps[0]
    if not isinstance(node, InputNode):
        raise ValueError("the chain must start at an InputNode")
    chain.reverse()
    return chain


def _topo(root: DAGNode):
    """(topological node order, input nodes) for the graph under root."""
    order: List[DAGNode] = []
    inputs: List[InputNode] = []
    seen: set = set()

    def walk(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, InputNode):
            inputs.append(n)
            return
        if isinstance(n, DAGNode):
            for v in list(n._args) + list(n._kwargs.values()):
                walk(v)
            order.append(n)   # parents first (post-order)
    walk(root)
    return order, inputs


def _find_inputs(node: DAGNode) -> List[InputNode]:
    return _topo(node)[1]
