"""Lazy task DAGs: fn.bind(...) -> DAGNode -> execute().

Role-equivalent to the reference's Ray DAG layer (reference:
dag/dag_node.py:32 DAGNode, function_node.py / input_node.py): binding
builds the graph without executing; execute() walks it bottom-up, submits
each node ONCE as a task (diamond dependencies deduplicate), and wires
parent results in as ObjectRefs so the data plane moves values directly
between workers.

``experimental_compile`` is the TPU answer to the reference's compiled
graphs (aDAG — dag/compiled_dag_node.py:767 + mutable-plasma/NCCL
channels): where the reference pre-allocates actor loops and moves
intermediates through zero-copy GPU channels, here the whole DAG of pure
stage functions FUSES into one jitted XLA program — intermediates never
leave HBM, stage boundaries cost nothing (XLA fuses across them), and
repeat executions skip Python orchestration entirely. The channel
machinery isn't reproduced because the compiler subsumes it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class DAGNode:
    """One bound task invocation in a lazy graph."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs

    def execute(self, _cache: Optional[Dict[int, Any]] = None):
        """Submit the whole graph; returns this node's ObjectRef."""
        cache: Dict[int, Any] = _cache if _cache is not None else {}
        return self._submit(cache)

    def _submit(self, cache: Dict[int, Any]):
        if id(self) in cache:
            return cache[id(self)]

        def resolve(v):
            if isinstance(v, DAGNode):
                return v._submit(cache)
            if isinstance(v, InputNode):
                return v._value()
            return v

        args = tuple(resolve(a) for a in self._args)
        kwargs = {k: resolve(v) for k, v in self._kwargs.items()}
        ref = self._fn.remote(*args, **kwargs)
        cache[id(self)] = ref
        return ref

    def __repr__(self):
        return f"DAGNode({getattr(self._fn, '__name__', 'fn')})"


class InputNode:
    """Placeholder for execute-time input (reference: dag/input_node.py).

    Usage:
        with InputNode() as inp:
            dag = f.bind(inp)
        ray_tpu.dag.execute_with_input(dag, 5)
    """

    def __init__(self):
        self._bound_value: Any = _UNSET

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _value(self):
        if self._bound_value is _UNSET:
            raise ValueError("InputNode used but no input supplied — "
                             "call execute_with_input(value)")
        return self._bound_value


_UNSET = object()


def execute_with_input(dag: DAGNode, value: Any):
    """Execute a DAG that contains InputNode placeholders."""
    inputs = _find_inputs(dag)
    for node in inputs:
        node._bound_value = value
    try:
        return dag.execute()
    finally:
        for node in inputs:
            node._bound_value = _UNSET


class CompiledDAG:
    """One jitted program standing in for the whole bound graph
    (reference: CompiledDAG — execute() without per-node task overhead).
    ``execute(x)`` runs on-device; intermediates stay in HBM."""

    def __init__(self, dag: DAGNode):
        import jax
        order, inputs = _topo(dag)
        if not inputs:
            raise ValueError("experimental_compile needs an InputNode "
                             "driving the graph")

        def run(x):
            values: Dict[int, Any] = {id(n): x for n in inputs}

            def resolve(v):
                if isinstance(v, (DAGNode, InputNode)):
                    return values[id(v)]
                return v
            out = None
            for node in order:
                args = tuple(resolve(a) for a in node._args)
                kwargs = {k: resolve(v)
                          for k, v in node._kwargs.items()}
                out = node._fn.underlying_function(*args, **kwargs)
                values[id(node)] = out
            return out

        self._compiled = jax.jit(run)

    def execute(self, x):
        """Run the fused program; returns the final node's value (a
        device array / pytree, not an ObjectRef — there is no task)."""
        return self._compiled(x)


def experimental_compile(dag: DAGNode) -> CompiledDAG:
    """Fuse a DAG of PURE, jax-traceable stage functions into a single
    XLA program. Stages with side effects, actor state, or non-jax
    Python control flow must stay on the task path (``execute()``)."""
    return CompiledDAG(dag)


def _topo(root: DAGNode):
    """(topological node order, input nodes) for the graph under root."""
    order: List[DAGNode] = []
    inputs: List[InputNode] = []
    seen: set = set()

    def walk(n):
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, InputNode):
            inputs.append(n)
            return
        if isinstance(n, DAGNode):
            for v in list(n._args) + list(n._kwargs.values()):
                walk(v)
            order.append(n)   # parents first (post-order)
    walk(root)
    return order, inputs


def _find_inputs(node: DAGNode) -> List[InputNode]:
    return _topo(node)[1]
