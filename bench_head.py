"""Head (GCS) scale-ceiling microbench.

The cluster control plane is deliberately single-head (a TPU pod has a
bounded host count — SURVEY §2.1's syncer row is answered with central
accounting instead of P2P gossip). That design has a ceiling; this bench
MEASURES it instead of leaving it unknown (round-2 verdict, Weak #4):

  - node registration rate (how fast a pod's hosts can join),
  - health-heartbeat capacity (pings/s the head absorbs),
  - KV read/write throughput (function export + discovery path),
  - lease grant/release cycle rate over registered fake nodes,

all against a real Head process over real sockets, from T client
threads. Prints one JSON line per metric; numbers land in COVERAGE.md's
syncer row so the ceiling is a documented fact, not a guess.
"""

import json
import os
import threading
import time

from ray_tpu.runtime.head import Head
from ray_tpu.runtime.protocol import RpcClient, RpcServer


def fake_node_server() -> RpcServer:
    """A node daemon stand-in that answers the head's lease RPCs
    instantly, so the lease metric isolates HEAD-side cost."""
    counter = [0]

    def lease_worker(p, ctx):
        counter[0] += 1
        return {"worker_id": counter[0].to_bytes(8, "little"),
                "worker_addr": "127.0.0.1:1"}

    return RpcServer({
        "lease_worker": lease_worker,
        "return_worker": lambda p, c: True,
        "ping": lambda p, c: "pong",
    }, max_workers=2, name="fake-node")


def timed(fn, n_threads: int, seconds: float = 2.0) -> float:
    """Run fn(thread_idx, iter_idx) from n_threads for ~seconds; return
    aggregate calls/s."""
    stop = time.monotonic() + seconds
    counts = [0] * n_threads

    def loop(t):
        i = 0
        while time.monotonic() < stop:
            fn(t, i)
            i += 1
        counts[t] = i

    threads = [threading.Thread(target=loop, args=(t,))
               for t in range(n_threads)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    dt = time.monotonic() - t0
    return sum(counts) / dt


def profiled(label: str, out: list, fn, n_threads: int) -> float:
    """timed() with a concurrent in-process burst capture: Head() lives
    in THIS process, so the burst's hot frames ARE the head policy's —
    the frame-level evidence behind the measured ceiling (stack_profiler
    burst mode; same data 'profile --record' returns cluster-wide)."""
    from ray_tpu.util.stack_profiler import burst_capture, top_frames
    cap: dict = {}

    def _capture():
        cap["export"] = burst_capture(1.5, hz=199.0)

    th = threading.Thread(target=_capture, name=f"profile-{label}")
    th.start()
    rate = timed(fn, n_threads)
    th.join(timeout=10.0)
    e = cap.get("export") or {}
    samples = int(e.get("samples") or 0)
    out.append({"metric": f"head_profile_{label}",
                "samples": samples,
                "top_frames": [
                    {"frame": r["frame"], "self": r["self"],
                     "self_pct": round(100.0 * r["self"] / max(1, samples),
                                       1)}
                    for r in top_frames(e.get("stacks") or {}, 5)]})
    return rate


def main() -> None:
    head = Head()
    addr = head.address
    T = min(8, (os.cpu_count() or 2) * 4)
    clients = [RpcClient(addr, name=f"bench-{t}") for t in range(T)]

    out = []

    # --- heartbeat/ping capacity (before table bloat)
    rate = timed(lambda t, i: clients[t].call("ping"), T)
    out.append({"metric": "head_pings_per_s", "value": round(rate, 1),
                "note": f"{T} concurrent clients; health checks cost one "
                        f"of these per node per period"})

    # --- KV write+read (function export / discovery path)
    def kv_cycle(t, i):
        clients[t].call("kv_put", {"key": f"b:{t}:{i % 64}",
                                   "value": b"x" * 256})
        clients[t].call("kv_get", {"key": f"b:{t}:{i % 64}"})
    rate = profiled("kv_cycle", out, kv_cycle, T)
    out.append({"metric": "head_kv_write_read_cycles_per_s",
                "value": round(rate, 1),
                "note": "256B values; one cycle = put + get (pickle RPC "
                        "path through the Python handlers)"})

    # --- KV via the native fast path (served inside the head's C event
    # loop; no Python, no pickle on the head — how ClusterBackend clients
    # actually talk to a native head)
    if hasattr(clients[0], "call_fast"):
        from ray_tpu.runtime import protocol_native as pn

        def kv_fast_cycle(t, i):
            key = f"f:{t}:{i % 64}".encode()
            clients[t].call_fast(pn.FAST_PUT, key, b"x" * 256, flags=1)
            clients[t].call_fast(pn.FAST_GET, key)
        rate = timed(kv_fast_cycle, T)
        out.append({"metric": "head_kv_fast_write_read_cycles_per_s",
                    "value": round(rate, 1),
                    "note": "same cycle through the C-loop fast path"})

    # --- node registration: M nodes backed by a handful of live fake
    # servers (addresses must answer the health loop + lease RPCs)
    M = 200
    servers = [fake_node_server() for _ in range(8)]
    t0 = time.monotonic()
    for i in range(M):
        clients[i % T].call("register_node", {
            "node_id": f"fake-{i:04d}",
            "address": servers[i % len(servers)].address,
            "shm_name": f"/fake_{i}", "resources": {"CPU": 8.0}})
    reg_rate = M / (time.monotonic() - t0)
    out.append({"metric": "head_node_registrations_per_s",
                "value": round(reg_rate, 1),
                "note": f"{M} node registrations, {T} client conns"})

    # --- lease grant/release across the registered node table
    def lease_cycle(t, i):
        r = clients[t].call("request_lease", {
            "resources": {"CPU": 1.0}, "requester": f"bench-{t}"})
        if r and r.get("lease_id"):
            clients[t].call("release_lease", {"lease_id": r["lease_id"]})
    rate = profiled("lease_cycle", out, lease_cycle, T)
    out.append({"metric": "head_lease_cycles_per_s",
                "value": round(rate, 1),
                "note": f"grant+release cycles over a {M}-node table "
                        "(scheduler + accounting + node lease RPC to a "
                        "stub server on every cycle)"})

    for line in out:
        print(json.dumps(line))
    for c in clients:
        c.close()
    for srv in servers:
        srv.stop()
    head.stop()


if __name__ == "__main__":
    main()
