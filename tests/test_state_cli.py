"""State API + CLI tests (reference coverage model:
python/ray/tests/test_state_api.py + CLI smoke in test_cli.py)."""

import subprocess
import sys
import uuid

import pytest

import ray_tpu as rt
from ray_tpu.util import state


@pytest.fixture(scope="module")
def state_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024})
    yield rt
    rt.shutdown()


def _cli(*args, address):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args, "--address", address],
        capture_output=True, text=True, timeout=60,
        env={**__import__("os").environ,
             "PYTHONPATH": __import__("os").path.dirname(
                 __import__("os").path.dirname(rt.__file__))})


def test_state_api_lists(state_rt):
    @rt.remote
    class Marker:
        def ping(self):
            return "pong"

    name = f"m-{uuid.uuid4().hex[:6]}"
    a = Marker.options(name=name).remote()
    rt.get(a.ping.remote(), timeout=60)

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    actors = state.list_actors(state="ALIVE")
    assert any(x["name"].endswith(name) for x in actors)
    s = state.summarize()
    assert s["nodes_alive"] == 1 and s["actors_alive"] >= 1


def test_cli_status_and_list(state_rt):
    from ray_tpu.core.worker import global_worker
    address = global_worker.backend.head_addr

    out = _cli("status", address=address)
    assert out.returncode == 0, out.stderr
    assert "nodes alive" in out.stdout and "CPU" in out.stdout

    out = _cli("list", "nodes", address=address)
    assert out.returncode == 0, out.stderr
    assert "node_id=" in out.stdout

    out = _cli("list", "actors", "--format", "json", address=address)
    assert out.returncode == 0, out.stderr
    import json
    rows = json.loads(out.stdout)
    assert isinstance(rows, list)

    out = _cli("list", "objects", address=address)
    assert out.returncode == 0, out.stderr
    assert "capacity=" in out.stdout
