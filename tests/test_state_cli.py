"""State API + CLI tests (reference coverage model:
python/ray/tests/test_state_api.py + CLI smoke in test_cli.py)."""

import json
import subprocess
import sys
import uuid

import pytest

import ray_tpu as rt
from ray_tpu.util import state


@pytest.fixture(scope="module")
def state_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024})
    yield rt
    rt.shutdown()


def _cli(*args, address):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu", *args, "--address", address],
        capture_output=True, text=True, timeout=60,
        env={**__import__("os").environ,
             "PYTHONPATH": __import__("os").path.dirname(
                 __import__("os").path.dirname(rt.__file__))})


def test_state_api_lists(state_rt):
    @rt.remote
    class Marker:
        def ping(self):
            return "pong"

    name = f"m-{uuid.uuid4().hex[:6]}"
    a = Marker.options(name=name).remote()
    rt.get(a.ping.remote(), timeout=60)

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    actors = state.list_actors(state="ALIVE")
    assert any(x["name"].endswith(name) for x in actors)
    s = state.summarize()
    assert s["nodes_alive"] == 1 and s["actors_alive"] >= 1


def test_hist_quantile_and_top_llm_line():
    """`top` derives TTFT/TPOT quantiles from the aggregated serving
    histograms (bucket upper bounds) and MEANS the SLO-attainment gauges
    across workers instead of summing fractions."""
    from ray_tpu.scripts import cli

    metrics = {
        "llm_ttft_seconds": {
            "type": "histogram", "boundaries": (0.01, 0.05, 0.1),
            "values": {
                "a": {"counts": [6, 2, 1, 1], "sum": 0.3, "n": 10},
                "b": {"counts": [4, 1, 0, 0], "sum": 0.05, "n": 5}}},
    }
    # counts sum ACROSS tag values: totals [10, 3, 1, +Inf 1], n=15
    assert cli._hist_quantile(metrics, "llm_ttft_seconds", 0.5) == 0.01
    # p99 lands in +Inf: report the largest finite bound
    assert cli._hist_quantile(metrics, "llm_ttft_seconds", 0.99) == 0.1
    assert cli._hist_quantile(metrics, "absent", 0.5) is None
    assert cli._hist_quantile(
        {"llm_ttft_seconds": {"type": "histogram", "boundaries": (1.0,),
                              "values": {}}},
        "llm_ttft_seconds", 0.5) is None

    metrics.update({
        "llm_tpot_seconds": {
            "type": "histogram", "boundaries": (0.005, 0.01),
            "values": {"a": {"counts": [3, 1, 0], "sum": 0.02, "n": 4}}},
        "llm_decode_tokens_per_s": {"type": "gauge",
                                    "values": {"w0": 120.0}},
        "llm_slo_ttft_attainment": {"type": "gauge",
                                    "values": {"w0": 0.9, "w1": 0.7}},
        "llm_slo_tpot_attainment": {"type": "gauge",
                                    "values": {"w0": 1.0, "w1": 0.5}},
    })

    class FakeClient:
        def call(self, op, payload=None, timeout=None):
            if op == "state_dump":
                return {"nodes": [{"node_id": "n" * 32, "alive": True}],
                        "leases": 0}
            if op == "timeseries_dump":
                return []
            if op == "metrics_dump":
                return metrics
            raise AssertionError(op)

    out = cli._render_top(FakeClient(), "127.0.0.1:1")
    assert "llm: decode 120 tok/s" in out
    assert "ttft p50<=10ms p99<=100ms" in out
    assert "tpot p50<=5.0ms" in out
    assert "slo ttft 80% tpot 75%" in out  # mean, not sum


def _seed_request_records(probe, trace_id):
    """Push two finished flight-recorder records (built by the REAL
    recorder, so the wire shape is authentic) + the router span of the
    slow one's trace into the head's telemetry tables."""
    import time as time_mod

    from ray_tpu.llm.request_log import FlightRecorder

    fr = FlightRecorder(capacity=8, observe_metrics=False)
    fast = fr.start("req-clifast-0", 8, 4, trace_id="")
    fast.note_admit(fast.t0 + 0.001, 0)
    fast.note_chunk(fast.t0 + 0.003, 8, 11)
    t = fast.t0 + 0.005
    fast.note_decode(t, 1)
    for _ in range(3):
        t += 0.002
        fast.note_decode(t, 1)
    fr.finish(fast, t + 0.001, "length")

    slow = fr.start("req-clislow-0", 16, 8, trace_id=trace_id)
    slow.note_admit(slow.t0 + 0.010, 4)
    slow.note_chunk(slow.t0 + 0.040, 16, 12)
    slow.note_stall(slow.t0 + 0.050)
    slow.note_preempt(slow.t0 + 0.055)
    slow.note_admit(slow.t0 + 0.060, 0)
    t = slow.t0 + 0.100
    slow.note_decode(t, 1)
    for _ in range(7):
        t += 0.020
        slow.note_decode(t, 1)
    fr.finish(slow, t + 0.001, "stop")

    now = time_mod.time()
    probe.call("telemetry_push", {
        "worker": "cliworker" + "0" * 23, "node": "clinode" + "0" * 25,
        "llm_requests": fr.drain_export(),
        "events": [{"name": "serve.router::llm.__call__",
                    "kind": "serve_router", "task_id": "",
                    "start": now - 0.2, "end": now, "ok": True,
                    "trace_id": trace_id, "span_id": "a1" * 8,
                    "parent_span_id": ""}],
    }, timeout=10)


def test_requests_cli_and_trace_request_merge(state_rt):
    """`requests` renders per-request timelines from the head's
    aggregated flight-recorder records; `--slowest N` ranks by e2e;
    `trace --request RID` merges the router span tree with the record's
    timeline (acceptance: trace-linked request view end-to-end)."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    trace_id = "feedc0de" * 4
    _seed_request_records(global_worker.backend.head, trace_id)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["requests", "--address", address]) == 0
    out = buf.getvalue()
    assert "req-clifast-0" in out and "req-clislow-0" in out
    assert "(TTFT)" in out and "enqueue" in out and "tpot" in out
    assert "reason=length" in out and "reason=stop" in out
    # the preempted record shows BOTH phases + the pressure line
    assert "admit #1" in out and "admit #2" in out
    assert "preempts 1" in out and "stalls" in out
    assert "@cliworker" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["requests", "--slowest", "1",
                         "--address", address]) == 0
    out = buf.getvalue()
    assert "req-clislow-0" in out and "req-clifast-0" not in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["requests", "--format", "json",
                         "--address", address]) == 0
    rows = json.loads(buf.getvalue())
    by_rid = {r["rid"]: r for r in rows}
    assert by_rid["req-clislow-0"]["trace_id"] == trace_id
    assert by_rid["req-clislow-0"]["preempts"] == 1

    # merged trace view: span tree + timeline in one rendering
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["trace", "--request", "req-clislow-0",
                         "--address", address]) == 0
    out = buf.getvalue()
    assert f"request req-clislow-0  trace {trace_id}" in out
    assert "serve.router::llm.__call__" in out  # the linked span tree
    assert "first tok" in out and "reason=stop" in out

    # unknown rid: exit 1 with a hint on stderr
    assert cli.main(["trace", "--request", "req-missing",
                     "--address", address]) == 1


@pytest.mark.slow
def test_requests_cli_live_watch(state_rt):
    """`requests --live` repaints until interrupted; the hidden --frames
    hook bounds the loop for tests."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    _seed_request_records(global_worker.backend.head, "ab" * 16)
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["requests", "--live", "--interval", "0.1",
                         "--frames", "2", "--address", address]) == 0
    out = buf.getvalue()
    assert out.count("\x1b[2J") == 2  # two repaints, then exit
    assert "req-clifast-0" in out


def _seed_object_directory(probe):
    """Push one fabricated owner directory + a worker-originated journal
    event into the head, exactly the wire shape cluster_backend's
    _flush_telemetry emits (dir rows + dir_totals + journal list)."""
    probe.call("telemetry_push", {
        "worker": "memworker" + "0" * 23, "node": "memnode" + "0" * 25,
        "role": "worker",
        "objects": {
            "tracked": 2, "sample": [],
            "dir": [
                {"object_id": "aa" * 14, "size": 1048576,
                 "role": "primary", "owner": "memworker000",
                 "age_s": 999.0,
                 "pins": {"local": 0, "submitted": 0, "borrowers": 0,
                          "owned": True}},
                {"object_id": "bb" * 14, "size": 4096,
                 "role": "secondary", "owner": "elsewhere000",
                 "age_s": 1.0, "pins": None},
            ],
            "dir_totals": {
                "primary": {"count": 1, "bytes": 1048576,
                            "arena_bytes": 1048576},
                "secondary": {"count": 1, "bytes": 4096,
                              "arena_bytes": 4096}},
        },
        "journal": [{"type": "spill_overflow", "object_id": "cc" * 14,
                     "bytes": 2048, "node": "memnode" + "0" * 25}],
    }, timeout=10)


def test_memory_cli(state_rt):
    """`memory` renders the head's aggregated object directory grouped
    by node with per-role totals and flags old unreferenced primaries;
    --format json round-trips the exact rows/totals."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    _seed_object_directory(global_worker.backend.head)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["memory", "--address", address]) == 0
    out = buf.getvalue()
    assert "memnode00000" in out          # node group header
    assert "primary" in out and "secondary" in out
    # the 999s-old zero-pin primary trips the leak heuristic; the fresh
    # secondary does not
    assert "LEAK?" in out and "1 LEAK suspect(s)" in out
    assert "pins=l0/s0/b0" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["memory", "--format", "json",
                         "--address", address]) == 0
    data = json.loads(buf.getvalue())
    t = data["totals"]["memnode" + "0" * 25]
    assert t["primary"] == {"count": 1, "bytes": 1048576,
                            "arena_bytes": 1048576}
    assert t["secondary"]["arena_bytes"] == 4096
    rows = [r for r in data["rows"] if r.get("reporter") == "memworker000"]
    assert {r["role"] for r in rows} == {"primary", "secondary"}

    # grouped by owner: the two rows land in different groups
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["memory", "--group-by", "owner",
                         "--address", address]) == 0
    out = buf.getvalue()
    assert "owner memworker000" in out and "owner elsewhere000" in out


def test_events_cli(state_rt):
    """`events` dumps the head journal in sequence order; --type
    filters; --follow with the hidden --frames hook terminates; json
    output carries strictly increasing seqs."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    _seed_object_directory(global_worker.backend.head)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["events", "--address", address]) == 0
    out = buf.getvalue()
    # the fixture cluster registered its node; the seed pushed a
    # worker-originated spill event sequenced at head arrival
    assert "node_register" in out and "spill_overflow" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["events", "--type", "spill_overflow",
                         "--address", address]) == 0
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    assert lines and all("spill_overflow" in ln for ln in lines)
    assert not any("node_register" in ln for ln in lines)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["events", "--format", "json",
                         "--address", address]) == 0
    evs = json.loads(buf.getvalue())
    seqs = [e["seq"] for e in evs]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert all(e.get("ts") for e in evs)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["events", "--follow", "--interval", "0.05",
                         "--frames", "2", "--address", address]) == 0
    assert "spill_overflow" in buf.getvalue()


def test_object_store_metric_names_follow_convention():
    """Every object-store series name is <subsystem>_<noun>_<unit> with
    the unit one of bytes|seconds|total|count (Prometheus naming; lint
    so new series stay greppable + renderable without special cases)."""
    import re

    from ray_tpu.util import metrics as m

    factories = [
        m.object_store_spill_write_total_counter,
        m.object_store_spill_write_bytes_counter,
        m.object_store_spill_restore_total_counter,
        m.object_store_spill_restore_bytes_counter,
        m.object_store_pull_in_bytes_counter,
        m.object_store_pull_out_bytes_counter,
        m.object_store_pull_seconds_histogram,
        m.object_store_fetch_inflight_count_gauge,
        m.object_store_primary_count_gauge,
        m.object_store_secondary_count_gauge,
        m.object_store_spilled_count_gauge,
    ]
    pat = re.compile(
        r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*_(bytes|seconds|total|count)$")
    names = set()
    for f in factories:
        inst = f()
        assert pat.match(inst.name), inst.name
        assert inst.name.startswith("object_store_"), inst.name
        names.add(inst.name)
    assert len(names) == len(factories)  # no duplicate registrations


def test_checkpoint_and_storage_metric_names_follow_convention():
    """Same lint for the ISSUE 14 series: train_checkpoint_* (async save
    telemetry) and storage_* (filesystem-seam retries/latency/volume)
    must follow <subsystem>_<noun>_<unit> with a sanctioned unit suffix."""
    import re

    from ray_tpu.util import metrics as m

    factories = [
        m.train_checkpoint_write_seconds_histogram,
        m.train_checkpoint_write_bytes_counter,
        m.train_checkpoint_queue_depth_count,
        m.train_checkpoint_step_hiccup_seconds_gauge,
        m.storage_retry_total_counter,
        m.storage_op_seconds_histogram,
        m.storage_put_bytes_counter,
    ]
    pat = re.compile(
        r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*_(bytes|seconds|total|count)$")
    names = set()
    for f in factories:
        inst = f()
        assert pat.match(inst.name), inst.name
        assert inst.name.startswith(("train_checkpoint_", "storage_")), \
            inst.name
        names.add(inst.name)
    assert len(names) == len(factories)


def test_profile_and_skew_metric_names_follow_convention():
    """Same lint for the profiler-plane series: profile_* counters carry
    a sanctioned unit suffix; train_phase_skew_s follows the existing
    train gauge `_s` convention (train_step_time_s, train_phase_time_s)
    and is tagged (phase, host) so host 0's comparison can attribute
    skew to one phase on one host."""
    import re

    from ray_tpu.util import metrics as m

    pat = re.compile(
        r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*_(bytes|seconds|total|count)$")
    names = set()
    for f in (m.profile_samples_total_counter,
              m.profile_dropped_samples_total_counter):
        inst = f()
        assert pat.match(inst.name), inst.name
        assert inst.name.startswith("profile_"), inst.name
        names.add(inst.name)
    assert len(names) == 2

    skew = m.train_phase_skew_gauge()
    assert re.match(r"^train_[a-z0-9_]+_s$", skew.name), skew.name
    assert tuple(skew.tag_keys) == ("phase", "host")


def test_log_metric_names_follow_convention():
    """Same lint for the log-plane series: log_* counters carry a
    sanctioned unit suffix, and the tagged ones declare exactly the tag
    keys the docs promise (level for volume, fingerprint for the error
    dedup series) so Prometheus renders stay stable."""
    import re

    from ray_tpu.util import metrics as m

    pat = re.compile(
        r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*_(bytes|seconds|total|count)$")
    names = set()
    for f in (m.log_records_total_counter,
              m.log_dropped_records_total_counter,
              m.log_errors_total_counter):
        inst = f()
        assert pat.match(inst.name), inst.name
        assert inst.name.startswith("log_"), inst.name
        names.add(inst.name)
    assert len(names) == 3
    assert tuple(m.log_records_total_counter().tag_keys) == ("level",)
    assert tuple(m.log_errors_total_counter().tag_keys) == ("fingerprint",)
    assert tuple(m.log_dropped_records_total_counter().tag_keys) == ()


def test_task_event_buffer_ring_eviction():
    """Satellite: the span buffer is a ring — at MAX_BUFFER the OLDEST
    spans are evicted (not the newest refused) and the __dropped__
    marker reports the exact eviction count."""
    from ray_tpu.runtime.events import TaskEventBuffer

    buf = TaskEventBuffer()
    n = TaskEventBuffer.MAX_BUFFER + 10
    for i in range(n):
        buf.record(name=f"t{i}", task_id=f"id{i}", kind="task",
                   start=float(i), end=float(i) + 0.5, ok=True)
    out = buf.drain()
    marker = [e for e in out if e["name"] == "__dropped__"]
    assert len(marker) == 1 and marker[0]["dropped"] == 10
    spans = [e for e in out if e["name"] != "__dropped__"]
    assert len(spans) == TaskEventBuffer.MAX_BUFFER
    # oldest went first: the survivors are exactly t10..t(n-1), in order
    assert spans[0]["name"] == "t10" and spans[-1]["name"] == f"t{n - 1}"
    # ring drained + marker reset: the next drain is clean
    assert buf.drain() == []


def test_local_mode_dump_synthesis():
    """Satellite: local mode has no head, so util/state._dump synthesizes
    the state_dump shape in-process — including the empty accounting
    surfaces (objects_dir, events) the cluster path always carries.
    Subprocess because the module fixture holds a cluster connection."""
    code = """
import ray_tpu as rt
rt.init(local_mode=True)
from ray_tpu.util import state
d = state._dump()
assert d["nodes"][0]["node_id"] == "local" and d["nodes"][0]["alive"]
assert d["objects_dir"] == []
assert d["events"] == {"recorded": 0, "kept": 0}
assert d["objects"][0]["owner"] == "local"
objs = state.list_objects()
assert objs and objs[0]["owner"] == "local"   # summary fallback path
s = state.summarize()
assert s["tasks"] == 0 and s["events_recorded"] == 0
assert s["objects_in_directory"] == 0
assert s["nodes_alive"] == 1
print("OK-LOCAL")
"""
    import os
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": os.path.dirname(
            os.path.dirname(rt.__file__))})
    assert out.returncode == 0, out.stderr
    assert "OK-LOCAL" in out.stdout


def test_cli_status_and_list(state_rt):
    from ray_tpu.core.worker import global_worker
    address = global_worker.backend.head_addr

    out = _cli("status", address=address)
    assert out.returncode == 0, out.stderr
    assert "nodes alive" in out.stdout and "CPU" in out.stdout

    out = _cli("list", "nodes", address=address)
    assert out.returncode == 0, out.stderr
    assert "node_id=" in out.stdout

    out = _cli("list", "actors", "--format", "json", address=address)
    assert out.returncode == 0, out.stderr
    import json
    rows = json.loads(out.stdout)
    assert isinstance(rows, list)

    out = _cli("list", "objects", address=address)
    assert out.returncode == 0, out.stderr
    assert "capacity=" in out.stdout


# ----------------------------------------------------------- compile plane


def test_xla_metric_names_follow_convention():
    """Same lint for the compile-plane series: xla_* metrics carry a
    sanctioned unit suffix, the per-kind counter declares exactly the
    (process, kind) tag keys the docs promise, and the recompile
    counter + seconds histogram stay untagged so their cluster sums
    read directly."""
    import re

    from ray_tpu.util import metrics as m

    pat = re.compile(
        r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*_(bytes|seconds|total|count)$")
    names = set()
    for f in (m.xla_compile_seconds_histogram,
              m.xla_compiles_total_counter,
              m.xla_recompiles_total_counter):
        inst = f()
        assert pat.match(inst.name), inst.name
        assert inst.name.startswith("xla_"), inst.name
        names.add(inst.name)
    assert len(names) == 3
    assert tuple(m.xla_compiles_total_counter().tag_keys) == \
        ("process", "kind")
    assert tuple(m.xla_recompiles_total_counter().tag_keys) == ()
    assert tuple(m.xla_compile_seconds_histogram().tag_keys) == ()


def _seed_compile_records(probe):
    """Push one compile window (built by the REAL tracker, so the wire
    shape is authentic) + its staged storm event into the head. The
    shape-unstable llm.ragged_step sequence yields 2 recompiles, which
    crosses the threshold=2 storm knob exactly once."""
    from ray_tpu.util.compile_tracker import CompileTracker

    tr = CompileTracker(role="worker", node="clinode", worker="cliworker",
                        ring_records=16, storm_threshold=2,
                        storm_window_s=60.0)
    tr.note_compile("llm.ragged_step", ["f32[8,128]", "i32[8]"],
                    wall_s=0.5)
    tr.note_compile("llm.ragged_step", ["f32[9,128]", "i32[8]"],
                    wall_s=0.4)
    tr.note_compile("llm.ragged_step", ["f32[10,128]", "i32[8]"],
                    wall_s=0.3)
    tr.note_compile("train.full_step", ["f32[16,64]"], wall_s=1.0)
    probe.call("telemetry_push", {
        "worker": "cliworker" + "0" * 23, "node": "clinode" + "0" * 25,
        "role": "worker",
        "compiles": tr.export(),
        "journal": tr.drain_journal_events(),
    }, timeout=10)


def test_compiles_cli_smoke(state_rt):
    """`compiles` renders the head's aggregated compile records with
    recompiles flagged and their signature diff attached; --recompiles
    filters, --by-callable aggregates, --storms lists the journal's
    once-per-excursion events."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    _seed_compile_records(global_worker.backend.head)

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["compiles", "--address", address]) == 0
    out = buf.getvalue()
    assert "RECOMPILE llm.ragged_step" in out
    assert "diff arg[0]: f32[8,128] -> f32[9,128]" in out
    assert "train.full_step" in out and "cliworker" in out
    assert "process(es)" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["compiles", "--recompiles", "--format", "json",
                         "--address", address]) == 0
    data = json.loads(buf.getvalue())
    recs = [r for r in data["records"]
            if r["name"] == "llm.ragged_step"]
    assert len(recs) >= 2
    assert all(r["recompile"] for r in recs)
    assert data["last_seq"] >= 4

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["compiles", "--by-callable",
                         "--address", address]) == 0
    out = buf.getvalue()
    assert "callable" in out and "recompiles" in out
    assert "llm.ragged_step" in out and "train.full_step" in out

    # the threshold=2 excursion staged exactly one storm journal event
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["compiles", "--storms", "--format", "json",
                         "--address", address]) == 0
    storms = json.loads(buf.getvalue())
    assert len(storms) == 1, storms
    assert storms[0]["type"] == "compile_storm"
    assert storms[0]["callable"] == "llm.ragged_step"


def test_trace_perfetto_cli_smoke(state_rt, tmp_path):
    """`trace --perfetto OUT` writes one multi-plane Chrome/Perfetto
    trace: task-span lanes per node, the train step/phase lane, the XLA
    compile lane (recompiles carrying their diff), and journal
    instants — all on one wall clock."""
    import io
    import time as time_mod
    from contextlib import redirect_stdout

    from ray_tpu.core.worker import global_worker
    from ray_tpu.scripts import cli

    address = global_worker.backend.head_addr
    head = global_worker.backend.head
    _seed_compile_records(head)
    now = time_mod.time()
    head.call("telemetry_push", {
        "worker": "cliworker" + "0" * 23, "node": "clinode" + "0" * 25,
        "events": [
            {"name": "step", "kind": "train_step", "task_id": "tsp",
             "start": now - 0.5, "end": now - 0.2, "ok": True},
            {"name": "forward", "kind": "train_phase", "task_id": "tsp",
             "start": now - 0.5, "end": now - 0.4, "ok": True},
            {"name": "work_task", "kind": "task", "task_id": "t" * 32,
             "start": now - 1.0, "end": now - 0.9, "ok": True},
        ]}, timeout=10)

    out_path = tmp_path / "cluster.perfetto.json"
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["trace", "--perfetto", str(out_path),
                         "--address", address]) == 0
    assert "lanes" in buf.getvalue()

    with open(out_path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    assert trace.get("displayTimeUnit") == "ms"
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "xla: compiles" in lanes, lanes
    assert "train: steps + phases" in lanes, lanes
    assert any(name.startswith("spans: node") for name in lanes), lanes
    # the compile lane carries the recompile with its signature diff
    rec = next(e for e in evs
               if e.get("ph") == "X"
               and str(e.get("name", "")).startswith("RECOMPILE"))
    assert rec["args"]["diff"], rec
    assert any(e.get("cat") == "train_phase" for e in evs)
    assert any(e.get("cat") == "journal" for e in evs)
