"""RLlib slice tests: GAE math, env dynamics, and PPO actually learning
CartPole (reference scope: rllib/algorithms/ppo tests + learner tests)."""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rllib import CartPoleVectorEnv, PPOConfig, compute_gae


@pytest.fixture(scope="module")
def local_rt():
    rt.init(local_mode=True, num_cpus=4)
    yield rt
    rt.shutdown()


def test_cartpole_env_terminates_and_resets():
    env = CartPoleVectorEnv(4)
    obs = env.reset(seed=0)
    assert obs.shape == (4, 4)
    # always push right: poles must fall within ~200 steps
    done_seen = False
    for _ in range(300):
        obs, r, dones, _ = env.step(np.ones(4, np.int64))
        assert r.shape == (4,)
        if dones.any():
            done_seen = True
            break
    assert done_seen, "pole never fell under constant force"
    assert len(env.episode_returns) >= 1


def test_gae_matches_manual():
    import jax.numpy as jnp
    T, B = 3, 1
    rewards = jnp.asarray([[1.0], [1.0], [1.0]])
    values = jnp.asarray([[0.5], [0.4], [0.3]])
    dones = jnp.zeros((T, B), bool)
    last_value = jnp.asarray([0.2])
    gamma, lam = 0.9, 0.8
    advs, rets = compute_gae(rewards, values, dones, last_value,
                             gamma=gamma, lam=lam)
    # manual backward recursion
    adv = np.zeros(T)
    next_adv, next_v = 0.0, 0.2
    for t in reversed(range(T)):
        delta = 1.0 + gamma * next_v - float(values[t, 0])
        adv[t] = delta + gamma * lam * next_adv
        next_adv, next_v = adv[t], float(values[t, 0])
    np.testing.assert_allclose(np.asarray(advs)[:, 0], adv, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(rets),
                               np.asarray(advs) + np.asarray(values),
                               rtol=1e-5)


def test_ppo_learns_cartpole(local_rt):
    algo = PPOConfig(
        num_env_runners=2, num_envs_per_runner=16, rollout_length=64,
        lr=1e-3, entropy_coeff=0.01, num_epochs=4, minibatches=4,
        seed=3).build()
    first_mean = None
    best = 0.0
    for i in range(40):
        result = algo.train()
        mean = result["episode_return_mean"]
        if first_mean is None and result["episodes_this_iter"]:
            first_mean = mean
        best = max(best, mean if mean == mean else 0.0)
        if best >= 100.0:
            break
    algo.stop()
    assert first_mean is not None and first_mean < 60.0, \
        f"env suspiciously easy from the start: {first_mean}"
    assert best >= 100.0, \
        f"PPO failed to learn: first={first_mean}, best={best}"


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rllib import ReplayBuffer
    buf = ReplayBuffer(capacity=10, obs_dim=2, seed=0)
    obs = np.arange(16 * 2, dtype=np.float32).reshape(16, 2)
    buf.add_batch(obs[:8], np.arange(8), np.ones(8), np.zeros(8, bool),
                  obs[1:9])
    assert len(buf) == 8
    buf.add_batch(obs[8:14], np.arange(8, 14), np.ones(6),
                  np.zeros(6, bool), obs[9:15])
    assert len(buf) == 10  # capacity-clamped after wraparound
    s = buf.sample(32)
    assert s["obs"].shape == (32, 2)
    # wraparound overwrote the oldest entries: actions 0..3 are gone
    assert set(np.unique(s["actions"])).issubset(set(range(4, 14)))


def test_dqn_learns_cartpole(local_rt):
    """The Learner/EnvRunner seams serve a REPLAY-based algorithm
    (reference: rllib/algorithms/dqn/ — buffer + target net + epsilon
    decay), not just on-policy PPO."""
    from ray_tpu.rllib import DQNConfig
    algo = DQNConfig(
        num_env_runners=2, num_envs_per_runner=8, rollout_length=32,
        lr=1e-3, learning_starts=500, updates_per_iter=16,
        target_sync_every=100, epsilon_decay_iters=25, seed=1).build()
    first_mean = None
    best = 0.0
    for _ in range(60):
        result = algo.train()
        mean = result["episode_return_mean"]
        if first_mean is None and result["episodes_this_iter"]:
            first_mean = mean
        best = max(best, mean if mean == mean else 0.0)
        if best >= 100.0:
            break
    algo.stop()
    assert first_mean is not None and first_mean < 60.0, \
        f"env suspiciously easy from the start: {first_mean}"
    assert best >= 100.0, \
        f"DQN failed to learn: first={first_mean}, best={best}"


def test_vtrace_on_policy_reduces_to_td():
    """With behavior == target policy (rhos = 1) and c=rho=1, V-trace
    v_s equals the lambda=1 TD(lambda) corrected value — check against a
    manual backward recursion."""
    import jax.numpy as jnp
    from ray_tpu.rllib import vtrace
    T = 4
    logp = jnp.log(jnp.full((T, 1), 0.5))
    values = jnp.asarray([[0.5], [0.4], [0.3], [0.2]])
    rewards = jnp.asarray([[1.0], [0.0], [1.0], [1.0]])
    dones = jnp.zeros((T, 1), bool)
    last_value = jnp.asarray([0.1])
    vs, pg_adv = vtrace(logp, logp, values, rewards, dones, last_value,
                        gamma=0.9)
    # manual: delta_t = r + g*v_next - v ; acc = delta + g*acc_next
    v = np.asarray(values)[:, 0]
    r = np.asarray(rewards)[:, 0]
    vn = np.append(v[1:], 0.1)
    acc = 0.0
    expect = np.zeros(T)
    for t in reversed(range(T)):
        delta = r[t] + 0.9 * vn[t] - v[t]
        acc = delta + 0.9 * acc
        expect[t] = v[t] + acc
    np.testing.assert_allclose(np.asarray(vs)[:, 0], expect, rtol=1e-5)
    # pg advantage at t uses vs_{t+1}
    vs_next = np.append(np.asarray(vs)[1:, 0], 0.1)
    np.testing.assert_allclose(np.asarray(pg_adv)[:, 0],
                               r + 0.9 * vs_next - v, rtol=1e-5)


def test_vtrace_clips_importance_weights():
    import jax.numpy as jnp
    from ray_tpu.rllib import vtrace
    T = 2
    behavior = jnp.log(jnp.full((T, 1), 0.1))  # improbable under behavior
    target = jnp.log(jnp.full((T, 1), 0.9))    # likely under target
    values = jnp.zeros((T, 1))
    rewards = jnp.ones((T, 1))
    dones = jnp.zeros((T, 1), bool)
    lv = jnp.zeros(1)
    vs_clip, _ = vtrace(behavior, target, values, rewards, dones, lv,
                        gamma=1.0, rho_clip=1.0, c_clip=1.0)
    # rho = 9 clipped to 1: identical to the on-policy result
    vs_on, _ = vtrace(target, target, values, rewards, dones, lv,
                      gamma=1.0)
    np.testing.assert_allclose(np.asarray(vs_clip), np.asarray(vs_on),
                               rtol=1e-6)


def test_impala_learns_cartpole(local_rt):
    from ray_tpu.rllib import IMPALAConfig
    algo = IMPALAConfig(
        num_env_runners=2, num_envs_per_runner=16, rollout_length=32,
        batches_per_iteration=8, lr=1e-3, entropy_coeff=0.01,
        seed=0).build()
    try:
        best = 0.0
        for _ in range(30):
            result = algo.train()
            if result["episodes_this_iter"]:
                best = max(best, result["episode_return_mean"])
            if best >= 120.0:
                break
        assert best >= 120.0, f"IMPALA failed to learn: best={best}"
    finally:
        algo.stop()


def test_pendulum_env_dynamics():
    from ray_tpu.rllib import PendulumVectorEnv
    env = PendulumVectorEnv(4)
    obs = env.reset(seed=0)
    assert obs.shape == (4, 3)
    # cos^2 + sin^2 = 1 invariant
    np.testing.assert_allclose(obs[:, 0] ** 2 + obs[:, 1] ** 2, 1.0,
                               rtol=1e-6)
    total = np.zeros(4)
    for _ in range(200):
        obs, r, dones, _ = env.step(np.zeros((4, 1), np.float32))
        assert (r <= 0).all()          # cost-based reward is never positive
        total += r
    assert dones.all(), "episodes must time-limit at 200 steps"
    assert len(env.episode_returns) == 4
    # zero-torque returns are bad but bounded
    assert (total > -2000).all() and (total < -100).all(), total


def test_sac_learns_pendulum(local_rt):
    """Continuous control through the shared seams (VERDICT round-4 #5):
    squashed-Gaussian actor + twin critics + auto temperature reach a
    reward gate on Pendulum — the RL stack is not CartPole-shaped
    (reference: rllib/algorithms/sac/sac.py)."""
    from ray_tpu.rllib import SACConfig
    algo = SACConfig(
        num_env_runners=2, num_envs_per_runner=8, rollout_length=32,
        lr=1e-3, learning_starts=512, updates_per_iter=256,
        train_batch_size=256, seed=0).build()
    first_mean = None
    best = -1e9
    try:
        for _ in range(60):
            result = algo.train()
            mean = result["episode_return_mean"]
            if first_mean is None and result["episodes_this_iter"]:
                first_mean = mean
            if mean == mean:
                best = max(best, mean)
            if best >= -350.0:
                break
    finally:
        algo.stop()
    assert first_mean is not None and first_mean < -700.0, \
        f"env suspiciously easy from the start: {first_mean}"
    assert best >= -350.0, \
        f"SAC failed to learn: first={first_mean}, best={best}"


def test_bc_clones_ppo_policy_from_dataset(local_rt):
    """Offline RL through the Data->Train path (VERDICT #8 done-criterion):
    record episodes from a trained PPO policy into a ray_tpu.data dataset,
    behavior-clone from the dataset alone, and reach reward parity with
    the PPO gate (reference: rllib/algorithms/bc + rllib/offline)."""
    from ray_tpu.rllib import BCConfig, record_dataset

    ppo = PPOConfig(
        num_env_runners=2, num_envs_per_runner=16, rollout_length=64,
        lr=1e-3, entropy_coeff=0.01, num_epochs=4, minibatches=4,
        seed=3).build()
    best = 0.0
    for _ in range(40):
        result = ppo.train()
        mean = result["episode_return_mean"]
        best = max(best, mean if mean == mean else 0.0)
        if best >= 100.0:
            break
    assert best >= 100.0, f"teacher PPO failed to learn: best={best}"

    ds = record_dataset(ppo, num_samples=8192)
    assert ds.count() == 8192
    ppo.stop()

    bc = BCConfig(dataset=ds, lr=1e-3, batch_size=512, seed=11).build()
    bc_best = 0.0
    for _ in range(15):
        result = bc.train()
        mean = result["episode_return_mean"]
        bc_best = max(bc_best, mean if mean == mean else 0.0)
        if bc_best >= 100.0:
            break
    bc.stop()
    assert bc_best >= 100.0, \
        f"BC failed to reach teacher parity: best={bc_best}"
