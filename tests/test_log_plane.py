"""Cluster-wide structured log plane.

Units: ring overflow with EXACT drop accounting (emitted == stored +
dropped across any export sequence), file-sink rotation, the head-side
LogStore (severity rings, cursor, filters, LRU), error fingerprinting,
storm detection (one journal event per excursion), the worker tee/
shipper satellite fixes, and the ambient request-id contextvar.

Lints: no bare `print(` calls anywhere in ray_tpu/ outside scripts/cli.py
(daemon diagnostics must go through the structured logger), and the
module must import jax-free (it runs inside the head and node daemons).

E2E: a two-node cluster where task prints under an active trace land in
the head's LogStore trace-stamped, request-id scoped records are
queryable with --request, a SIGKILLed worker's stderr/log tails are
attached to its worker_death journal record, and a forced overflow burst
keeps the stored+dropped ledger exact.

Reference: `ray logs` / log_monitor.py over session_latest/logs — ours is
structured, head-aggregated and correlation-stamped rather than
file-scrape-only.
"""

import ast
import io
import json
import os
import signal
import subprocess
import sys
import time
from contextlib import redirect_stdout

import pytest

from ray_tpu.util import log_plane as lp

MiB = 1 << 20


# ----------------------------------------------------------------- lints

def test_log_plane_imports_without_jax():
    """Tier-1 contract: the log plane runs inside the head and node
    daemons, which must never pull in the accelerator stack."""
    code = (
        "import sys; from ray_tpu.util import log_plane as lp; "
        "lg = lp.StructuredLogger(role='t'); "
        "lg.info('hello', k=1); e = lg.export(); "
        "assert e and e['emitted'] == 1, e; "
        "s = lp.LogStore(); s.ingest('t', e); "
        "assert s.dump()['records'], 'store empty'; "
        "print('jax' in sys.modules)")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "False", out.stdout


def test_no_bare_print_outside_cli():
    """Daemon/runtime diagnostics must go through the structured logger
    (or an explicit sys.stream write) — a bare print() in a worker
    recurses through the tee and is invisible to `ray_tpu logs`. The CLI
    is the one legitimate print surface."""
    pkg = os.path.join(os.path.dirname(lp.__file__), "..")
    pkg = os.path.abspath(pkg)
    offenders = []
    for dirpath, _dirnames, filenames in os.walk(pkg):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, pkg)
            if rel == os.path.join("scripts", "cli.py"):
                continue
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)
            for node in ast.walk(tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    offenders.append(f"{rel}:{node.lineno}")
    assert not offenders, (
        "bare print() in runtime code (route through "
        "log_plane.get_logger() or sys.<stream>.write): "
        + ", ".join(offenders))


# ----------------------------------------------------------------- units

def test_ring_overflow_exact_drop_accounting():
    """The acceptance invariant: across any sequence of exports,
    sum(emitted) == sum(stored) + sum(dropped), to the record."""
    lg = lp.StructuredLogger(role="t", ring_size=8)
    for i in range(30):
        lg.info(f"burst {i}")
    e = lg.export()
    assert e["emitted"] == 30
    assert len(e["records"]) == 8
    assert e["dropped"] == 22
    assert e["emitted"] == len(e["records"]) + e["dropped"]
    # drained: an immediate re-export is empty
    assert lg.export() is None
    # multi-window: the invariant holds summed across windows too
    tot_emitted, tot_stored, tot_dropped = 30, 8, 22
    for n in (3, 20, 1):
        for i in range(n):
            lg.warning(f"w{i}")
        e = lg.export()
        tot_emitted += e["emitted"]
        tot_stored += len(e["records"])
        tot_dropped += e["dropped"]
    assert tot_emitted == tot_stored + tot_dropped
    assert lg.stats()["emitted_total"] == tot_emitted
    assert lg.stats()["dropped_total"] == tot_dropped


def test_export_levels_and_stamps():
    lg = lp.StructuredLogger(role="worker", node="n1", worker="w1",
                             ring_size=64)
    tok = None
    from ray_tpu.util import trace_context
    tok = trace_context.activate("t" * 32, "s" * 16)
    try:
        with lp.request_context("req-abc-1"):
            rec = lg.info("hello", foo="bar")
    finally:
        trace_context.deactivate(tok)
    assert rec["level"] == "info" and rec["msg"] == "hello"
    assert rec["role"] == "worker" and rec["node"] == "n1"
    assert rec["worker"] == "w1" and rec["pid"] == os.getpid()
    assert rec["trace_id"] == "t" * 32
    assert rec["request_id"] == "req-abc-1"
    assert rec["fields"] == {"foo": "bar"}
    # outside the scopes: unstamped
    rec2 = lg.info("later")
    assert rec2["trace_id"] == "" and rec2["request_id"] == ""
    # unknown level degrades to info, JSON-serializable as-is
    rec3 = lg.log("nonsense", "x")
    assert rec3["level"] == "info"
    json.dumps(lg.export())


def test_file_sink_rotation(tmp_path):
    path = str(tmp_path / "x.log")
    sink = lp._FileSink(path, max_bytes=4096, backups=2)
    line = "y" * 100
    for _ in range(200):  # ~20 KiB >> 4 KiB cap
        sink.write_line(line)
    sink.close()
    assert os.path.exists(path)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert os.path.getsize(path) <= 4096 + 128
    # rotation preserved whole lines
    with open(path + ".1") as f:
        for ln in f.read().splitlines():
            assert ln == line
    # a dead sink (unwritable dir) swallows, never raises
    bad = lp._FileSink(str(tmp_path / "x.log" / "nope.log"),
                       max_bytes=4096)
    bad.write_line("a")  # open fails -> dead
    bad.write_line("b")
    assert bad._dead


def test_error_fingerprint_normalizes_ids():
    a = lp.error_fingerprint("worker 4f21ab9920ccd110 died rc=137")
    b = lp.error_fingerprint("worker 9ac3004cde1199ff died rc=1")
    assert a == b  # one bug, one fingerprint
    assert a != lp.error_fingerprint("lease rejected for worker 4f21")
    assert len(a) == 12 and all(c in "0123456789abcdef" for c in a)
    assert lp.error_fingerprint("oom at 0xDEADBEEF") == \
        lp.error_fingerprint("oom at 0x1234")


def test_error_storm_one_event_per_excursion():
    lg = lp.StructuredLogger(role="t", ring_size=64,
                             storm_threshold=5, storm_window_s=0.2)
    for i in range(10):
        lg.error(f"boom {i}")
    evs = lg.drain_journal_events()
    assert len(evs) == 1, evs  # one event for the whole excursion
    ev = evs[0]
    assert ev["type"] == "log_error_storm"
    assert ev["errors"] >= 5 and ev["window_s"] == 0.2
    # still storming: no second event
    for i in range(5):
        lg.error(f"boom more {i}")
    assert lg.drain_journal_events() == []
    # recovery re-arms: window empties, then a fresh burst fires again
    time.sleep(0.3)
    lg.error("calm one")  # prunes the window; count < threshold/2
    for i in range(6):
        lg.error(f"boom again {i}")
    evs = lg.drain_journal_events()
    assert len(evs) == 1, evs
    # fingerprints accumulated under the normalized key
    fps = lg.stats()["fingerprints"]
    assert fps[lp.error_fingerprint("boom 1")] >= 10


def test_fingerprint_cap_folds_long_tail():
    lg = lp.StructuredLogger(role="t", ring_size=8, storm_threshold=0)
    for i in range(lp._FINGERPRINT_CAP + 20):
        # non-hex letters (runs of hex chars normalize away), distinct
        # lengths: each message is a DISTINCT fingerprint
        lg.error("unique " + "xyz"[i % 3] * (i + 1))
    fps = lg.stats()["fingerprints"]
    assert len(fps) <= lp._FINGERPRINT_CAP + 1
    assert fps.get("other", 0) > 0  # tail folded, not dropped


def test_log_store_severity_rings_cursor_filters_lru():
    store = lp.LogStore(ring=8, max_procs=4)  # 8 = the floor

    def mk(recs):
        return {"records": recs, "emitted": len(recs), "dropped": 0,
                "pid": 1, "ts": time.time()}

    def rec(level, msg, **kw):
        base = {"ts": time.time(), "level": level, "role": "worker",
                "node": "nodeA", "worker": "w1", "pid": 1,
                "trace_id": "", "request_id": "", "msg": msg,
                "fields": {}}
        base.update(kw)
        return base

    # an early error survives a later debug flood: severity-indexed rings
    store.ingest("w1", mk([rec("error", "the crash")]),
                 role="worker", node="nodeA", worker="w1")
    store.ingest("w1", mk([rec("debug", f"noise {i}") for i in range(20)]),
                 role="worker", node="nodeA", worker="w1")
    d = store.dump(worker="w1")
    msgs = [r["msg"] for r in d["records"]]
    assert "the crash" in msgs
    assert sum(1 for m in msgs if m.startswith("noise")) == 8  # ring=8
    # severity floor
    d = store.dump(level="error")
    assert [r["msg"] for r in d["records"]] == ["the crash"]
    # grep regex on msg
    d = store.dump(grep=r"^the cr\w+$")
    assert [r["msg"] for r in d["records"]] == ["the crash"]
    # cursor: seq is head-assigned and monotonic; after_seq follows
    all_recs = store.dump()["records"]
    seqs = [r["seq"] for r in all_recs]
    assert seqs == sorted(seqs)
    mid = seqs[len(seqs) // 2]
    d = store.dump(after_seq=mid)
    assert all(r["seq"] > mid for r in d["records"])
    assert d["last_seq"] >= seqs[-1]
    # limit keeps the NEWEST n
    d = store.dump(limit=2)
    assert [r["seq"] for r in d["records"]] == seqs[-2:]
    # trace/request correlation filters
    store.ingest("w2", mk([
        rec("info", "traced", trace_id="t" * 32, worker="w2"),
        rec("info", "requested", request_id="req-1-0", worker="w2"),
    ]), role="worker", node="nodeB", worker="w2")
    assert [r["msg"] for r in store.dump(trace="t" * 32)["records"]] \
        == ["traced"]
    assert [r["msg"] for r in store.dump(request="req-1-0")["records"]] \
        == ["requested"]
    # node / role filters (substring, same as profiles_dump)
    assert all(r["msg"] in ("traced", "requested")
               for r in store.dump(node="nodeB")["records"])
    assert store.dump(role="node")["records"] == []
    # drop ledger aggregates per-proc
    store.ingest("w2", {"records": [], "emitted": 7, "dropped": 7,
                        "pid": 1, "ts": time.time()},
                 role="worker", node="nodeB", worker="w2")
    assert store.dump(worker="w2")["dropped_total"] == 7
    # LRU: two more procs overflow max_procs=4 and evict the oldest (w1)
    for k in ("w3", "w4", "w5"):
        store.ingest(k, mk([rec("info", k, worker=k)]),
                     role="worker", worker=k)
    assert store.dump(worker="w1")["records"] == []
    assert store.stats()["procs"] == 4


def test_log_shipper_carries_drops_across_empty_flush():
    """Satellite: drops recorded while the batch was empty must survive
    to the next non-empty flush — the '...N dropped' notice itself must
    never be dropped."""
    from ray_tpu.runtime import worker_main as wm

    sent = []

    class _Client:
        def oneway(self, method, payload):
            sent.append(payload)

    class _Plane:
        def owner_client(self, owner):
            return _Client()

    class _Worker:
        class worker_id:
            @staticmethod
            def hex():
                return "ab" * 16

    class _Backend:
        object_plane = _Plane()
        worker = _Worker()

    shipper = _LogShipperNoThread(wm, _Backend())
    shipper.set_owner(b"o" * 16)
    # overflow: buffer fills, then keeps dropping the oldest
    for i in range(shipper.MAX_BUFFER + 5):
        shipper.emit("stdout", f"line {i}")
    # drain the buffer WITHOUT a flush (simulates the flush thread
    # racing production), leaving only the drop count behind
    with shipper._lock:
        shipper._buf.clear()
    shipper.flush()     # empty batch + pending drops: nothing sent...
    assert sent == []
    shipper.emit("stdout", "after")
    shipper.flush()     # ...but the count was carried, not lost
    assert len(sent) == 1
    lines = sent[0]["lines"]
    assert ("stdout", "after") in lines
    assert any("5 log lines dropped" in text for _s, text in lines), lines


def _LogShipperNoThread(wm, backend):
    """A _LogShipper without its background flush thread (deterministic
    flush timing for the drop-carry test)."""
    shipper = wm._LogShipper.__new__(wm._LogShipper)
    import collections
    import contextvars
    import threading
    shipper.backend = backend
    shipper._owner_var = contextvars.ContextVar("t_owner", default=None)
    shipper._lock = threading.Lock()
    shipper._buf = collections.deque()
    shipper._last_owner = None
    shipper._dropped = 0
    return shipper


def test_tee_stream_emits_trailing_partial_on_flush():
    """Satellite: print(..., end='') then flush (or process exit via the
    atexit hooks) must emit the partial line — the last words before a
    crash are exactly the ones written without a newline."""
    from ray_tpu.runtime import worker_main as wm

    got = []

    class _Shipper:
        def emit(self, stream, text):
            got.append((stream, text))

    real = io.StringIO()
    tee = wm._TeeStream(real, "stdout", _Shipper())
    tee.write("complete line\npartial")
    assert got == [("stdout", "complete line")]
    tee.flush()
    assert got == [("stdout", "complete line"), ("stdout", "partial")]
    assert real.getvalue() == "complete line\npartial"
    tee.flush()  # idempotent: nothing left to emit
    assert len(got) == 2


def test_tee_stream_feeds_log_plane_without_shipper(tmp_path):
    """Satellite: pre-first-task (ownerless) output still reaches the
    local file sink + ring via the process logger, even with no shipper
    owner to attribute it to."""
    from ray_tpu.runtime import worker_main as wm

    lp.stop_global()
    from ray_tpu.core.config import GlobalConfig
    assert GlobalConfig.log_plane_enabled
    try:
        lg = lp.ensure_started(role="worker", worker="wX",
                               log_dir=str(tmp_path), filename="wX.log")
        assert lg is not None
        tee = wm._TeeStream(io.StringIO(), "stderr", shipper=None)
        tee.write("early traceback\n")
        tee.write("dying words")
        tee.flush()
        e = lg.export()
        msgs = [(r["level"], r["msg"]) for r in e["records"]]
        assert ("error", "early traceback") in msgs  # stderr -> error
        assert ("error", "dying words") in msgs
        for r in e["records"]:
            assert r["fields"]["stream"] == "stderr"
        # and the durable sink has them as JSON lines
        with open(tmp_path / "wX.log") as f:
            on_disk = [json.loads(ln)["msg"] for ln in f]
        assert "early traceback" in on_disk and "dying words" in on_disk
    finally:
        lp.stop_global()


def test_null_logger_keeps_warnings_visible():
    lp.stop_global()
    lg = lp.get_logger()
    assert isinstance(lg, lp._NullLogger)
    lg.debug("invisible")
    lg.info("invisible too")
    lg.warning("something odd")
    lg.error("something bad")
    assert lg.export() is None and lg.drain_journal_events() == []


def test_ensure_started_respects_disable(tmp_path):
    from ray_tpu.core.config import GlobalConfig
    lp.stop_global()
    old = GlobalConfig.log_plane_enabled
    try:
        GlobalConfig.apply({"log_plane_enabled": False})
        assert lp.ensure_started(role="t") is None
        assert lp.get_global() is None
        assert lp.drain_export() is None
    finally:
        GlobalConfig.apply({"log_plane_enabled": old})
        lp.stop_global()


def test_tail_lines_bounded(tmp_path):
    p = tmp_path / "t.err"
    p.write_text("".join(f"line {i}\n" for i in range(1000)))
    assert lp.tail_lines(str(p), 3) == ["line 997", "line 998",
                                        "line 999"]
    assert lp.tail_lines(str(p), 0) == []
    assert lp.tail_lines(str(tmp_path / "missing"), 5) == []
    assert lp.tail_lines(None, 5) == []
    # bounded read: a tiny max_bytes still returns the newest lines
    assert lp.tail_lines(str(p), 2, max_bytes=64)[-1] == "line 999"


def test_format_record_renders_correlation():
    line = lp.format_record({
        "ts": time.time(), "level": "error", "role": "worker",
        "node": "nodeA", "worker": "w1", "pid": 7,
        "trace_id": "t" * 32, "request_id": "req-9",
        "msg": "boom", "fields": {"rc": 137}})
    assert "ERROR" in line and "boom" in line and "w1" in line
    assert "rc=137" in line
    assert f"trace={'t' * 12}" in line and "req=req-9" in line


# ------------------------------------------------------------------- e2e

@pytest.fixture(scope="module")
def two_node_logged():
    import ray_tpu as rt
    rt.init(num_cpus=1, _system_config={
        "object_store_memory_bytes": 64 * MiB,
        "metrics_export_period_s": 0.2,
        "hw_sampler_period_s": 0.5,
        "log_ring_records": 64,       # small ring: overflow is testable
        "log_death_tail_lines": 20,
    })
    from ray_tpu.core.worker import global_worker
    from ray_tpu.runtime.cluster_backend import start_node
    backend = global_worker.backend
    session = backend.head.call("connect_driver", {})["session"]
    proc = start_node(backend.head_addr, session,
                      resources={"CPU": 1.0, "n2": 1.0})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"second node exited rc={proc.returncode}")
        nodes = backend.head.call("list_nodes")
        if sum(1 for n in nodes if n["alive"]) >= 2:
            break
        time.sleep(0.2)
    else:
        raise RuntimeError("second node never registered")
    yield rt, backend, session
    proc.terminate()
    try:
        proc.wait(timeout=10)
    finally:
        rt.shutdown()


def _dump_until(head, payload, pred, timeout=30):
    deadline = time.monotonic() + timeout
    d = {"records": []}
    while time.monotonic() < deadline:
        d = head.call("logs_dump", dict(payload), timeout=10)
        if pred(d):
            return d
        time.sleep(0.3)
    return d


def test_task_logs_reach_head_trace_stamped(two_node_logged):
    """A task's prints (tee'd) and logger records land in the head's
    LogStore stamped with the task's ambient trace id; `logs --trace`
    returns exactly the correlated lines."""
    rt_, backend, _session = two_node_logged
    head = backend.head

    @rt_.remote(num_cpus=1)
    def chatty():
        from ray_tpu.util import log_plane, trace_context
        print("marker-stdout-line")
        log_plane.get_logger().info("marker-structured-line", step=3)
        ctx = trace_context.current()
        return ctx[0] if ctx else ""

    tid = rt_.get(chatty.remote(), timeout=60)
    assert tid
    d = _dump_until(
        head, {"trace": tid},
        lambda d: {"marker-stdout-line", "marker-structured-line"}
        <= {r["msg"] for r in d["records"]})
    msgs = {r["msg"] for r in d["records"]}
    assert {"marker-stdout-line", "marker-structured-line"} <= msgs, msgs
    for r in d["records"]:
        assert r["trace_id"] == tid
        assert r["role"] == "worker" and r["worker"], r
    # the structured record kept its fields
    rec = next(r for r in d["records"]
               if r["msg"] == "marker-structured-line")
    assert rec["fields"]["step"] == 3
    # grep narrows within the trace
    d2 = head.call("logs_dump", {"trace": tid, "grep": "stdout"},
                   timeout=10)
    assert {r["msg"] for r in d2["records"]} == {"marker-stdout-line"}


def test_request_scoped_logs_queryable(two_node_logged):
    """Records emitted inside a request_context (the Serve/LLM wrapper's
    mechanism) are queryable by request id at the head."""
    rt_, backend, _session = two_node_logged
    head = backend.head
    rid = "req-e2etest-0"

    @rt_.remote(num_cpus=1)
    def serve_like(rid):
        from ray_tpu.util import log_plane
        with log_plane.request_context(rid):
            log_plane.get_logger().info("llm request start")
            log_plane.get_logger().info("llm request finished")
        return True

    assert rt_.get(serve_like.remote(rid), timeout=60)
    d = _dump_until(head, {"request": rid},
                    lambda d: len(d["records"]) >= 2)
    msgs = [r["msg"] for r in d["records"]]
    assert "llm request start" in msgs and "llm request finished" in msgs
    assert all(r["request_id"] == rid for r in d["records"])


def test_overflow_burst_exact_ledger(two_node_logged):
    """Forced overflow: a tight burst past the (shrunken) ring drops
    records at the source, and the head's ledger stays exact —
    emitted == stored-at-head + dropped, to the record."""
    rt_, backend, _session = two_node_logged
    head = backend.head
    n_burst = 300

    @rt_.remote(num_cpus=1)
    def burst(n):
        from ray_tpu.util import log_plane
        lg = log_plane.get_global()
        before = lg.stats()
        for i in range(n):
            lg.warning(f"ledger-burst {i}")
        after = lg.stats()
        return {"emitted": after["emitted_total"] - before["emitted_total"],
                "dropped_delta": after["dropped_total"]
                - before["dropped_total"],
                "worker": lg.worker}

    r = rt_.get(burst.remote(n_burst), timeout=60)
    assert r["emitted"] == n_burst
    assert r["dropped_delta"] > 0  # the 64-slot ring really overflowed

    def settled(d):
        stored = sum(1 for rec in d["records"]
                     if rec["msg"].startswith("ledger-burst"))
        return stored + d["dropped_total"] >= n_burst

    d = _dump_until(head, {"worker": r["worker"], "grep": "ledger-burst"},
                    settled)
    stored = len(d["records"])
    assert stored + d["dropped_total"] == n_burst, \
        (stored, d["dropped_total"])


def test_worker_sigkill_forensics_in_journal(two_node_logged, tmp_path):
    """SIGKILL a worker mid-task: the node daemon tails the dead
    worker's durable .err stream and .log records into the
    worker_death journal record (bounded)."""
    rt_, backend, _session = two_node_logged
    head = backend.head
    sentinel = str(tmp_path / "released")

    @rt_.remote(num_cpus=1)
    def doomed(sentinel):
        import os as _os
        import sys as _sys
        import time as _time
        from ray_tpu.util import log_plane
        log_plane.get_logger().error("fatal: about to be killed")
        print("last words before sigkill", file=_sys.stderr)
        _sys.stderr.flush()
        # park until killed; a post-kill RETRY of this task sees the
        # sentinel and returns fast instead of hogging a cpu slot
        for _ in range(600):
            if _os.path.exists(sentinel):
                return 0
            _time.sleep(0.1)
        return _os.getpid()

    ref = doomed.remote(sentinel)
    # find the victim: the worker that emitted the marker
    deadline = time.monotonic() + 30
    victim = None
    while time.monotonic() < deadline and victim is None:
        d = head.call("logs_dump", {"grep": "about to be killed"},
                      timeout=10)
        for rec in d["records"]:
            victim = (rec["worker"], rec["pid"])
        time.sleep(0.3)
    assert victim, "marker record never reached the head"
    os.kill(victim[1], signal.SIGKILL)
    with open(sentinel, "w"):
        pass
    try:  # dead-worker failure or a successful retry: both acceptable
        rt_.get(ref, timeout=60)
    except Exception:
        pass
    ev = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and ev is None:
        for e in head.call("events_dump", {"type": "worker_death"},
                           timeout=10):
            if str(e.get("worker_id", "")).startswith(victim[0]) \
                    and e.get("stderr_tail"):
                ev = e
        time.sleep(0.3)
    assert ev is not None, "worker_death with tails never journaled"
    assert any("last words before sigkill" in ln
               for ln in ev["stderr_tail"]), ev["stderr_tail"]
    assert ev.get("log_tail"), ev
    assert any("about to be killed" in ln for ln in ev["log_tail"]), \
        ev["log_tail"]
    # bounded: the config cap (20) held, after head-side re-bounding
    assert len(ev["stderr_tail"]) <= 50
    assert len(ev["log_tail"]) <= 50


def test_every_role_reports_and_files_exist(two_node_logged):
    """Every role's logger reports into the store, and the durable
    session log directory has the per-process files."""
    rt_, backend, session = two_node_logged
    head = backend.head
    from ray_tpu.util import log_plane

    # drive one task so workers exist and have logged something
    @rt_.remote(num_cpus=1)
    def touch():
        print("role-check line")
        return True

    assert rt_.get(touch.remote(), timeout=60)
    log_plane.get_logger().info("driver marker record")

    roles = set()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        d = head.call("logs_dump", {}, timeout=10)
        roles = {r["role"] for r in d["records"]}
        if {"head", "worker", "driver"} <= roles:
            break
        time.sleep(0.3)
    assert {"head", "worker", "driver"} <= roles, roles

    log_dir = log_plane.session_log_dir(session)
    names = os.listdir(log_dir)
    assert "head.log" in names, names
    assert any(n.startswith("node-") and n.endswith(".log")
               for n in names), names
    assert any(n.startswith("worker-") and n.endswith(".log")
               for n in names), names
    assert any(n.startswith("worker-") and n.endswith(".err")
               for n in names), names
    assert any(n.startswith("worker-") and n.endswith(".out")
               for n in names), names
    # head.log is JSON-lines structured records
    with open(os.path.join(log_dir, "head.log")) as f:
        first = f.readline()
    rec = json.loads(first)
    assert rec["role"] == "head" and "ts" in rec and "level" in rec


def test_logs_cli_smoke(two_node_logged):
    """`ray_tpu logs` renders records; filters and --follow work."""
    from ray_tpu.scripts import cli

    rt_, backend, _session = two_node_logged
    address = backend.head_addr

    @rt_.remote(num_cpus=1)
    def emit():
        from ray_tpu.util import log_plane, trace_context
        print("cli-smoke-line")
        ctx = trace_context.current()
        return ctx[0] if ctx else ""

    tid = rt_.get(emit.remote(), timeout=60)
    _dump_until(backend.head, {"grep": "cli-smoke-line"},
                lambda d: d["records"])

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", "--address", address]) == 0
    out = buf.getvalue()
    assert "cli-smoke-line" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", "--grep", "cli-smoke",
                         "--trace", tid, "--address", address]) == 0
    out = buf.getvalue()
    assert "cli-smoke-line" in out and f"trace={tid[:12]}" in out

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", "--level", "error", "--grep",
                         "cli-smoke-line", "--address", address]) == 0
    assert "cli-smoke-line" not in buf.getvalue()  # it was info-level

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", "--format", "json",
                         "--limit", "5", "--address", address]) == 0
    data = json.loads(buf.getvalue())
    assert len(data["records"]) <= 5 and "last_seq" in data

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli.main(["logs", "--follow", "--interval", "0.05",
                         "--frames", "2", "--address", address]) == 0
    assert buf.getvalue()  # follow rendered at least something
