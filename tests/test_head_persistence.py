"""Head KV durability (reference role: GCS persistence via Redis,
store_client/redis_store_client.h — scoped to the KV/jobs tables: a
restarted head serves the previous KV; actors/leases are process state
and do not survive)."""

import os
import signal
import time

import pytest

from ray_tpu.runtime.cluster_backend import start_head
from ray_tpu.runtime.protocol import RpcClient


def test_kv_survives_head_restart(tmp_path):
    persist = str(tmp_path / "gcs_state.pkl")
    proc, addr = start_head("persistA", persist_path=persist)
    try:
        c = RpcClient(addr, name="t")
        c.call("kv_put", {"key": "job:j1:status", "value": b"SUCCEEDED"})
        c.call("kv_put", {"key": "cfg", "value": b"v1"})
        c.call("kv_del", {"key": "cfg"})
        # force a flush: the persist loop runs every 1s
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not os.path.exists(persist):
            time.sleep(0.2)
        c.close()
    finally:
        os.kill(proc.pid, signal.SIGTERM)
        proc.wait(timeout=10)

    proc2, addr2 = start_head("persistB", persist_path=persist)
    try:
        c2 = RpcClient(addr2, name="t2")
        assert c2.call("kv_get", {"key": "job:j1:status"}) == b"SUCCEEDED"
        assert c2.call("kv_get", {"key": "cfg"}) is None
        c2.close()
    finally:
        os.kill(proc2.pid, signal.SIGTERM)
        proc2.wait(timeout=10)
