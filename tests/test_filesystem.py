"""StorageFilesystem seam: local/memory backends, retry policy, fault
points, and the resolver (ISSUE 14 tentpole part 1)."""

import os
import threading

import pytest

from ray_tpu.util.filesystem import (FaultInjectableFilesystem,
                                     LocalFilesystem, MemoryFilesystem,
                                     RetryPolicy, StorageError,
                                     storage_filesystem)


class TestLocalFilesystem:
    def test_put_get_roundtrip_and_overwrite(self, tmp_path):
        fs = LocalFilesystem()
        p = str(tmp_path / "a" / "b.bin")
        fs.put(p, b"one")
        assert fs.get(p) == b"one"
        fs.put(p, b"two")
        assert fs.get(p) == b"two"

    def test_put_is_atomic_no_staging_left(self, tmp_path):
        fs = LocalFilesystem()
        fs.put(str(tmp_path / "x"), b"data")
        assert sorted(os.listdir(tmp_path)) == ["x"]  # no .tmp.* debris

    def test_get_missing_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            LocalFilesystem().get(str(tmp_path / "nope"))

    def test_list_delete_rename(self, tmp_path):
        fs = LocalFilesystem()
        fs.put(str(tmp_path / "d" / "one"), b"1")
        fs.put(str(tmp_path / "d" / "two"), b"2")
        assert fs.list(str(tmp_path / "d")) == ["one", "two"]
        assert fs.list(str(tmp_path / "missing")) == []
        fs.rename(str(tmp_path / "d" / "one"), str(tmp_path / "d" / "uno"))
        assert fs.list(str(tmp_path / "d")) == ["two", "uno"]
        fs.delete(str(tmp_path / "d"))  # whole-tree delete
        assert fs.list(str(tmp_path / "d")) == []
        fs.delete(str(tmp_path / "d"))  # absent path is a no-op


class TestMemoryFilesystem:
    def test_roundtrip_list_exists(self):
        fs = MemoryFilesystem()
        fs.put("/run/ck/one", b"1")
        fs.put("/run/ck/sub/two", b"2")
        assert fs.get("run/ck/one") == b"1"
        assert fs.list("/run/ck") == ["one", "sub"]
        assert fs.exists("/run/ck/sub")  # "directory" prefix exists
        with pytest.raises(FileNotFoundError):
            fs.get("/run/ck/three")

    def test_delete_tree_and_rename(self):
        fs = MemoryFilesystem()
        fs.put("a/x", b"1")
        fs.put("a/y/z", b"2")
        fs.rename("a", "b")
        assert fs.get("b/x") == b"1" and fs.get("b/y/z") == b"2"
        fs.delete("b")
        assert fs.list("b") == []
        with pytest.raises(FileNotFoundError):
            fs.rename("gone", "anywhere")

    def test_put_copies_bytes(self):
        fs = MemoryFilesystem()
        buf = bytearray(b"abc")
        fs.put("k", buf)
        buf[0] = ord("z")
        assert fs.get("k") == b"abc"


class TestRetryPolicy:
    def test_backoff_is_bounded_full_jitter(self):
        rp = RetryPolicy(max_attempts=5, base_s=0.1, cap_s=0.3)
        for attempt in range(1, 10):
            for _ in range(20):
                s = rp.backoff_s(attempt)
                assert 0.0 <= s <= min(0.3, 0.1 * 2 ** attempt)


class TestFaultInjectableFilesystem:
    def test_transient_faults_are_retried(self, fault_injector):
        fs = FaultInjectableFilesystem(
            MemoryFilesystem(), retry=RetryPolicy(max_attempts=4,
                                                  base_s=0.001, cap_s=0.002))
        fault_injector.configure("storage.put=raise*2")  # fail, fail, ok
        fs.put("k", b"v")
        assert fs.get("k") == b"v"

    def test_exhausted_retries_raise_storage_error(self, fault_injector):
        fs = FaultInjectableFilesystem(
            MemoryFilesystem(), retry=RetryPolicy(max_attempts=3,
                                                  base_s=0.001, cap_s=0.002))
        fault_injector.configure("storage.put=raise")  # unlimited
        with pytest.raises(StorageError):
            fs.put("k", b"v")

    def test_absence_is_not_retried(self, fault_injector):
        # FileNotFoundError must pass straight through — retrying a
        # missing object would turn every latest()-probe into a stall
        fs = FaultInjectableFilesystem(MemoryFilesystem())
        with pytest.raises(FileNotFoundError):
            fs.get("never-put")

    def test_get_point_covers_reads(self, fault_injector):
        fs = FaultInjectableFilesystem(
            MemoryFilesystem(), retry=RetryPolicy(max_attempts=2,
                                                  base_s=0.001, cap_s=0.002))
        fs.put("k", b"v")
        fault_injector.configure("storage.get=raise")
        with pytest.raises(StorageError):
            fs.get("k")


class TestResolver:
    def test_default_is_fault_injectable_local(self):
        fs = storage_filesystem(None)
        assert isinstance(fs, FaultInjectableFilesystem)
        assert isinstance(fs.inner, LocalFilesystem)

    def test_memory_spec_is_process_shared(self):
        a = storage_filesystem("memory://shared-x")
        b = storage_filesystem("memory://shared-x")
        a.put("k", b"v")
        assert b.get("k") == b"v"  # same named store
        other = storage_filesystem("memory://other")
        with pytest.raises(FileNotFoundError):
            other.get("k")

    def test_instance_passthrough(self):
        mem = MemoryFilesystem()
        assert storage_filesystem(mem) is mem

    def test_concurrent_memory_puts(self):
        fs = storage_filesystem("memory://concurrent")
        errs = []

        def work(i):
            try:
                for j in range(50):
                    fs.put(f"d/{i}-{j}", bytes([i]))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=work, args=(i,)) for i in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs
        assert len(fs.list("d")) == 400
