"""Serve model multiplexing: LRU model cache per replica, request
tagging, and model-aware routing (reference: serve/multiplex.py,
handle option multiplexed_model_id, pow-2 scheduler candidate
preference for multiplexed requests)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.multiplex import multiplexed, _MultiplexedDescriptor


@pytest.fixture(scope="module")
def serve_rt():
    ray_tpu.init(num_cpus=8)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


# ---------------------------------------------------------- unit: cache

class _Loader:
    """Plain object standing in for a deployment instance."""

    def __init__(self):
        self.loads = []

    @multiplexed(max_num_models_per_replica=2)
    def load(self, model_id: str):
        self.loads.append(model_id)
        return {"id": model_id}


def test_multiplexed_lru_eviction():
    host = _Loader()
    assert host.load("a")["id"] == "a"
    assert host.load("b")["id"] == "b"
    assert host.load("a")["id"] == "a"      # hit — no reload
    assert host.loads == ["a", "b"]
    host.load("c")                          # evicts LRU = "b"
    assert host.load("a")["id"] == "a"      # still cached
    assert host.loads == ["a", "b", "c"]
    host.load("b")                          # reload after eviction
    assert host.loads == ["a", "b", "c", "b"]
    assert set(host.load.cache.model_ids()) == {"a", "b"}
    assert host.load.cache.evict_count == 2


def test_multiplexed_plain_function():
    calls = []

    @multiplexed(max_num_models_per_replica=1)
    def load(model_id: str):
        calls.append(model_id)
        return model_id.upper()

    assert load("x") == "X"
    assert load("x") == "X"
    assert calls == ["x"]
    load("y")                               # evicts x (max=1)
    load("x")
    assert calls == ["x", "y", "x"]


def test_multiplexed_eager_teardown():
    died = []

    class Model:
        def __init__(self, mid):
            self.mid = mid

        def __del__(self):
            died.append(self.mid)

    @multiplexed(max_num_models_per_replica=1)
    def load(model_id: str):
        return Model(model_id)

    m1 = load("one")
    load("two")
    # eviction of "one" calls its __del__ eagerly even while we still
    # hold m1 (reference behavior: free accelerator memory NOW)
    assert "one" in died
    del m1


def test_multiplexed_rejects_bad_config():
    with pytest.raises(ValueError):
        multiplexed(max_num_models_per_replica=0)(lambda mid: mid)


# ------------------------------------------------- cluster: serve routing

@serve.deployment(num_replicas=2)
class MuxServer:
    def __init__(self):
        # worker id is unique per replica process — a usable replica tag
        self.replica_tag = ray_tpu.get_runtime_context().worker_id.hex()[:8]

    @serve.multiplexed(max_num_models_per_replica=2)
    def load(self, model_id: str):
        return {"model": model_id, "loaded_on": self.replica_tag}

    def __call__(self, body):
        mid = serve.get_multiplexed_model_id()
        model = self.load(mid)
        return {"model_id": mid, "replica": self.replica_tag,
                "loaded_on": model["loaded_on"]}


def test_multiplex_routing_affinity(serve_rt):
    handle = serve.run(MuxServer.bind())
    # first touch establishes each model's home replica
    homes = {}
    for mid in ("m1", "m2"):
        out = handle.options(multiplexed_model_id=mid).remote(mid) \
            .result(timeout=60)
        assert out["model_id"] == mid
        homes[mid] = out["replica"]
    # repeated traffic for a model sticks to its home replica
    for _ in range(6):
        for mid in ("m1", "m2"):
            out = handle.options(multiplexed_model_id=mid).remote(mid) \
                .result(timeout=60)
            assert out["replica"] == homes[mid], \
                f"{mid} moved from {homes[mid]} to {out['replica']}"
    # untagged requests still route (no affinity involved)
    out = handle.remote("untagged").result(timeout=60)
    assert out["model_id"] == ""


def test_multiplex_model_ids_in_stats(serve_rt):
    # the deployment from the previous test is still running
    st = serve.status()
    assert "MuxServer" in st
    handle = serve.get_app_handle("MuxServer")
    ctrl = handle._controller
    table = ray_tpu.get(ctrl.get_routing_table.remote("MuxServer"),
                        timeout=30)
    ids = set()
    for h in table["replicas"]:
        s = ray_tpu.get(h.stats.remote(), timeout=30)
        ids.update(s.get("multiplexed_model_ids", []))
    assert {"m1", "m2"} <= ids


def test_multiplex_descriptor_detected():
    assert isinstance(
        type(_Loader.__dict__["load"]), type) or True
    assert isinstance(_Loader.__dict__["load"], _MultiplexedDescriptor)


# --------------------------------------------- batching x multiplexing

@serve.deployment(num_replicas=1)
class BatchedMux:
    @serve.multiplexed(max_num_models_per_replica=4)
    def load(self, model_id: str):
        return model_id.upper()

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
    def predict(self, bodies):
        # runs on the batcher thread: the model id must still resolve,
        # and every request in one batch shares it by construction
        mid = serve.get_multiplexed_model_id()
        w = self.load(mid)
        return [{"model_id": mid, "weights": w, "n": len(bodies)}
                for _ in bodies]

    def __call__(self, body):
        return self.predict(body)


def test_batch_partitions_by_model_id(serve_rt):
    handle = serve.run(BatchedMux.bind())
    from concurrent.futures import ThreadPoolExecutor

    def call(mid):
        return handle.options(multiplexed_model_id=mid).remote({}) \
            .result(timeout=60)

    with ThreadPoolExecutor(max_workers=8) as ex:
        outs = list(ex.map(call, ["a", "b", "a", "b", "a", "b", "a", "b"]))
    for out in outs:
        # the batched fn saw the request's own model id — never another
        # model's (queues are partitioned per model id)
        assert out["weights"] == out["model_id"].upper()
    mids = {o["model_id"] for o in outs}
    assert mids == {"a", "b"}
    # batching still coalesced concurrent same-model requests
    assert any(o["n"] > 1 for o in outs), [o["n"] for o in outs]
