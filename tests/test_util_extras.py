"""util extras: ActorPool + distributed Queue (reference:
python/ray/util/actor_pool.py, util/queue.py)."""

import pytest

import ray_tpu as rt
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Queue


@pytest.fixture(scope="module")
def local_rt():
    rt.init(local_mode=True, num_cpus=4)
    yield rt
    rt.shutdown()


def test_actor_pool_ordered_and_unordered(local_rt):
    @rt.remote
    class Sq:
        def compute(self, x):
            return x * x

    pool = ActorPool([Sq.remote() for _ in range(2)])
    out = pool.map(lambda a, v: a.compute.remote(v), [1, 2, 3, 4, 5])
    assert out == [1, 4, 9, 16, 25]
    got = sorted(pool.map_unordered(
        lambda a, v: a.compute.remote(v), [2, 3, 4]))
    assert got == [4, 9, 16]


def test_queue_fifo_and_limits(local_rt):
    q = Queue(maxsize=2)
    q.put("a")
    q.put("b")
    from ray_tpu.util.queue import Full
    with pytest.raises(Full):
        q.put("c", block=False)
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get_nowait()
    assert q.empty()


def test_queue_across_tasks(local_rt):
    q = Queue()

    @rt.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    assert rt.get(producer.remote(q, 5), timeout=60)
    assert [q.get(timeout=10) for _ in range(5)] == [0, 1, 2, 3, 4]
