"""Memory monitor + OOM worker killing (reference:
src/ray/common/memory_monitor.h:52 RSS polling;
raylet/worker_killing_policy_retriable_fifo.h victim policy; death cause
propagated into the task error)."""

import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.exceptions import OutOfMemoryError


@pytest.fixture()
def oom_rt():
    rt.init(num_cpus=2, _system_config={
        "object_store_memory_bytes": 64 * 1024 * 1024,
        # deterministic per-worker cap: a worker whose RSS exceeds 400 MB
        # is OOM-killed regardless of actual host pressure
        "worker_memory_limit_bytes": 400 * 1024 * 1024,
        "memory_monitor_refresh_ms": 100,
    })
    yield rt
    rt.shutdown()


def test_memory_hog_killed_with_oom_error(oom_rt):
    @rt.remote(max_retries=0)
    def hog():
        import time
        ballast = np.ones(120_000_000)  # ~960 MB, far over the cap
        time.sleep(30)
        return ballast.nbytes

    with pytest.raises(OutOfMemoryError):
        rt.get(hog.remote(), timeout=90)


def test_oom_retry_completes_elsewhere(oom_rt, tmp_path):
    marker = str(tmp_path / "attempted")

    @rt.remote(max_retries=2)
    def flaky_hog():
        import time
        if not os.path.exists(marker):
            open(marker, "w").close()
            ballast = np.ones(120_000_000)  # first attempt hogs -> killed
            time.sleep(30)
            return -1
        return 42  # retry is frugal and completes

    assert rt.get(flaky_hog.remote(), timeout=120) == 42


def test_frugal_workload_untouched(oom_rt):
    @rt.remote
    def modest(i):
        return i * 2

    assert rt.get([modest.remote(i) for i in range(8)], timeout=60) == \
        [i * 2 for i in range(8)]
