"""C++ shm store + scheduler unit tests (interface-seamed, no cluster).

Mirrors the reference's colocated C++ unit test strategy (SURVEY.md §4.1 —
plasma tests at src/ray/object_manager/plasma/test/, scheduler policy tests
at src/ray/raylet/scheduling/*_test.cc) at the binding layer.
"""

import os

import pytest

from ray_tpu.core._native import (ClusterState, ObjectExists, ObjectStoreFull,
                                  ShmStore)


@pytest.fixture
def store():
    name = f"/rtpu_test_{os.getpid()}"
    s = ShmStore.create(name, 8 * 1024 * 1024, slots=1024)
    yield s
    s.close()
    ShmStore.attach(name).unlink()


def _oid(i: int) -> bytes:
    return i.to_bytes(28, "little")


class TestShmStore:
    def test_put_get_roundtrip(self, store):
        store.put(_oid(1), b"abc" * 1000)
        view = store.get(_oid(1))
        assert bytes(view[:3000]) == b"abc" * 1000
        store.release(_oid(1))

    def test_get_missing_returns_none(self, store):
        assert store.get(_oid(99)) is None

    def test_unsealed_invisible(self, store):
        buf = store.create_object(_oid(2), 100)
        assert store.get(_oid(2)) is None
        assert not store.contains(_oid(2))
        store.seal(_oid(2))
        assert store.contains(_oid(2))

    def test_duplicate_create_raises(self, store):
        store.put(_oid(3), b"x")
        with pytest.raises(ObjectExists):
            store.create_object(_oid(3), 10)

    def test_zero_copy_write(self, store):
        buf = store.create_object(_oid(4), 8)
        memoryview(buf).cast("B")[:] = b"12345678"
        store.seal(_oid(4))
        v = store.get(_oid(4))
        assert bytes(v[:8]) == b"12345678"
        store.release(_oid(4))

    def test_cross_attach_visibility(self, store):
        store.put(_oid(5), b"shared")
        other = ShmStore.attach(store.name)
        v = other.get(_oid(5))
        assert bytes(v[:6]) == b"shared"
        other.release(_oid(5))
        other.close()

    def test_delete_pinned_is_deferred(self, store):
        store.put(_oid(6), b"pinned")  # creator pin still held
        assert not store.delete(_oid(6))  # -> delete_pending
        assert store.contains(_oid(6))
        store.release(_oid(6))  # last pin drops -> deleted
        assert not store.contains(_oid(6))

    def test_eviction_under_pressure(self, store):
        blob = b"z" * (1024 * 1024)
        for i in range(20):
            store.put(_oid(100 + i), blob)
            store.release(_oid(100 + i))  # unpinned -> evictable
        stats = store.stats()
        assert stats["total_evicted"] > 0
        # most recent objects survive
        assert store.contains(_oid(119))

    def test_pinned_objects_never_evicted(self, store):
        blob = b"z" * (1024 * 1024)
        store.put(_oid(50), blob)  # keep creator pin
        for i in range(20):
            store.put(_oid(200 + i), blob)
            store.release(_oid(200 + i))
        assert store.contains(_oid(50))

    def test_store_full_when_all_pinned(self, store):
        blob = b"z" * (1024 * 1024)
        with pytest.raises(ObjectStoreFull):
            for i in range(20):
                store.put(_oid(300 + i), blob)  # all pinned

    def test_stats(self, store):
        store.put(_oid(7), b"abc")
        st = store.stats()
        assert st["num_objects"] == 1
        assert st["total_created"] == 1
        assert st["capacity"] == 8 * 1024 * 1024


class TestClusterState:
    def test_schedule_respects_feasibility(self):
        c = ClusterState()
        c.add_node("n1", {"CPU": 4})
        c.add_node("n2", {"CPU": 4, "TPU": 8})
        assert c.schedule({"TPU": 4}) == "n2"
        assert c.schedule({"GPU": 1}) is None

    def test_hybrid_packs_then_spreads(self):
        c = ClusterState()
        c.add_node("a", {"CPU": 10})
        c.add_node("b", {"CPU": 10})
        # first task: both empty — picks one; acquire and check consolidation
        first = c.schedule({"CPU": 1})
        assert c.acquire(first, {"CPU": 1})
        second = c.schedule({"CPU": 1})
        assert second == first  # pack below threshold

    def test_acquire_release(self):
        c = ClusterState()
        c.add_node("n", {"CPU": 2})
        assert c.acquire("n", {"CPU": 2})
        assert c.schedule({"CPU": 1}) is None
        c.release("n", {"CPU": 2})
        assert c.schedule({"CPU": 1}) == "n"

    def test_fractional_resources(self):
        c = ClusterState()
        c.add_node("n", {"CPU": 1})
        for _ in range(4):
            assert c.acquire("n", {"CPU": 0.25})
        assert c.schedule({"CPU": 0.25}) is None

    def test_strict_spread_distinct_nodes(self):
        c = ClusterState()
        c.add_node("x", {"CPU": 4})
        c.add_node("y", {"CPU": 4})
        c.add_node("z", {"CPU": 4})
        nodes = c.schedule_bundles([{"CPU": 2}] * 3, "STRICT_SPREAD")
        assert sorted(nodes) == ["x", "y", "z"]

    def test_strict_spread_infeasible(self):
        c = ClusterState()
        c.add_node("x", {"CPU": 4})
        assert c.schedule_bundles([{"CPU": 2}] * 2, "STRICT_SPREAD") is None

    def test_strict_pack_one_node(self):
        c = ClusterState()
        c.add_node("x", {"CPU": 2})
        c.add_node("y", {"CPU": 8})
        nodes = c.schedule_bundles([{"CPU": 3}, {"CPU": 3}], "STRICT_PACK")
        assert nodes == ["y", "y"]

    def test_bundles_all_or_nothing(self):
        c = ClusterState()
        c.add_node("x", {"CPU": 4})
        before = c.schedule({"CPU": 4})  # feasible now
        assert before == "x"
        assert c.schedule_bundles([{"CPU": 3}, {"CPU": 3}], "PACK") is None
        # nothing was deducted
        assert c.schedule({"CPU": 4}) == "x"

    def test_node_affinity(self):
        from ray_tpu.core._native import POLICY_NODE_AFFINITY
        c = ClusterState()
        c.add_node("n1", {"CPU": 4})
        c.add_node("n2", {"CPU": 4})
        assert c.schedule({"CPU": 1}, POLICY_NODE_AFFINITY, "n2") == "n2"
        c.acquire("n2", {"CPU": 4})
        # hard affinity fails, soft falls back
        assert c.schedule({"CPU": 1}, POLICY_NODE_AFFINITY, "n2") is None
        assert c.schedule({"CPU": 1}, POLICY_NODE_AFFINITY, "n2",
                          soft=True) == "n1"

    def test_remove_node(self):
        c = ClusterState()
        c.add_node("n1", {"CPU": 4})
        c.remove_node("n1")
        assert c.schedule({"CPU": 1}) is None
        assert c.num_nodes() == 0

    def test_tpu_gang_resources(self):
        # TPU slice head resource pattern (reference: accelerators/tpu.py:330)
        c = ClusterState()
        c.add_node("host0", {"CPU": 8, "TPU": 4, "TPU-v5p-16-head": 1})
        c.add_node("host1", {"CPU": 8, "TPU": 4})
        assert c.schedule({"TPU-v5p-16-head": 1}) == "host0"
