"""Placement groups, TPU gang resources, chip allocation.

Reference coverage model: python/ray/tests/test_placement_group*.py plus
the TPU accelerator-manager unit tests
(python/ray/tests/accelerators/test_tpu.py).
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.accelerators.tpu import ChipAllocator, TPUAcceleratorManager
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import placement_group, remove_placement_group


# ------------------------------------------------------- unit: TPU manager


def test_tpu_resources_from_env(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-16")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    res = TPUAcceleratorManager.node_resources()
    assert res["TPU"] == 4.0           # v5p hosts carry 4 chips
    assert res["TPU-v5p"] == 4.0
    assert res["TPU-v5p-16-head"] == 1.0  # gang resource on worker 0


def test_tpu_resources_non_head_worker(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-16")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    res = TPUAcceleratorManager.node_resources()
    assert "TPU-v5p-16-head" not in res
    assert res["TPU"] == 4.0


def test_tpu_v5e_chips(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    res = TPUAcceleratorManager.node_resources()
    assert res["TPU"] == 8.0


def test_chip_request_validation():
    TPUAcceleratorManager.validate_chip_request(4)
    with pytest.raises(ValueError):
        TPUAcceleratorManager.validate_chip_request(3)


def test_visibility_env():
    env = TPUAcceleratorManager.visibility_env([0, 1, 2, 3])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1,2,3"
    assert env["TPU_CHIPS_PER_HOST_BOUNDS"] == "2,2,1"


def test_chip_allocator():
    alloc = ChipAllocator(4)
    a = alloc.allocate(b"w1", 2)
    b = alloc.allocate(b"w2", 2)
    assert sorted(a + b) == [0, 1, 2, 3]
    assert alloc.allocate(b"w3", 1) is None
    alloc.release(b"w1")
    assert alloc.allocate(b"w3", 2) == a


def test_multislice_pg_one_bundle_per_slice():
    """Multi-slice job placement: one slice-head gang bundle PER SLICE
    lands on distinct slices atomically — the placement half of the
    ICI x DCN hybrid mesh (parallel/mesh.py MeshSpec.dcn_dp: dp/pp span
    slices over DCN, so a 2-slice job reserves 2 whole slices)."""
    cluster = Cluster()
    cluster.add_node(num_cpus=1, resources={
        "TPU": 4, "TPU-v5e": 4, "TPU-v5e-8-head": 1})
    cluster.add_node(num_cpus=1, resources={
        "TPU": 4, "TPU-v5e": 4, "TPU-v5e-8-head": 1})
    rt.init(address=cluster.address)
    try:
        pg = placement_group(
            [{"TPU-v5e-8-head": 1}, {"TPU-v5e-8-head": 1}],
            strategy="STRICT_SPREAD")
        assert pg.wait(30)
        nodes = pg.state()["nodes"]
        assert len(set(nodes)) == 2  # one bundle per slice
        # both slices are now taken: a third slice reservation queues
        pg2 = placement_group([{"TPU-v5e-8-head": 1}],
                              strategy="STRICT_PACK")
        assert not pg2.wait(1.5)
        remove_placement_group(pg)
        assert pg2.wait(30)
        remove_placement_group(pg2)
    finally:
        rt.shutdown()
        cluster.shutdown()


# ------------------------------------------------- cluster: PG semantics


@pytest.fixture(scope="module")
def pg_cluster():
    cluster = Cluster()
    cluster.add_node(num_cpus=2, resources={
        "nodeA": 1, "TPU": 4, "TPU-v5p": 4, "TPU-v5p-8-head": 1})
    cluster.add_node(num_cpus=2, resources={
        "nodeB": 1, "TPU": 4, "TPU-v5p": 4})
    rt.init(address=cluster.address)
    yield cluster
    rt.shutdown()
    cluster.shutdown()


def test_pg_pack_ready(pg_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(30)
    st = pg.state()
    assert st["state"] == "CREATED"
    # PACK prefers one node for both bundles
    assert len(set(st["nodes"])) == 1
    remove_placement_group(pg)


def test_pg_strict_spread_lands_on_distinct_nodes(pg_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30)
    nodes = pg.state()["nodes"]
    assert len(set(nodes)) == 2
    remove_placement_group(pg)


def test_pg_infeasible_stays_pending(pg_cluster):
    """PG-or-nothing: 3 STRICT_SPREAD bundles on 2 nodes can never all
    reserve — the PG must stay PENDING, not partially place."""
    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert not pg.wait(2)
    assert pg.state()["state"] == "PENDING"
    remove_placement_group(pg)


def test_pg_queues_until_resources_free(pg_cluster):
    """A pending PG is created once a blocking one is removed."""
    first = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="STRICT_SPREAD")
    assert first.wait(30)
    second = placement_group([{"CPU": 2}], strategy="PACK")
    assert not second.wait(1.5)
    remove_placement_group(first)
    assert second.wait(30), "queued PG never created after resources freed"
    remove_placement_group(second)


def test_task_runs_in_bundle(pg_cluster):
    """A task submitted into bundle 1 of a STRICT_SPREAD PG runs on the
    bundle's node."""
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(30)
    target = pg.bundle_node(1)

    @rt.remote(placement_group=pg, placement_group_bundle_index=1, num_cpus=0)
    def where():
        from ray_tpu.core.worker import global_worker
        return global_worker.node_id

    assert rt.get(where.remote(), timeout=60) == target
    remove_placement_group(pg)


def test_actor_in_pg_bundle(pg_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(30)

    @rt.remote
    class Where:
        def node(self):
            from ray_tpu.core.worker import global_worker
            return global_worker.node_id

    a = Where.options(placement_group=pg,
                      placement_group_bundle_index=0).remote()
    assert rt.get(a.node.remote(), timeout=60) == pg.bundle_node(0)
    rt.kill(a)
    remove_placement_group(pg)


def test_tpu_gang_reservation(pg_cluster):
    """A single-bundle PG on the slice-head gang resource claims the slice
    atomically: only node A advertises TPU-v5p-8-head (SURVEY.md §2.6 gang
    scheduling row; reference accelerators/tpu.py:330,377)."""
    pg = placement_group([{"TPU-v5p-8-head": 1}], strategy="STRICT_PACK")
    assert pg.wait(30)
    nodes = rt.nodes()
    head_node = next(n["NodeID"] for n in nodes
                     if "TPU-v5p-8-head" in n["Resources"])
    assert pg.state()["nodes"][0] == head_node
    # a second gang reservation must queue (the slice is taken)
    pg2 = placement_group([{"TPU-v5p-8-head": 1}], strategy="STRICT_PACK")
    assert not pg2.wait(1.5)
    remove_placement_group(pg)
    assert pg2.wait(30)
    remove_placement_group(pg2)
